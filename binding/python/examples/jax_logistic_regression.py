"""Distributed softmax regression with the drop-in multiverso binding.

The JAX twin of the reference binding's theano example
(ref: binding/python/examples/theano/logistic_regression.py): every
worker trains on its own shard of the data, and a ``JaxParamManager``
syncs the whole parameter pytree through one ArrayTable after every
batch (ASGD model averaging; ``sync_every_n`` relaxes the cadence).

Run it single-process (one worker is worker+server)::

    python jax_logistic_regression.py

or as N virtual workers in one process::

    python jax_logistic_regression.py -workers=4
"""

import sys

import numpy as np


def make_data(seed=0, n=4096, d=64, classes=10):
    rng = np.random.default_rng(seed)
    w_true = rng.standard_normal((d, classes))
    x = rng.standard_normal((n, d)).astype(np.float32)
    y = (x @ w_true).argmax(axis=1)
    return x, y


def train_worker(rank: int, num_workers: int, epochs: int = 15) -> float:
    import jax
    import jax.numpy as jnp

    from multiverso.ext.param_manager import JaxParamManager, SyncEveryN

    x, y = make_data()
    shard = slice(rank, None, num_workers)  # each worker's data shard
    x, y = x[shard], y[shard]

    params = {"w": jnp.zeros((x.shape[1], 10)), "b": jnp.zeros((10,))}

    @jax.jit
    def step(params, xb, yb):
        def loss_fn(p):
            logits = xb @ p["w"] + p["b"]
            logp = jax.nn.log_softmax(logits)
            return -logp[jnp.arange(yb.size), yb].mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, grads), loss

    state = {"params": params}
    manager = JaxParamManager(lambda: state["params"],
                              lambda p: state.__setitem__("params", p))
    sync = SyncEveryN(manager, n=1)

    batch = 256
    for _ in range(epochs):
        for i in range(0, x.shape[0] - batch + 1, batch):
            state["params"], loss = step(
                state["params"], x[i:i + batch], y[i:i + batch])
            sync()  # push delta, pull merged params

    manager.sync_all_param()
    logits = x @ state["params"]["w"] + state["params"]["b"]
    return float((np.asarray(logits).argmax(axis=1) == y).mean())


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    workers = 1
    for a in list(argv):
        if a.startswith("-workers="):
            workers = int(a.split("=", 1)[1])
            argv.remove(a)
    if workers <= 1:
        import multiverso as mv
        mv.init()
        acc = train_worker(0, 1)
        mv.barrier()
        mv.shutdown()
        print(f"accuracy: {acc:.3f}")
        return 0
    from multiverso_tpu.runtime.cluster import LocalCluster

    def body(rank):
        return train_worker(rank, workers)

    accs = LocalCluster(workers).run(body)
    print("per-worker accuracy:", [f"{a:.3f}" for a in accs])
    return 0


if __name__ == "__main__":
    sys.exit(main())
