#!/usr/bin/env python
"""Runnable multi-process distributed training example.

Launches N OS processes over localhost TCP (machine-file bootstrap, the
reference's ZMQ deployment mode — ref: include/multiverso/net/
zmq_net.h:20-61) and trains word2vec through the parameter server: each
worker reads its own shard of the corpus, pulls embedding rows, trains,
and pushes deltas; BSP or async per the ``--sync`` flag. Rank 0 saves
the embeddings and verifies they learned the corpus's two-topic
structure. (The reference ships the same story as theano/lasagne
multi-process examples — ref: binding/python/examples/theano/.)

    python binding/python/examples/distributed_word2vec.py            # 2 procs
    python binding/python/examples/distributed_word2vec.py -n 4 --sync

Runs on any machine — no TPU needed (children force the CPU backend);
on a TPU host the same script uses the chip. Wired into ci.sh as the
distributed-example gate.
"""

import argparse
import os
import subprocess
import sys
import tempfile

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                    "..", "..", ".."))
sys.path.insert(0, REPO)


def make_corpus(path: str, sentences: int = 600, seed: int = 0) -> None:
    """Two disjoint topic vocabularies; words co-occur only within
    their topic — so trained embeddings must cluster by topic."""
    import numpy as np
    rng = np.random.default_rng(seed)
    topics = [[f"a{i}" for i in range(8)], [f"b{i}" for i in range(8)]]
    with open(path, "w") as f:
        for _ in range(sentences):
            topic = topics[rng.integers(0, 2)]
            f.write(" ".join(rng.choice(topic, size=12)) + "\n")


def worker(rank: int) -> None:
    """One training process: machine-file TCP mesh + PS word2vec on
    this rank's corpus shard."""
    import jax
    jax.config.update("jax_platforms", "cpu")  # example runs anywhere
    import numpy as np

    import multiverso_tpu as mv
    from multiverso_tpu.models.wordembedding import (BlockLoader,
                                                     Dictionary,
                                                     PSWord2Vec,
                                                     Word2VecConfig,
                                                     iter_pair_batches)

    argv = [f"-machine_file={os.environ['MV_MACHINE_FILE']}",
            f"-rank={rank}"]
    if os.environ.get("MV_SYNC") == "1":
        argv.append("-sync=true")
    mv.init(argv)
    corpus = os.environ["MV_CORPUS"]
    # Shared dictionary (every rank builds it from the full corpus, as
    # the reference's workers all load the same vocab file).
    dictionary = Dictionary.build(corpus, min_count=1)
    config = Word2VecConfig(embedding_size=16, window=3, epochs=2,
                            init_learning_rate=0.02, batch_size=512,
                            sample=0, use_ps=True)
    model = PSWord2Vec(config, dictionary)
    shard = f"{corpus}.shard{rank}"
    for epoch in range(config.epochs):
        loss, pairs = model.train_batches(BlockLoader(model.prepared(
            iter_pair_batches(dictionary, shard, batch_size=512,
                              window=3, subsample=0, seed=epoch))))
        print(f"rank {rank} epoch {epoch}: "
              f"loss/pair {loss / max(pairs, 1):.4f}", flush=True)
    mv.barrier()
    if rank == 0:
        emb = model.embeddings
        emb = emb / np.maximum(
            np.linalg.norm(emb, axis=1, keepdims=True), 1e-9)
        ids_a = [dictionary.word2id[w] for w in dictionary.words
                 if w.startswith("a")]
        ids_b = [dictionary.word2id[w] for w in dictionary.words
                 if w.startswith("b")]
        sims = emb @ emb.T
        within = (sims[np.ix_(ids_a, ids_a)].mean()
                  + sims[np.ix_(ids_b, ids_b)].mean()) / 2
        across = sims[np.ix_(ids_a, ids_b)].mean()
        sep = float(within - across)
        model.save_embeddings(os.environ["MV_OUTPUT"])
        print(f"rank 0: topic separation {sep:.3f} "
              f"(embeddings -> {os.environ['MV_OUTPUT']})", flush=True)
        assert sep > 0.2, f"embeddings failed to learn topics: {sep}"
    mv.shutdown()


def launch(n: int, sync: bool) -> int:
    from multiverso_tpu.util.net_util import free_listen_port
    tmp = tempfile.mkdtemp(prefix="mv_dist_example_")
    corpus = os.path.join(tmp, "corpus.txt")
    make_corpus(corpus)
    # Shard the corpus round-robin, one shard file per worker (the
    # reference splits input by rank the same way).
    with open(corpus) as f:
        lines = f.readlines()
    for rank in range(n):
        with open(f"{corpus}.shard{rank}", "w") as f:
            f.writelines(lines[rank::n])
    machine_file = os.path.join(tmp, "machines")
    with open(machine_file, "w") as f:
        for _ in range(n):
            f.write(f"127.0.0.1:{free_listen_port()}\n")
    env = dict(os.environ,
               MV_MACHINE_FILE=machine_file,
               MV_CORPUS=corpus,
               MV_OUTPUT=os.path.join(tmp, "vectors.txt"),
               MV_SYNC="1" if sync else "0",
               PYTHONPATH=REPO)
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--rank", str(rank)],
        env=env) for rank in range(n)]
    rc = 0
    for rank, p in enumerate(procs):
        try:
            p.wait(timeout=600)
        except subprocess.TimeoutExpired:
            p.kill()
            print(f"rank {rank} timed out", file=sys.stderr)
            rc = 1
        rc = rc or p.returncode
    print("distributed example:", "OK" if rc == 0 else "FAILED")
    return rc


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--processes", type=int, default=2)
    ap.add_argument("--sync", action="store_true",
                    help="BSP mode (-sync=true) instead of async")
    ap.add_argument("--rank", type=int, default=None,
                    help=argparse.SUPPRESS)  # internal: worker role
    args = ap.parse_args()
    if args.rank is not None:
        worker(args.rank)
        return 0
    return launch(args.processes, args.sync)


if __name__ == "__main__":
    sys.exit(main())
