"""Multi-worker torch MLP with the drop-in multiverso binding.

The torch twin of the reference binding's keras/lasagne examples
(ref: binding/python/examples/theano/keras, lasagne): a plain
``torch.nn`` model trains per-worker shards while ``TorchParamManager``
syncs all parameters through one ArrayTable; ``SyncEveryN`` mirrors the
keras callback's every-N-batches cadence
(ref: keras_ext/callbacks.py:8-39).

Run::

    python torch_mlp.py            # single process
    python torch_mlp.py -workers=4 # N virtual workers, one process
"""

import sys

import numpy as np


def make_data(seed=0, n=2048, d=32):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    y = (np.cos(x[:, 0]) * x[:, 1] > 0).astype(np.int64)
    return x, y


def train_worker(rank: int, num_workers: int, epochs: int = 12) -> float:
    import torch

    from multiverso.ext.param_manager import SyncEveryN, TorchParamManager

    torch.manual_seed(7)  # identical init on every worker
    x, y = make_data()
    shard = slice(rank, None, num_workers)
    xt = torch.from_numpy(x[shard])
    yt = torch.from_numpy(y[shard])

    model = torch.nn.Sequential(
        torch.nn.Linear(x.shape[1], 64), torch.nn.ReLU(),
        torch.nn.Linear(64, 2))
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    loss_fn = torch.nn.CrossEntropyLoss()

    manager = TorchParamManager(model)
    sync = SyncEveryN(manager, n=4)

    batch = 128
    for _ in range(epochs):
        for i in range(0, xt.shape[0] - batch + 1, batch):
            opt.zero_grad()
            loss = loss_fn(model(xt[i:i + batch]), yt[i:i + batch])
            loss.backward()
            opt.step()
            sync()

    manager.sync_all_param()
    with torch.no_grad():
        acc = (model(xt).argmax(dim=1) == yt).float().mean().item()
    return acc


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    workers = 1
    for a in list(argv):
        if a.startswith("-workers="):
            workers = int(a.split("=", 1)[1])
            argv.remove(a)
    if workers <= 1:
        import multiverso as mv
        mv.init()
        acc = train_worker(0, 1)
        mv.barrier()
        mv.shutdown()
        print(f"accuracy: {acc:.3f}")
        return 0
    from multiverso_tpu.runtime.cluster import LocalCluster

    accs = LocalCluster(workers).run(
        lambda rank: train_worker(rank, workers))
    print("per-worker accuracy:", [f"{a:.3f}" for a in accs])
    return 0


if __name__ == "__main__":
    sys.exit(main())
