"""Table handlers (ref: binding/python/multiverso/tables.py:38-165).

Float32 numpy marshalling and the master-init convention preserved: when
``init_value`` is given, every worker performs a synchronous add — the
master adds the value, the rest add zeros — so initialization also lines
up the BSP clocks in sync mode (ref: tables.py:52-58).
"""

from __future__ import annotations

import numpy as np

import multiverso_tpu as _mv

from . import api


def _convert(data) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(data, dtype=np.float32))


class TableHandler:
    def __init__(self, size, init_value=None):
        raise NotImplementedError

    def get(self):
        raise NotImplementedError

    def add(self, data, sync=False):
        raise NotImplementedError


class ArrayTableHandler(TableHandler):
    """Sync a one-dimensional float array."""

    def __init__(self, size: int, init_value=None):
        self._size = int(size)
        self._table = _mv.create_array_table(self._size, dtype=np.float32)
        if init_value is not None:
            init_value = _convert(init_value)
            self.add(init_value if api.is_master_worker()
                     else np.zeros(init_value.shape, np.float32), sync=True)

    def get(self) -> np.ndarray:
        out = np.zeros(self._size, dtype=np.float32)
        self._table.get(out=out)
        return out

    def add(self, data, sync: bool = False) -> None:
        data = _convert(data).reshape(-1)
        assert data.size == self._size
        if sync:
            self._table.add(data)
        else:
            self._table.add_async(data.copy())


class MatrixTableHandler(TableHandler):
    """Sync a two-dimensional float matrix, whole or by rows."""

    def __init__(self, num_row: int, num_col: int, init_value=None):
        self._num_row, self._num_col = int(num_row), int(num_col)
        self._size = self._num_row * self._num_col
        self._table = _mv.create_matrix_table(self._num_row, self._num_col,
                                              dtype=np.float32)
        if init_value is not None:
            init_value = _convert(init_value)
            self.add(init_value if api.is_master_worker()
                     else np.zeros(init_value.shape, np.float32), sync=True)

    def get(self, row_ids=None) -> np.ndarray:
        if row_ids is None:
            out = np.zeros((self._num_row, self._num_col), np.float32)
            self._table.get(out=out)
            return out
        row_ids = np.asarray(list(row_ids), dtype=np.int32)
        out = np.zeros((row_ids.size, self._num_col), np.float32)
        self._table.get_rows(row_ids, out=out)
        return out

    def add(self, data=None, row_ids=None, sync: bool = False) -> None:
        assert data is not None
        data = _convert(data)
        if row_ids is None:
            assert data.size == self._size
            if sync:
                self._table.add(data)
            else:
                self._table.add_async(data.copy())
            return
        row_ids = np.asarray(list(row_ids), dtype=np.int32)
        assert data.size == row_ids.size * self._num_col
        data = data.reshape(row_ids.size, self._num_col)
        if sync:
            self._table.add_rows(row_ids, data)
        else:
            self._table.add_rows_async(row_ids.copy(), data.copy())
