"""multiverso: drop-in Python binding for the TPU-native runtime.

Same public surface as the reference binding
(ref: binding/python/multiverso/__init__.py, api.py, tables.py) — init/
shutdown/barrier, workers_num/worker_id/server_id, ArrayTableHandler and
MatrixTableHandler with the master-initialized init_value convention — but
implemented directly on multiverso_tpu (no ctypes hop: the runtime IS
Python). Non-Python hosts use the byte-compatible C ABI in
native/c_api instead.
"""

from .api import (barrier, init, is_master_worker, server_id, shutdown,  # noqa: F401
                  worker_id, workers_num)
from .tables import ArrayTableHandler, MatrixTableHandler, TableHandler  # noqa: F401
