"""Framework adapters (the reference's theano_ext/keras_ext/lasagne_ext,
re-targeted at today's frameworks: generic, torch, and jax pytrees)."""

from .param_manager import (JaxParamManager, MVModelParamManager,  # noqa: F401
                            SyncEveryN, TorchParamManager)
