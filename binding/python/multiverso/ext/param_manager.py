"""Whole-model parameter sync through one ArrayTable.

Functional equivalent of the reference's theano/lasagne/keras param
managers (ref: binding/python/multiverso/theano_ext/param_manager.py:9-81,
theano_ext/sharedvar.py:12-50, keras_ext/callbacks.py:8-39): a model's
parameters are flattened into a single float32 ArrayTable; each sync pushes
``current - last_synced`` as the delta and pulls the merged latest, which
implements ASGD model averaging across workers. ``SyncEveryN`` is the
keras-callback equivalent (sync every N batches).

Adapters: generic (user get/set functions), ``TorchParamManager`` for
torch modules, ``JaxParamManager`` for jax pytrees.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import numpy as np

from .. import api
from ..tables import ArrayTableHandler


class MVModelParamManager:
    def __init__(self, get_params: Callable[[], Sequence[np.ndarray]],
                 set_params: Callable[[List[np.ndarray]], None]):
        self._get = get_params
        self._set = set_params
        values = [np.asarray(v, np.float32) for v in self._get()]
        self._shapes = [v.shape for v in values]
        self._offsets = np.cumsum([0] + [v.size for v in values])
        flat = self._flatten(values)
        self.table = ArrayTableHandler(flat.size, init_value=flat)
        api.barrier()
        self._last = self.table.get()
        self._set(self._unflatten(self._last))

    def _flatten(self, values) -> np.ndarray:
        return np.concatenate([np.asarray(v, np.float32).reshape(-1)
                               for v in values])

    def _unflatten(self, flat: np.ndarray) -> List[np.ndarray]:
        return [flat[self._offsets[i]:self._offsets[i + 1]]
                .reshape(self._shapes[i]).copy()
                for i in range(len(self._shapes))]

    def sync_all_param(self) -> None:
        """Push (current - last synced), pull the merged model
        (ref: sharedvar.py:26-50)."""
        current = self._flatten(self._get())
        self.table.add(current - self._last, sync=True)
        self._last = self.table.get()
        self._set(self._unflatten(self._last))


class TorchParamManager(MVModelParamManager):
    """Sync a torch.nn.Module's parameters (the torch/fb.resnet ASGD
    setup from the reference's Lua binding, re-targeted)."""

    def __init__(self, module):
        import torch

        def get_params():
            return [p.detach().cpu().numpy()
                    for p in module.parameters()]

        def set_params(values):
            with torch.no_grad():
                for p, v in zip(module.parameters(), values):
                    p.copy_(torch.from_numpy(v))

        super().__init__(get_params, set_params)


class JaxParamManager(MVModelParamManager):
    """Sync a jax pytree of parameters held by the caller via a getter
    returning the pytree and a setter taking the merged pytree."""

    def __init__(self, get_tree: Callable, set_tree: Callable):
        import jax

        self._treedef = None

        def get_params():
            leaves, treedef = jax.tree_util.tree_flatten(get_tree())
            self._treedef = treedef
            return [np.asarray(leaf, np.float32) for leaf in leaves]

        def set_params(values):
            set_tree(jax.tree_util.tree_unflatten(self._treedef, values))

        super().__init__(get_params, set_params)


class SyncEveryN:
    """Callback: sync the manager every N calls (the keras callback's
    every-N-batches contract, ref: keras_ext/callbacks.py:8-39)."""

    def __init__(self, manager: MVModelParamManager, n: int = 1):
        self.manager = manager
        self.n = max(int(n), 1)
        self._count = 0

    def __call__(self) -> None:
        self._count += 1
        if self._count % self.n == 0:
            self.manager.sync_all_param()
