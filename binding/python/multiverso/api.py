"""Process-level API (ref: binding/python/multiverso/api.py:12-75)."""

from __future__ import annotations

import multiverso_tpu as _mv


def init(sync: bool = False, args: list = None) -> None:
    """Initialize multiverso. ``sync=True`` creates a BSP sync server —
    every process must then call add/get in the same order the same number
    of times, and every get returns identical results (ref api.py:12-34).
    """
    argv = list(args or [])
    if sync:
        argv.append("-sync=true")
    _mv.init(argv)


def shutdown() -> None:
    _mv.shutdown()


def barrier() -> None:
    _mv.barrier()


def workers_num() -> int:
    return _mv.num_workers()


def worker_id() -> int:
    return _mv.worker_id()


def server_id() -> int:
    return _mv.server_id()


def is_master_worker() -> bool:
    """The master (worker 0) owns shared initialization
    (ref: api.py:68-75)."""
    return worker_id() == 0
