// Static facade mirroring the reference's MultiversoCLR surface
// (ref: binding/C#/MultiversoCLR/MultiversoCLR.h:11-45,
//  binding/C#/MultiversoCLR/MultiversoCLR.cpp:23-115): Init/Shutdown,
// CreateTable(s), Rank/Size/Barrier, and Get/Add by whole table or row.
//
// Differences from the CLR original, by design:
//  - float only: the c_api ABI is float-only (ref: include/multiverso/
//    c_api.h:28-54), so the `generic <class Type>` surface collapses to
//    float[] overloads.
//  - NetBind/NetConnect call the shim's MV_NetBind/MV_NetConnect
//    exports (app-driven TCP bootstrap); the -machine_file/-port argv
//    flags on Init remain the machine-file alternative.

using System;
using System.Collections.Generic;

namespace Multiverso
{
    public static class MultiversoWrapper
    {
        private static readonly List<ITableHandle> Tables = new List<ITableHandle>();

        private interface ITableHandle
        {
            void Get(float[] value);
            void Get(int rowId, float[] value);
            void Add(float[] update, bool sync);
            void Add(int rowId, float[] update, bool sync);
        }

        /// <summary>Init with table slots; args become -key=value argv
        /// entries (e.g. "-sync=true", "-machine_file=hosts.txt").</summary>
        public static void Init(int numTables, bool sync, params string[] extraArgs)
        {
            var argv = new List<string> { "csharp" };
            if (sync) argv.Add("-sync=true");
            argv.AddRange(extraArgs);
            int argc = argv.Count;
            NativeMethods.MV_Init(ref argc, argv.ToArray());
            Tables.Clear();
            for (int i = 0; i < numTables; ++i) Tables.Add(null);
        }

        public static void Shutdown()
        {
            Tables.Clear();
            NativeMethods.MV_ShutDown();
        }

        public static int Rank() { return NativeMethods.MV_WorkerId(); }

        public static int Size() { return NativeMethods.MV_NumWorkers(); }

        public static int ServerId() { return NativeMethods.MV_ServerId(); }

        public static void Barrier() { NativeMethods.MV_Barrier(); }

        // App-driven TCP bootstrap (ref: MultiversoCLR.h NetBind/NetConnect):
        // declare this process's endpoint, then every rank's, before Init.
        public static void NetBind(int rank, string endpoint)
        {
            NativeMethods.MV_NetBind(rank, endpoint);
        }

        public static void NetConnect(int[] ranks, string[] endpoints)
        {
            if (ranks.Length != endpoints.Length)
            {
                throw new ArgumentException(
                    "ranks and endpoints must have the same length");
            }
            NativeMethods.MV_NetConnect(ranks, endpoints, ranks.Length);
        }

        public static void CreateTables(int[] rows, int[] cols)
        {
            for (int i = 0; i < rows.Length; ++i) CreateTable(i, rows[i], cols[i]);
        }

        /// <summary>rows == 1 creates an Array table of `cols` elements;
        /// otherwise a rows×cols Matrix table — the same mapping the CLR
        /// wrapper's eleType/shape dispatch performed.</summary>
        public static void CreateTable(int tableId, int rows, int cols)
        {
            while (Tables.Count <= tableId) Tables.Add(null);
            Tables[tableId] = rows == 1
                ? (ITableHandle)new ArrayHandle(cols)
                : new MatrixHandle(rows, cols);
        }

        public static void Get(int tableId, float[] value)
        {
            Tables[tableId].Get(value);
        }

        public static void Get(int tableId, int rowId, float[] value)
        {
            Tables[tableId].Get(rowId, value);
        }

        public static void Add(int tableId, float[] update)
        {
            Tables[tableId].Add(update, sync: true);
        }

        public static void Add(int tableId, int rowId, float[] update)
        {
            Tables[tableId].Add(rowId, update, sync: true);
        }

        public static void AddAsync(int tableId, float[] update)
        {
            Tables[tableId].Add(update, sync: false);
        }

        private sealed class ArrayHandle : ITableHandle
        {
            private readonly IntPtr handle;

            internal ArrayHandle(int size)
            {
                NativeMethods.MV_NewArrayTable(size, out handle);
            }

            public void Get(float[] value)
            {
                NativeMethods.MV_GetArrayTable(handle, value, value.Length);
            }

            public void Get(int rowId, float[] value)
            {
                throw new InvalidOperationException("array tables have no rows");
            }

            public void Add(float[] update, bool sync)
            {
                if (sync) NativeMethods.MV_AddArrayTable(handle, update, update.Length);
                else NativeMethods.MV_AddAsyncArrayTable(handle, update, update.Length);
            }

            public void Add(int rowId, float[] update, bool sync)
            {
                throw new InvalidOperationException("array tables have no rows");
            }
        }

        private sealed class MatrixHandle : ITableHandle
        {
            private readonly IntPtr handle;

            internal MatrixHandle(int rows, int cols)
            {
                NativeMethods.MV_NewMatrixTable(rows, cols, out handle);
            }

            public void Get(float[] value)
            {
                NativeMethods.MV_GetMatrixTableAll(handle, value, value.Length);
            }

            public void Get(int rowId, float[] value)
            {
                NativeMethods.MV_GetMatrixTableByRows(
                    handle, value, value.Length, new[] { rowId }, 1);
            }

            public void Add(float[] update, bool sync)
            {
                if (sync) NativeMethods.MV_AddMatrixTableAll(handle, update, update.Length);
                else NativeMethods.MV_AddAsyncMatrixTableAll(handle, update, update.Length);
            }

            public void Add(int rowId, float[] update, bool sync)
            {
                if (sync)
                    NativeMethods.MV_AddMatrixTableByRows(
                        handle, update, update.Length, new[] { rowId }, 1);
                else
                    NativeMethods.MV_AddAsyncMatrixTableByRows(
                        handle, update, update.Length, new[] { rowId }, 1);
            }
        }
    }
}
