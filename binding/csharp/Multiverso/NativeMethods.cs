// P/Invoke declarations over the libmultiverso c_api ABI.
//
// The reference ships a Windows-only C++/CLI wrapper
// (ref: binding/C#/MultiversoCLR/MultiversoCLR.h:11-45) that links the C++
// API directly. This binding is a portable re-design: pure C# DllImport over
// the flat C ABI (ref: include/multiverso/c_api.h:14-54) — the same ABI the
// Python (ctypes) and Lua (LuaJIT FFI) bindings load — so it runs on .NET
// (Core) / Mono on Linux against the TPU-native libmultiverso.so.

using System;
using System.Runtime.InteropServices;

namespace Multiverso
{
    internal static class NativeMethods
    {
        // Resolved via the standard loader search path; set LD_LIBRARY_PATH
        // to native/build/ or use NativeLibrary.SetDllImportResolver.
        internal const string LibName = "multiverso";

        [DllImport(LibName, EntryPoint = "MV_Init")]
        internal static extern void MV_Init(ref int argc, string[] argv);

        [DllImport(LibName, EntryPoint = "MV_ShutDown")]
        internal static extern void MV_ShutDown();

        [DllImport(LibName, EntryPoint = "MV_Barrier")]
        internal static extern void MV_Barrier();

        [DllImport(LibName, EntryPoint = "MV_NetBind")]
        internal static extern void MV_NetBind(int rank, string endpoint);

        [DllImport(LibName, EntryPoint = "MV_NetConnect")]
        internal static extern void MV_NetConnect(int[] ranks, string[] endpoints, int size);

        [DllImport(LibName, EntryPoint = "MV_NumWorkers")]
        internal static extern int MV_NumWorkers();

        [DllImport(LibName, EntryPoint = "MV_WorkerId")]
        internal static extern int MV_WorkerId();

        [DllImport(LibName, EntryPoint = "MV_ServerId")]
        internal static extern int MV_ServerId();

        // -- Array table (float only, as in the reference c_api) --

        [DllImport(LibName, EntryPoint = "MV_NewArrayTable")]
        internal static extern void MV_NewArrayTable(int size, out IntPtr handler);

        [DllImport(LibName, EntryPoint = "MV_GetArrayTable")]
        internal static extern void MV_GetArrayTable(IntPtr handler, float[] data, int size);

        [DllImport(LibName, EntryPoint = "MV_AddArrayTable")]
        internal static extern void MV_AddArrayTable(IntPtr handler, float[] data, int size);

        [DllImport(LibName, EntryPoint = "MV_AddAsyncArrayTable")]
        internal static extern void MV_AddAsyncArrayTable(IntPtr handler, float[] data, int size);

        // -- Matrix table --

        [DllImport(LibName, EntryPoint = "MV_NewMatrixTable")]
        internal static extern void MV_NewMatrixTable(int numRow, int numCol, out IntPtr handler);

        [DllImport(LibName, EntryPoint = "MV_GetMatrixTableAll")]
        internal static extern void MV_GetMatrixTableAll(IntPtr handler, float[] data, int size);

        [DllImport(LibName, EntryPoint = "MV_AddMatrixTableAll")]
        internal static extern void MV_AddMatrixTableAll(IntPtr handler, float[] data, int size);

        [DllImport(LibName, EntryPoint = "MV_AddAsyncMatrixTableAll")]
        internal static extern void MV_AddAsyncMatrixTableAll(IntPtr handler, float[] data, int size);

        [DllImport(LibName, EntryPoint = "MV_GetMatrixTableByRows")]
        internal static extern void MV_GetMatrixTableByRows(
            IntPtr handler, float[] data, int size, int[] rowIds, int rowIdsN);

        [DllImport(LibName, EntryPoint = "MV_AddMatrixTableByRows")]
        internal static extern void MV_AddMatrixTableByRows(
            IntPtr handler, float[] data, int size, int[] rowIds, int rowIdsN);

        [DllImport(LibName, EntryPoint = "MV_AddAsyncMatrixTableByRows")]
        internal static extern void MV_AddAsyncMatrixTableByRows(
            IntPtr handler, float[] data, int size, int[] rowIds, int rowIdsN);
    }
}
