// Multi-worker counter demo — the C# analogue of the Python binding's
// test (ref: binding/python/multiverso/tests/test_multiverso.py:18-60):
// every worker adds i to slot i, barriers, and reads back i * num_workers.

using System;
using Multiverso;

namespace MultiversoExamples
{
    public static class Counter
    {
        public static void Main(string[] args)
        {
            const int size = 8;
            MultiversoWrapper.Init(numTables: 1, sync: true, extraArgs: args);
            MultiversoWrapper.CreateTable(0, rows: 1, cols: size);
            MultiversoWrapper.Barrier();

            var delta = new float[size];
            for (int i = 0; i < size; ++i) delta[i] = i;
            MultiversoWrapper.Add(0, delta);
            MultiversoWrapper.Barrier();

            var value = new float[size];
            MultiversoWrapper.Get(0, value);
            int workers = MultiversoWrapper.Size();
            for (int i = 0; i < size; ++i)
            {
                if (Math.Abs(value[i] - i * workers) > 1e-5)
                    throw new Exception($"slot {i}: got {value[i]}, want {i * workers}");
            }
            Console.WriteLine($"counter OK on rank {MultiversoWrapper.Rank()}/{workers}");
            MultiversoWrapper.Shutdown();
        }
    }
}
