--- MatrixTableHandler: 2-D row-addressable float table client.
--
-- Public surface of the reference handler (ref: binding/lua/
-- MatrixTableHandler.lua: new/get/add with optional row_ids) over the
-- c_api's whole-table and by-rows entry points.

local ffi = require 'ffi'
local util = require 'multiverso.util'

ffi.cdef[[
    void MV_NewMatrixTable(int num_row, int num_col, TableHandler* out);
    void MV_GetMatrixTableAll(TableHandler handler, float* data, int size);
    void MV_AddMatrixTableAll(TableHandler handler, float* data, int size);
    void MV_AddAsyncMatrixTableAll(TableHandler handler, float* data,
                                   int size);
    void MV_GetMatrixTableByRows(TableHandler handler, float* data,
                                 int size, int* row_ids, int row_ids_n);
    void MV_AddMatrixTableByRows(TableHandler handler, float* data,
                                 int size, int* row_ids, int row_ids_n);
    void MV_AddAsyncMatrixTableByRows(TableHandler handler, float* data,
                                      int size, int* row_ids,
                                      int row_ids_n);
]]

local MatrixTableHandler = {}
MatrixTableHandler.__index = MatrixTableHandler

function MatrixTableHandler:new(num_row, num_col, init_value)
    local self_ = setmetatable({}, MatrixTableHandler)
    self_._num_row, self_._num_col = num_row, num_col
    self_._size = num_row * num_col
    self_._handler = ffi.new('TableHandler[1]')
    libmv.MV_NewMatrixTable(ffi.new('int', num_row),
                            ffi.new('int', num_col), self_._handler)
    if init_value ~= nil then
        local mv = require 'multiverso.init'
        if mv.worker_id() == 0 then
            self_:add(init_value, nil, true)
        else
            local zeros = {}
            for i = 1, self_._size do zeros[i] = 0 end
            self_:add(zeros, nil, true)
        end
    end
    return self_
end

--- get(row_ids): whole table as a flat row-major table, or just the
-- requested rows (concatenated) when row_ids is given.
function MatrixTableHandler:get(row_ids)
    if row_ids == nil then
        local cdata = ffi.new('float[?]', self._size)
        libmv.MV_GetMatrixTableAll(self._handler[0], cdata, self._size)
        return util.to_table(cdata, self._size)
    end
    local n = #row_ids * self._num_col
    local cdata = ffi.new('float[?]', n)
    local ids = util.to_int_cdata(row_ids)
    libmv.MV_GetMatrixTableByRows(self._handler[0], cdata, n, ids,
                                  #row_ids)
    return util.to_table(cdata, n)
end

--- add(data, row_ids, sync): whole-table or by-rows delta add.
function MatrixTableHandler:add(data, row_ids, sync)
    if row_ids == nil then
        local cdata = util.to_cdata(data, self._size)
        if sync then
            libmv.MV_AddMatrixTableAll(self._handler[0], cdata, self._size)
        else
            libmv.MV_AddAsyncMatrixTableAll(self._handler[0], cdata,
                                            self._size)
        end
        return
    end
    local n = #row_ids * self._num_col
    local cdata = util.to_cdata(data, n)
    local ids = util.to_int_cdata(row_ids)
    if sync then
        libmv.MV_AddMatrixTableByRows(self._handler[0], cdata, n, ids,
                                      #row_ids)
    else
        libmv.MV_AddAsyncMatrixTableByRows(self._handler[0], cdata, n,
                                           ids, #row_ids)
    end
end

return MatrixTableHandler
