--- multiverso: LuaJIT FFI binding over the libmultiverso c_api.
--
-- Drop-in module layout and public surface of the reference binding
-- (ref: binding/lua/init.lua:1-67) on top of this framework's ABI-
-- compatible shim (native/c_api/multiverso_c_api.cpp). Re-implemented:
-- torch is optional (plain Lua tables work), the library is located via
-- MULTIVERSO_LIB / package.cpath / the in-repo build dir, and handlers
-- are plain metatables instead of torch classes.

local ffi = require 'ffi'

local mv = {}

ffi.cdef[[
    typedef void* TableHandler;
    void MV_Init(int* argc, char* argv[]);
    void MV_ShutDown();
    void MV_Barrier();
    int MV_NumWorkers();
    int MV_WorkerId();
    int MV_ServerId();
]]

local function locate_lib()
    local env = os.getenv('MULTIVERSO_LIB')
    if env ~= nil then return env end
    local here = debug.getinfo(1, 'S').source:match('@?(.*/)') or './'
    local candidates = {
        here .. '../../../native/build/libmultiverso.so',
        'libmultiverso.so',
    }
    for _, path in ipairs(candidates) do
        local f = io.open(path, 'r')
        if f ~= nil then f:close(); return path end
    end
    package.cpath = '/usr/local/lib/?.so;' .. package.cpath
    return package.searchpath('libmultiverso', package.cpath, '')
end

local libpath = locate_lib()
if libpath == nil then
    error('libmultiverso.so not found: set MULTIVERSO_LIB or build ' ..
          'native/ (make -C native)')
end
-- Global export: RTLD_GLOBAL so the embedded runtime resolves.
libmv = ffi.load(libpath, true)
mv._lib = libmv

mv.ArrayTableHandler = require('multiverso.ArrayTableHandler')
mv.MatrixTableHandler = require('multiverso.MatrixTableHandler')

--- init(sync): MV_Init with an optional -sync=true flag.
function mv.init(sync)
    local args = { 'lua' }
    if sync then args[#args + 1] = '-sync=true' end
    local argc = ffi.new('int[1]', #args)
    local argv = ffi.new('char*[?]', #args)
    local keep = {}
    for i = 1, #args do
        local buf = ffi.new('char[?]', #args[i] + 1)
        ffi.copy(buf, args[i])
        argv[i - 1] = buf
        keep[i] = buf
    end
    libmv.MV_Init(argc, argv)
end

function mv.shutdown() libmv.MV_ShutDown() end
function mv.barrier() libmv.MV_Barrier() end
function mv.num_workers() return libmv.MV_NumWorkers() end
function mv.worker_id() return libmv.MV_WorkerId() end
function mv.server_id() return libmv.MV_ServerId() end

return mv
