--- ArrayTableHandler: 1-D float table client.
--
-- Public surface of the reference handler (ref: binding/lua/
-- ArrayTableHandler.lua: new/get/add with init_value master-add
-- convention) as a plain-metatable class; returns Lua tables (or keeps
-- torch tensors out of the core path entirely).

local ffi = require 'ffi'
local util = require 'multiverso.util'

ffi.cdef[[
    void MV_NewArrayTable(int size, TableHandler* out);
    void MV_GetArrayTable(TableHandler handler, float* data, int size);
    void MV_AddArrayTable(TableHandler handler, float* data, int size);
    void MV_AddAsyncArrayTable(TableHandler handler, float* data, int size);
]]

local ArrayTableHandler = {}
ArrayTableHandler.__index = ArrayTableHandler

--- new(size, init_value): create the table; when init_value is given the
-- master worker (id 0) adds it and every other worker adds zeros — the
-- reference's init convention, so sync mode stays balanced.
function ArrayTableHandler:new(size, init_value)
    local self_ = setmetatable({}, ArrayTableHandler)
    self_._size = size
    self_._handler = ffi.new('TableHandler[1]')
    libmv.MV_NewArrayTable(ffi.new('int', size), self_._handler)
    if init_value ~= nil then
        local mv = require 'multiverso.init'
        if mv.worker_id() == 0 then
            self_:add(init_value, true)
        else
            local zeros = {}
            for i = 1, size do zeros[i] = 0 end
            self_:add(zeros, true)
        end
    end
    return self_
end

function ArrayTableHandler:get()
    local cdata = ffi.new('float[?]', self._size)
    libmv.MV_GetArrayTable(self._handler[0], cdata, self._size)
    return util.to_table(cdata, self._size)
end

function ArrayTableHandler:add(data, sync)
    local cdata, keep = util.to_cdata(data, self._size)
    if sync then
        libmv.MV_AddArrayTable(self._handler[0], cdata, self._size)
    else
        libmv.MV_AddAsyncArrayTable(self._handler[0], cdata, self._size)
    end
    return keep ~= nil  -- anchor: keep cdata alive through the call
end

return ArrayTableHandler
