--- Conversion helpers: Lua tables / torch tensors <-> C float arrays.
--
-- Equivalent role to the reference's util.lua (ref: binding/lua/
-- util.lua:17-27) but torch-optional: anything exposing :data() and
-- :nElement() (a torch tensor) is used zero-copy-ish via its contiguous
-- buffer; plain Lua (possibly nested) tables are flattened.

local ffi = require 'ffi'

local util = {}

local function flatten(t, out)
    for i = 1, #t do
        local v = t[i]
        if type(v) == 'table' then
            flatten(v, out)
        else
            out[#out + 1] = v
        end
    end
    return out
end

--- to_cdata(data, n): float[n] cdata from a table or torch tensor.
function util.to_cdata(data, n)
    if type(data) ~= 'table' and data.data ~= nil then
        -- torch tensor: contiguous float buffer
        local ft = data:contiguous():float()
        return ft:data(), ft
    end
    local flat = flatten(data, {})
    n = n or #flat
    local cdata = ffi.new('float[?]', n)
    for i = 1, math.min(#flat, n) do
        cdata[i - 1] = flat[i]
    end
    return cdata, cdata
end

--- to_table(cdata, n): Lua array table from float* cdata.
function util.to_table(cdata, n)
    local out = {}
    for i = 1, n do
        out[i] = tonumber(cdata[i - 1])
    end
    return out
end

--- to_int_cdata(list): int[n] cdata from a Lua table.
function util.to_int_cdata(list)
    local cdata = ffi.new('int[?]', #list)
    for i = 1, #list do
        cdata[i - 1] = list[i]
    end
    return cdata
end

return util
