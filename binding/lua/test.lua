--- Binding test: array + matrix roundtrips through libmultiverso.so.
--
-- Non-interactive re-design of the reference's torch TestSuite
-- (ref: binding/lua/test.lua): plain asserts, exit 0 on success.
-- Run: luajit test.lua   (from binding/lua/, with native/ built)

package.path = './?.lua;./?/init.lua;' .. package.path

local mv = require 'multiverso'

mv.init()
assert(mv.num_workers() >= 1, 'no workers')
assert(mv.worker_id() >= 0, 'bad worker id')

-- Array roundtrip: two sync adds accumulate.
local size = 1000
local abh = mv.ArrayTableHandler:new(size)
mv.barrier()
local ones = {}
for i = 1, size do ones[i] = 1 end
abh:add(ones, true)
abh:add(ones, true)
local got = abh:get()
assert(#got == size, 'bad get size: ' .. #got)
assert(got[1] == 2 and got[size] == 2,
       'array add/get mismatch: ' .. got[1])

-- init_value convention: master lands it exactly once.
local init = {}
for i = 1, size do init[i] = i end
local abh2 = mv.ArrayTableHandler:new(size, init)
mv.barrier()
local got2 = abh2:get()
assert(got2[7] == 7, 'init_value mismatch: ' .. got2[7])

-- Matrix whole-table + by-rows.
local rows, cols = 11, 10
local mbh = mv.MatrixTableHandler:new(rows, cols)
mv.barrier()
local flat = {}
for i = 1, rows * cols do flat[i] = i end
mbh:add(flat, nil, true)
local all = mbh:get()
assert(all[1] == 1 and all[rows * cols] == rows * cols,
       'matrix whole add/get mismatch')
local some = mbh:get({ 0, 5, 10 })
assert(#some == 3 * cols, 'bad by-rows size')
assert(some[1] == 1, 'row 0 mismatch: ' .. some[1])
assert(some[cols + 1] == 5 * cols + 1, 'row 5 mismatch')
local delta = {}
for i = 1, 2 * cols do delta[i] = 1 end
mbh:add(delta, { 1, 3 }, true)
local row13 = mbh:get({ 1, 3 })
assert(row13[1] == cols + 2, 'by-rows add mismatch: ' .. row13[1])

mv.barrier()
mv.shutdown()
print('LUA_BINDING_OK')
