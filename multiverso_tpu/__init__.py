"""multiverso_tpu: a TPU-native parameter-server framework.

Brand-new implementation of the capabilities of Microsoft Multiverso
(the DMTK parameter server) designed for JAX/XLA on TPU. Distributed tables
live as sharded ``jax.Array``s in HBM; server-side optimizers are
jit-compiled donated-buffer updates; model-average mode maps to
``lax.psum`` over the device mesh.

Public API mirrors the reference's ``MV_*`` surface
(ref: include/multiverso/multiverso.h:9-65).
"""

from __future__ import annotations

from typing import List, Optional

from .runtime.net import PeerLostError
from .runtime.zoo import (ClusterAborted, Zoo, current_zoo,
                          set_default_zoo, set_thread_zoo)
from .tables import (ArrayTableOption, KVTableOption, MatrixTableOption,
                     create_array_table, create_kv_table,
                     create_matrix_table, create_table)
from .tables.table_interface import RpcTimeoutError, TableRequestError
from .updater import AddOption, GetOption
from .util.configure import set_flag as _set_flag

__version__ = "0.1.0"


def init(argv: Optional[List[str]] = None) -> List[str]:
    """MV_Init (ref: src/multiverso.cpp:11-14). Returns remaining argv."""
    zoo = Zoo()
    set_default_zoo(zoo)
    return zoo.start(argv)


def shutdown(finalize_net: bool = True) -> None:
    """MV_ShutDown (ref: src/multiverso.cpp:20-23)."""
    from .runtime import zoo as zoo_mod
    zoo = current_zoo()
    zoo.stop(finalize_net)
    # Clear only the slot this zoo actually occupies.
    if getattr(zoo_mod._tls, "zoo", None) is zoo:
        set_thread_zoo(None)
    if zoo_mod._default_zoo is zoo:
        set_default_zoo(None)


def barrier() -> None:
    """MV_Barrier (ref: src/multiverso.cpp:16-18)."""
    current_zoo().barrier()


def reshard_table(worker_table, server_ids,
                  wait_s: float = 60.0) -> None:
    """Respread a table over exactly ``server_ids`` with live row
    migration (grow onto standby servers / drain a retiring one) —
    traffic keeps flowing throughout (docs/SHARDING.md elastic
    resharding)."""
    current_zoo().reshard_table(worker_table, server_ids,
                                wait_s=wait_s)


def serve_table(name: str, worker_table, vocab=None) -> None:
    """Expose a worker table on this rank's online serving frontend
    (``-serving_port``, docs/SERVING.md) under ``/v1/tables/<name>``;
    ``vocab`` (word -> row id) enables the nearest-neighbor endpoint's
    word lookups. No-op when serving is off."""
    current_zoo().serve_table(name, worker_table, vocab)


def rank() -> int:
    return current_zoo().rank


def size() -> int:
    return current_zoo().size


def num_workers() -> int:
    return current_zoo().num_workers


def num_servers() -> int:
    return current_zoo().num_servers


def worker_id() -> int:
    return current_zoo().worker_id


def server_id() -> int:
    return current_zoo().server_id


def set_flag(name: str, value) -> None:
    """MV_SetFlag (ref: src/multiverso.cpp:48-51)."""
    _set_flag(name, value)


def aggregate(data):
    """MV_Aggregate: sum-allreduce a host array across ranks
    (ref: src/multiverso.cpp:53-56, net::Allreduce src/net.cpp:27-35)."""
    return current_zoo().net.allreduce(data)


def net_bind(rank: int, endpoint: str) -> None:
    """MV_NetBind (ref: include/multiverso/multiverso.h:55-59): declare
    this process's rank and ``host:port`` endpoint before ``init``."""
    from .runtime.tcp import net_bind as _net_bind
    _net_bind(rank, endpoint)


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, argv=None, control_port=None):
    """Multi-host bootstrap: jax.distributed (data plane) + the TCP
    control mesh rendezvoused through its coordinator + init. See
    runtime/bootstrap.py."""
    from .runtime.bootstrap import init_distributed as _impl
    return _impl(coordinator_address, num_processes, process_id,
                 argv, control_port)


def net_connect(ranks, endpoints) -> None:
    """MV_NetConnect (ref: include/multiverso/multiverso.h:60-64): supply
    peer endpoints and build the TCP mesh consumed by the next ``init``."""
    from .runtime.tcp import net_connect as _net_connect
    _net_connect(list(ranks), list(endpoints))
