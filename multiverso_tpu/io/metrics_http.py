"""Tiny stdlib HTTP scrape surface for the observability layer.

Serves the controller's cluster-aggregated metrics view
(docs/OBSERVABILITY.md) on ``-metrics_port``:

- ``GET /metrics`` — Prometheus text exposition format 0.0.4 (the
  contract every Prometheus-compatible scraper speaks);
- ``GET /trace.json`` — the merged Chrome-trace/Perfetto JSON of every
  rank's shipped span events (load in ``chrome://tracing`` or
  https://ui.perfetto.dev).

The HTTP plumbing itself (ThreadingHTTPServer lifecycle, dispatch,
404/500 handling) lives in the shared ``io/http_server.py`` base,
which the online serving tier (``serving/frontend.py``,
docs/SERVING.md) builds on too; this module is just the fixed
exact-path route table over it. Read-only and dependency-free; this is
deliberately NOT a general app server — it is the scrape side, and
stays a leaf: renderers are plain callables injected by the runtime
(no imports back into it).
"""

from __future__ import annotations

import json
from typing import Callable, Dict, Tuple

from .http_server import HttpServer, Response

#: path -> () -> (content_type, body_bytes)
Routes = Dict[str, Callable[[], Tuple[str, bytes]]]


class MetricsHttpServer(HttpServer):
    """Threaded HTTP server over a fixed route table."""

    def __init__(self, port: int, routes: Routes,
                 host: str = "0.0.0.0"):
        self._routes = dict(routes)
        super().__init__(port, self._resolve_path, host=host,
                         name="metrics-http")

    def _resolve_path(self, path: str):
        route = self._routes.get(path)
        if route is None:
            return None

        def handler(query):
            ctype, body = route()
            return Response(body, ctype)
        return handler

    def describe(self) -> str:
        return ", ".join(sorted(self._routes))


def prometheus_route(render: Callable[[], str]):
    """Adapt a text renderer to a route (content type per the
    exposition-format spec)."""
    def route():
        return ("text/plain; version=0.0.4; charset=utf-8",
                render().encode())
    return route


def json_route(render: Callable[[], dict]):
    def route():
        return ("application/json; charset=utf-8",
                json.dumps(render()).encode())
    return route
