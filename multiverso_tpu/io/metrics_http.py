"""Tiny stdlib HTTP scrape surface for the observability layer.

Serves the controller's cluster-aggregated metrics view
(docs/OBSERVABILITY.md) on ``-metrics_port``:

- ``GET /metrics`` — Prometheus text exposition format 0.0.4 (the
  contract every Prometheus-compatible scraper speaks);
- ``GET /trace.json`` — the merged Chrome-trace/Perfetto JSON of every
  rank's shipped span events (load in ``chrome://tracing`` or
  https://ui.perfetto.dev).

Read-only and dependency-free (``http.server``); one daemon thread per
server, each request handled on its own thread
(``ThreadingHTTPServer``) so a slow scraper cannot block a concurrent
one. This is deliberately NOT a general app server — it is the scrape
side of ROADMAP item 4's serving tier, and stays a leaf: handlers are
plain callables injected by the runtime (no imports back into it).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from ..util import log

#: path -> () -> (content_type, body_bytes)
Routes = Dict[str, Callable[[], Tuple[str, bytes]]]


class MetricsHttpServer:
    """Threaded HTTP server over a fixed route table."""

    def __init__(self, port: int, routes: Routes,
                 host: str = "0.0.0.0"):
        self._routes = dict(routes)
        routes_ref = self._routes

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server contract
                route = routes_ref.get(self.path)
                if route is None:
                    self.send_error(404, "unknown path (served: "
                                    + ", ".join(sorted(routes_ref))
                                    + ")")
                    return
                try:
                    ctype, body = route()
                except Exception as exc:  # noqa: BLE001 - a broken
                    # renderer must answer 500, not kill the handler
                    # thread mid-response
                    self.send_error(500, f"renderer failed: {exc}")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # quiet: scrapes are
                # periodic; stderr noise per poll helps nobody
                log.debug("metrics_http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"mv-metrics-http-{self.port}")
        self._thread.start()
        log.info("metrics http: serving %s on port %d",
                 ", ".join(sorted(self._routes)), self.port)

    @property
    def port(self) -> int:
        """The actually-bound port (differs from the requested one only
        when constructed with port 0 — tests use the ephemeral bind)."""
        return self._httpd.server_address[1]

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def prometheus_route(render: Callable[[], str]):
    """Adapt a text renderer to a route (content type per the
    exposition-format spec)."""
    def route():
        return ("text/plain; version=0.0.4; charset=utf-8",
                render().encode())
    return route


def json_route(render: Callable[[], dict]):
    def route():
        return ("application/json; charset=utf-8",
                json.dumps(render()).encode())
    return route
