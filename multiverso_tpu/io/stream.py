"""URI-dispatched stream IO + checkpoint driver.

TPU-native equivalent of the reference's IO layer
(ref: include/multiverso/io/io.h:24-132, src/io/io.cpp:8-62): a
``StreamFactory`` keyed on URI scheme (``file://`` default; other schemes
register via ``register_scheme`` — the reference gates ``hdfs://`` behind a
build flag the same way), buffered ``TextReader.get_line``, and
``Serializable`` Store/Load driven over every server table.

The checkpoint driver (``save_checkpoint``/``load_checkpoint``) recreates
the upstream end-to-end checkpoint/restore flow whose tests were dropped
from the reference snapshot (ref: deploy/docker/Dockerfile:105-106 runs
``multiverso.test checkpoint|restore`` against Test/main.cpp which no
longer has them).
"""

from __future__ import annotations

import io
import json
import os
import zlib
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import urlparse

from ..runtime.zoo import current_zoo
from ..util import log


class Stream:
    """Binary stream (ref: io.h:24-60)."""

    def __init__(self, fileobj, path: str):
        self._f = fileobj
        self.path = path

    def write(self, data: bytes) -> int:
        return self._f.write(data)

    def read(self, size: int = -1) -> bytes:
        return self._f.read(size)

    def good(self) -> bool:
        return not self._f.closed

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _open_local(path: str, mode: str) -> Stream:
    binary_mode = mode if "b" in mode else mode + "b"
    parent = os.path.dirname(path)
    if parent and "w" in mode:
        os.makedirs(parent, exist_ok=True)
    return Stream(open(path, binary_mode), path)


class StreamFactory:
    """Scheme-dispatched open (ref: io.h:62-117, io.cpp:8-21)."""

    _openers: Dict[str, Callable[[str, str], Stream]] = {}

    @classmethod
    def register_scheme(cls, scheme: str,
                        opener: Callable[[str, str], Stream]) -> None:
        cls._openers[scheme] = opener

    @classmethod
    def get_stream(cls, uri: str, mode: str = "r") -> Stream:
        parsed = urlparse(uri)
        scheme = parsed.scheme or "file"
        if scheme == "file" or len(scheme) == 1:  # len==1: windows drive
            if parsed.scheme == "file":
                # file://tmp/x parses 'tmp' into netloc — a relative-path
                # URI; rejoin it rather than silently opening /x.
                path = (parsed.netloc + parsed.path) if parsed.netloc \
                    else parsed.path
            else:
                path = uri
            return _open_local(path, mode)
        opener = cls._openers.get(scheme)
        if opener is None:
            raise ValueError(f"unsupported stream scheme: {scheme}://")
        return opener(uri, mode)


class TextReader:
    """Buffered line reader (ref: io.h:119-132, io.cpp:33-55)."""

    def __init__(self, uri: str, buf_size: int = 1 << 20):
        self._stream = StreamFactory.get_stream(uri, "r")
        self._buf_size = buf_size
        self._buf = b""
        self._eof = False

    def get_line(self) -> Optional[str]:
        while True:
            newline = self._buf.find(b"\n")
            if newline >= 0:
                line, self._buf = self._buf[:newline], self._buf[newline + 1:]
                return line.decode("utf-8", errors="replace").rstrip("\r")
            if self._eof:
                if self._buf:
                    line, self._buf = self._buf, b""
                    return line.decode("utf-8",
                                       errors="replace").rstrip("\r")
                return None
            chunk = self._stream.read(self._buf_size)
            if not chunk:
                self._eof = True
            else:
                self._buf += chunk

    def close(self) -> None:
        self._stream.close()


# -- atomic whole-object writes (checkpoint/snapshot robustness) --

def _local_path(uri: str) -> Optional[str]:
    """The local filesystem path behind a uri, or None for remote
    schemes."""
    parsed = urlparse(uri)
    if parsed.scheme == "file":
        return (parsed.netloc + parsed.path) if parsed.netloc \
            else parsed.path
    if not parsed.scheme or len(parsed.scheme) == 1:  # plain / drive
        return uri
    return None


def write_bytes_atomic(uri: str, data: bytes, fsync: bool = False) -> None:
    """Write a whole object so a crash mid-write can never leave a
    half-written file under the final name: local files go to a
    ``.tmp.{pid}`` sibling first (optionally fsync'd) and are
    ``os.replace``d into place — the POSIX atomic-rename guarantee.
    Remote schemes write through their driver directly (object stores
    are typically whole-object-or-nothing already); readers must still
    validate (the checkpoint manifest records size+crc32 per file)."""
    path = _local_path(uri)
    if path is None:
        with StreamFactory.get_stream(uri, "w") as stream:
            stream.write(data)
        return
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)


def read_bytes_or_none(uri: str) -> Optional[bytes]:
    """Whole-object read; None when the object does not exist (any
    scheme's open/read failure counts as absent — PRESENT-but-torn
    payloads are caught by the manifest's size/crc validation)."""
    try:
        with StreamFactory.get_stream(uri, "r") as stream:
            return stream.read()
    except Exception:  # noqa: BLE001 - absent object
        return None


# -- checkpoint driver over every registered server table --

CHECKPOINT_FORMAT = 1


class CheckpointError(RuntimeError):
    """A checkpoint failed validation on load: torn table file, torn or
    partial manifest, or a manifest whose entries do not match the
    registered tables. Loading it would silently serve corrupt or
    spliced parameters, so it fails loudly instead."""


def _table_uri(uri_prefix: str, i: int, rank: int) -> str:
    return f"{uri_prefix}.table{i}.rank{rank}"


def _manifest_uri(uri_prefix: str, rank: int) -> str:
    return f"{uri_prefix}.manifest.rank{rank}.json"


def save_checkpoint(uri_prefix: str, zoo=None) -> int:
    """Store every server table shard under ``{prefix}.table{i}.rank{r}``
    plus an fsync'd, atomically-renamed manifest recording size, crc32
    and shard version per file — so ``load_checkpoint`` can reject torn
    or mixed-save checkpoints instead of restoring garbage. Returns the
    number of tables written."""
    zoo = zoo if zoo is not None else current_zoo()
    tables = zoo.server_tables
    entries = []
    for i, table in enumerate(tables):
        buf = io.BytesIO()
        table.store(buf)
        data = buf.getvalue()
        # fsync'd: the manifest below commits the save — every payload
        # it names must be durable before the manifest rename.
        write_bytes_atomic(_table_uri(uri_prefix, i, zoo.rank), data,
                           fsync=True)
        entries.append({"table": i,
                        "file": f"table{i}.rank{zoo.rank}",
                        "bytes": len(data),
                        "crc32": zlib.crc32(data),
                        "version": int(getattr(table, "version", 0))})
    manifest = {"format": CHECKPOINT_FORMAT, "rank": zoo.rank,
                "complete": True, "tables": entries}
    write_bytes_atomic(_manifest_uri(uri_prefix, zoo.rank),
                       json.dumps(manifest, indent=1).encode(),
                       fsync=True)
    log.info("rank %d: checkpointed %d tables to %s",
             zoo.rank, len(tables), uri_prefix)
    return len(tables)


def _validated_payloads(uri_prefix: str, zoo,
                        raw_manifest: bytes) -> Dict[int, Tuple[bytes,
                                                                int]]:
    """Parse + validate a checkpoint manifest against the registered
    tables; returns {table_id: (bytes, version)} or raises
    CheckpointError naming exactly what is wrong."""
    try:
        manifest = json.loads(raw_manifest.decode("utf-8"))
    except ValueError as exc:
        raise CheckpointError(
            f"checkpoint manifest for {uri_prefix!r} is torn "
            f"(unparseable JSON): {exc}") from exc
    if manifest.get("format") != CHECKPOINT_FORMAT \
            or not manifest.get("complete"):
        raise CheckpointError(
            f"checkpoint manifest for {uri_prefix!r} is partial or of "
            f"an unknown format ({manifest.get('format')!r}, "
            f"complete={manifest.get('complete')!r})")
    entries = manifest.get("tables", [])
    if len(entries) != len(zoo.server_tables):
        raise CheckpointError(
            f"checkpoint for {uri_prefix!r} covers {len(entries)} "
            f"tables but this rank registered "
            f"{len(zoo.server_tables)} — partial save or table-"
            f"creation drift; refusing a mixed restore")
    payloads: Dict[int, Tuple[bytes, int]] = {}
    for entry in entries:
        i = int(entry["table"])
        data = read_bytes_or_none(_table_uri(uri_prefix, i, zoo.rank))
        if data is None:
            raise CheckpointError(
                f"checkpoint table file "
                f"{_table_uri(uri_prefix, i, zoo.rank)!r} is missing")
        if len(data) != int(entry["bytes"]) \
                or zlib.crc32(data) != int(entry["crc32"]):
            raise CheckpointError(
                f"checkpoint table file "
                f"{_table_uri(uri_prefix, i, zoo.rank)!r} is torn or "
                f"from a different save ({len(data)} bytes vs "
                f"{entry['bytes']} in the manifest / crc mismatch)")
        payloads[i] = (data, int(entry.get("version", 0)))
    return payloads


def load_checkpoint(uri_prefix: str, zoo=None) -> int:
    """Load every server table shard saved by ``save_checkpoint``.

    With a manifest present every payload is validated (size + crc32,
    complete flag, table count) BEFORE any table is touched — a torn
    write or a manifest spliced across saves raises ``CheckpointError``
    with nothing restored. Pre-manifest checkpoints (no manifest file)
    load through the legacy per-file path unchanged."""
    zoo = zoo if zoo is not None else current_zoo()
    tables = zoo.server_tables
    raw_manifest = read_bytes_or_none(_manifest_uri(uri_prefix, zoo.rank))
    if raw_manifest is not None:
        payloads = _validated_payloads(uri_prefix, zoo, raw_manifest)
        for i, table in enumerate(tables):
            data, version = payloads[i]
            table.load(io.BytesIO(data))
            table.version = version
    else:
        for i, table in enumerate(tables):
            with StreamFactory.get_stream(
                    _table_uri(uri_prefix, i, zoo.rank), "r") as stream:
                table.load(stream)
    log.info("rank %d: restored %d tables from %s",
             zoo.rank, len(tables), uri_prefix)
    return len(tables)
