"""URI-dispatched stream IO + checkpoint driver.

TPU-native equivalent of the reference's IO layer
(ref: include/multiverso/io/io.h:24-132, src/io/io.cpp:8-62): a
``StreamFactory`` keyed on URI scheme (``file://`` default; other schemes
register via ``register_scheme`` — the reference gates ``hdfs://`` behind a
build flag the same way), buffered ``TextReader.get_line``, and
``Serializable`` Store/Load driven over every server table.

The checkpoint driver (``save_checkpoint``/``load_checkpoint``) recreates
the upstream end-to-end checkpoint/restore flow whose tests were dropped
from the reference snapshot (ref: deploy/docker/Dockerfile:105-106 runs
``multiverso.test checkpoint|restore`` against Test/main.cpp which no
longer has them).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional
from urllib.parse import urlparse

from ..runtime.zoo import current_zoo
from ..util import log


class Stream:
    """Binary stream (ref: io.h:24-60)."""

    def __init__(self, fileobj, path: str):
        self._f = fileobj
        self.path = path

    def write(self, data: bytes) -> int:
        return self._f.write(data)

    def read(self, size: int = -1) -> bytes:
        return self._f.read(size)

    def good(self) -> bool:
        return not self._f.closed

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _open_local(path: str, mode: str) -> Stream:
    binary_mode = mode if "b" in mode else mode + "b"
    parent = os.path.dirname(path)
    if parent and "w" in mode:
        os.makedirs(parent, exist_ok=True)
    return Stream(open(path, binary_mode), path)


class StreamFactory:
    """Scheme-dispatched open (ref: io.h:62-117, io.cpp:8-21)."""

    _openers: Dict[str, Callable[[str, str], Stream]] = {}

    @classmethod
    def register_scheme(cls, scheme: str,
                        opener: Callable[[str, str], Stream]) -> None:
        cls._openers[scheme] = opener

    @classmethod
    def get_stream(cls, uri: str, mode: str = "r") -> Stream:
        parsed = urlparse(uri)
        scheme = parsed.scheme or "file"
        if scheme == "file" or len(scheme) == 1:  # len==1: windows drive
            if parsed.scheme == "file":
                # file://tmp/x parses 'tmp' into netloc — a relative-path
                # URI; rejoin it rather than silently opening /x.
                path = (parsed.netloc + parsed.path) if parsed.netloc \
                    else parsed.path
            else:
                path = uri
            return _open_local(path, mode)
        opener = cls._openers.get(scheme)
        if opener is None:
            raise ValueError(f"unsupported stream scheme: {scheme}://")
        return opener(uri, mode)


class TextReader:
    """Buffered line reader (ref: io.h:119-132, io.cpp:33-55)."""

    def __init__(self, uri: str, buf_size: int = 1 << 20):
        self._stream = StreamFactory.get_stream(uri, "r")
        self._buf_size = buf_size
        self._buf = b""
        self._eof = False

    def get_line(self) -> Optional[str]:
        while True:
            newline = self._buf.find(b"\n")
            if newline >= 0:
                line, self._buf = self._buf[:newline], self._buf[newline + 1:]
                return line.decode("utf-8", errors="replace").rstrip("\r")
            if self._eof:
                if self._buf:
                    line, self._buf = self._buf, b""
                    return line.decode("utf-8",
                                       errors="replace").rstrip("\r")
                return None
            chunk = self._stream.read(self._buf_size)
            if not chunk:
                self._eof = True
            else:
                self._buf += chunk

    def close(self) -> None:
        self._stream.close()


# -- checkpoint driver over every registered server table --

def save_checkpoint(uri_prefix: str, zoo=None) -> int:
    """Store every server table shard under ``{prefix}.table{i}.rank{r}``.
    Returns the number of tables written."""
    zoo = zoo if zoo is not None else current_zoo()
    tables = zoo.server_tables
    for i, table in enumerate(tables):
        with StreamFactory.get_stream(
                f"{uri_prefix}.table{i}.rank{zoo.rank}", "w") as stream:
            table.store(stream)
    log.info("rank %d: checkpointed %d tables to %s",
             zoo.rank, len(tables), uri_prefix)
    return len(tables)


def load_checkpoint(uri_prefix: str, zoo=None) -> int:
    """Load every server table shard saved by ``save_checkpoint``."""
    zoo = zoo if zoo is not None else current_zoo()
    tables = zoo.server_tables
    for i, table in enumerate(tables):
        with StreamFactory.get_stream(
                f"{uri_prefix}.table{i}.rank{zoo.rank}", "r") as stream:
            table.load(stream)
    log.info("rank %d: restored %d tables from %s",
             zoo.rank, len(tables), uri_prefix)
    return len(tables)
