"""Shared stdlib HTTP plumbing for the scrape and serving surfaces.

One ``ThreadingHTTPServer`` wrapper used by both HTTP frontends in the
tree — the observability scrape surface (``io/metrics_http.py``:
/metrics, /trace.json) and the online serving tier
(``serving/frontend.py``: /v1/tables/...; docs/SERVING.md). Factoring
it here keeps the two surfaces byte-for-byte consistent on the parts
that are pure protocol: route dispatch, Content-Type/Content-Length
handling, 404 for unknown paths, 500 for a handler that raises, and
typed non-200 responses with extra headers (the admission controller's
429 + Retry-After rides ``HttpError``).

Dependency-free (``http.server``); one daemon thread per server, each
request handled on its own thread (``ThreadingHTTPServer``) so a slow
client cannot block a concurrent one. Deliberately a LEAF module:
handlers are plain callables injected by the owner — no imports back
into the runtime.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qs, urlsplit

from ..util import log


class Response:
    """What a route handler returns: status + content type + body bytes
    (+ any extra headers, e.g. the serving tier's X-MV-* metadata)."""

    __slots__ = ("status", "content_type", "body", "headers")

    def __init__(self, body: bytes, content_type: str,
                 status: int = 200,
                 headers: Optional[Dict[str, str]] = None):
        self.status = int(status)
        self.content_type = content_type
        self.body = body
        self.headers = dict(headers or {})


class HttpError(Exception):
    """A typed non-200 answer a handler wants sent — carries the status
    and any extra headers (Retry-After on a 429/503 shed), rendered as
    a small JSON error body so programmatic clients can read the
    machine fields (``retry_after_s``) that the integer-seconds
    Retry-After header cannot carry."""

    def __init__(self, status: int, message: str,
                 headers: Optional[Dict[str, str]] = None,
                 extra: Optional[dict] = None):
        super().__init__(message)
        self.status = int(status)
        self.message = message
        self.headers = dict(headers or {})
        self.extra = dict(extra or {})


#: A route handler: query params (last value per key) -> Response.
Handler = Callable[[Dict[str, str]], Response]


class HttpServer:
    """Threaded HTTP server dispatching GETs through ``resolve``.

    ``resolve(path)`` returns the ``Handler`` for a path or ``None``
    (-> 404 listing ``describe()``). A handler may raise ``HttpError``
    for a typed non-200 answer; any other exception answers 500 —
    a broken renderer must not kill the handler thread mid-response.
    """

    def __init__(self, port: int,
                 resolve: Callable[[str], Optional[Handler]],
                 host: str = "0.0.0.0", name: str = "http"):
        self._name = name
        server = self

        class _Handler(BaseHTTPRequestHandler):
            # Keep-alive: serving clients issue thousands of small
            # GETs, and HTTP/1.0's connection-per-request tears down a
            # TCP handshake per read (~an order of magnitude of the
            # whole request on loopback). Safe because every response
            # path below goes through _send, which always sets
            # Content-Length.
            protocol_version = "HTTP/1.1"

            def do_GET(self):  # noqa: N802 - http.server contract
                server._handle(self)

            def log_message(self, fmt, *args):  # quiet: per-request
                # stderr noise helps nobody; scrapes are periodic and
                # serving traffic is high-rate by design
                log.debug(f"{server._name}: " + fmt, *args)

        self._resolve = resolve
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        # Local import: io must not pull the runtime package (and its
        # actor/zoo import chain) at module load.
        from ..runtime import thread_roles
        self._thread = thread_roles.spawn(
            thread_roles.BACKGROUND, target=self._httpd.serve_forever,
            name=f"mv-{name}-{self.port}")
        log.info("%s: serving on port %d", self._name, self.port)

    # -- request plumbing --
    def _handle(self, request: BaseHTTPRequestHandler) -> None:
        parts = urlsplit(request.path)
        handler = self._resolve(parts.path)
        if handler is None:
            self._send_json(request, 404,
                            {"error": f"unknown path {parts.path!r}"
                                      f" (served: {self.describe()})"})
            return
        query = {key: values[-1] for key, values
                 in parse_qs(parts.query).items()}
        try:
            response = handler(query)
        except HttpError as exc:
            self._send_json(request, exc.status,
                            {"error": exc.message, **exc.extra},
                            exc.headers)
            return
        except Exception as exc:  # noqa: BLE001 - a broken handler
            # must answer 500, not kill the handler thread mid-response
            self._send_json(request, 500,
                            {"error": f"handler failed: {exc}"})
            return
        self._send(request, response.status, response.content_type,
                   response.body, response.headers)

    @staticmethod
    def _send(request: BaseHTTPRequestHandler, status: int,
              content_type: str, body: bytes,
              headers: Optional[Dict[str, str]] = None) -> None:
        try:
            request.send_response(status)
            request.send_header("Content-Type", content_type)
            request.send_header("Content-Length", str(len(body)))
            for name, value in (headers or {}).items():
                request.send_header(name, value)
            request.end_headers()
            request.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up mid-response; nothing to answer

    @classmethod
    def _send_json(cls, request: BaseHTTPRequestHandler, status: int,
                   doc: dict,
                   headers: Optional[Dict[str, str]] = None) -> None:
        cls._send(request, status, "application/json; charset=utf-8",
                  json.dumps(doc).encode(), headers)

    def describe(self) -> str:
        """Human hint appended to 404 bodies; owners override with
        their route listing."""
        return self._name

    @property
    def port(self) -> int:
        """The actually-bound port (differs from the requested one only
        when constructed with port 0 — tests use the ephemeral bind)."""
        return self._httpd.server_address[1]

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def json_response(doc: dict, status: int = 200,
                  headers: Optional[Dict[str, str]] = None) -> Response:
    return Response(json.dumps(doc).encode(),
                    "application/json; charset=utf-8", status, headers)
