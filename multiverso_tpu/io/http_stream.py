"""HTTP(S) stream scheme: checkpoints and corpora over the network.

The second StreamFactory scheme, playing the role of the reference's
``hdfs://`` backend (ref: include/multiverso/io/hdfs_stream.h:10-60,
src/io/io.cpp:8-21 — a remote object store behind the same Stream
interface). HDFS/libhdfs does not exist on TPU hosts; the natural remote
store for a TPU pod is an HTTP(S) object endpoint (GCS/S3 interop
endpoints speak exactly this), implemented here with the standard
library only:

- read: streamed chunked ``GET``;
- write: buffered locally, one ``PUT`` on close (object stores are
  whole-object, like the reference's HDFS append-only streams).

Registered for ``http://`` and ``https://`` on import (the reference
registers hdfs behind a build flag; importing this module is the
equivalent opt-in).
"""

from __future__ import annotations

import io as _io
import urllib.request
from typing import Optional

from .stream import Stream, StreamFactory

_CHUNK = 1 << 20


class _HttpReadStream(Stream):
    def __init__(self, uri: str):
        self._resp = urllib.request.urlopen(uri)  # noqa: S310 - scheme-gated
        super().__init__(self._resp, uri)
        self._closed = False

    def read(self, size: int = -1) -> bytes:
        return self._resp.read(None if size is None or size < 0 else size)

    def write(self, data: bytes) -> int:
        raise IOError("http stream opened for read")

    def good(self) -> bool:
        return not self._closed

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self._closed = True
        self._resp.close()


class _HttpWriteStream(Stream):
    """Buffer locally; a single PUT ships the object on close."""

    def __init__(self, uri: str):
        self._buf = _io.BytesIO()
        super().__init__(self._buf, uri)
        self._uri = uri
        self._closed = False

    def read(self, size: int = -1) -> bytes:
        raise IOError("http stream opened for write")

    def good(self) -> bool:
        return not self._closed

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        payload = self._buf.getvalue()
        req = urllib.request.Request(self._uri, data=payload, method="PUT")
        req.add_header("Content-Type", "application/octet-stream")
        with urllib.request.urlopen(req):  # noqa: S310 - scheme-gated
            pass


def _open_http(uri: str, mode: str) -> Stream:
    if "w" in mode:
        return _HttpWriteStream(uri)
    return _HttpReadStream(uri)


def register() -> None:
    StreamFactory.register_scheme("http", _open_http)
    StreamFactory.register_scheme("https", _open_http)


register()
