"""HTTP(S) stream scheme: checkpoints and corpora over the network.

The second StreamFactory scheme, playing the role of the reference's
``hdfs://`` backend (ref: include/multiverso/io/hdfs_stream.h:10-60,
src/io/io.cpp:8-21 — a remote object store behind the same Stream
interface). HDFS/libhdfs does not exist on TPU hosts; the natural remote
store for a TPU pod is an HTTP(S) object endpoint (GCS/S3 interop
endpoints speak exactly this), implemented here with the standard
library only:

- read: streamed chunked ``GET``;
- write: buffered locally, one ``PUT`` on close (object stores are
  whole-object, like the reference's HDFS append-only streams).

Registered for ``http://`` and ``https://`` on import (the reference
registers hdfs behind a build flag; importing this module is the
equivalent opt-in).
"""

from __future__ import annotations

import io as _io
import os
import urllib.request
from typing import Callable, Dict, Optional, Union

from .stream import Stream, StreamFactory

_CHUNK = 1 << 20

# -- authentication hook (the reference's hdfs backend was an
# authenticated store, ref: include/multiverso/io/hdfs_stream.h:10-60;
# real GCS/S3 interop endpoints need credential headers too).
# Either a static header dict or a callable uri -> headers (for signed
# URLs / refreshing tokens). The MV_HTTP_AUTH_TOKEN env var provides a
# zero-code Bearer default.
_auth: Optional[Union[Dict[str, str],
                      Callable[[str], Dict[str, str]]]] = None


def set_auth(auth: Optional[Union[Dict[str, str],
                                  Callable[[str], Dict[str, str]]]]
             ) -> None:
    """Install auth headers for all http(s) streams: a header dict, a
    ``uri -> headers`` callable, or None to clear."""
    global _auth
    _auth = auth


def _auth_headers(uri: str) -> Dict[str, str]:
    if callable(_auth):
        return dict(_auth(uri))
    headers = dict(_auth) if _auth else {}
    token = os.environ.get("MV_HTTP_AUTH_TOKEN")
    if token and "Authorization" not in headers:
        # Scope the ambient token: only the host named by
        # MV_HTTP_AUTH_HOST, or any https endpoint when unset — never
        # cleartext http, where a bearer token would leak to whatever
        # host (or redirect target) the uri points at. Cross-host or
        # http use cases must opt in explicitly via set_auth.
        from urllib.parse import urlsplit
        parts = urlsplit(uri)
        wanted = os.environ.get("MV_HTTP_AUTH_HOST")
        if (parts.hostname == wanted if wanted
                else parts.scheme == "https"):
            headers["Authorization"] = f"Bearer {token}"
    return headers


def _request(uri: str, **kw) -> urllib.request.Request:
    req = urllib.request.Request(uri, **kw)
    for name, value in _auth_headers(uri).items():
        req.add_header(name, value)
    return req


class _HttpReadStream(Stream):
    def __init__(self, uri: str):
        self._resp = urllib.request.urlopen(  # noqa: S310 - scheme-gated
            _request(uri))
        super().__init__(self._resp, uri)
        self._closed = False

    def read(self, size: int = -1) -> bytes:
        return self._resp.read(None if size is None or size < 0 else size)

    def write(self, data: bytes) -> int:
        raise IOError("http stream opened for read")

    def good(self) -> bool:
        return not self._closed

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self._closed = True
        self._resp.close()


class _HttpWriteStream(Stream):
    """Buffer locally; a single PUT ships the object on close."""

    def __init__(self, uri: str):
        self._buf = _io.BytesIO()
        super().__init__(self._buf, uri)
        self._uri = uri
        self._closed = False

    def read(self, size: int = -1) -> bytes:
        raise IOError("http stream opened for write")

    def good(self) -> bool:
        return not self._closed

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        payload = self._buf.getvalue()
        req = _request(self._uri, data=payload, method="PUT")
        req.add_header("Content-Type", "application/octet-stream")
        with urllib.request.urlopen(req):  # noqa: S310 - scheme-gated
            pass


def _open_http(uri: str, mode: str) -> Stream:
    if "w" in mode:
        return _HttpWriteStream(uri)
    return _HttpReadStream(uri)


def register() -> None:
    StreamFactory.register_scheme("http", _open_http)
    StreamFactory.register_scheme("https", _open_http)


register()
