"""HTTP(S) stream scheme: checkpoints and corpora over the network.

The second StreamFactory scheme, playing the role of the reference's
``hdfs://`` backend (ref: include/multiverso/io/hdfs_stream.h:10-60,
src/io/io.cpp:8-21 — a remote object store behind the same Stream
interface). HDFS/libhdfs does not exist on TPU hosts; the natural remote
store for a TPU pod is an HTTP(S) object endpoint (GCS/S3 interop
endpoints speak exactly this), implemented here with the standard
library only:

- read: streamed chunked ``GET``;
- write: buffered locally, one ``PUT`` on close (object stores are
  whole-object, like the reference's HDFS append-only streams).

Registered for ``http://`` and ``https://`` on import (the reference
registers hdfs behind a build flag; importing this module is the
equivalent opt-in).
"""

from __future__ import annotations

import io as _io
import os
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional, Union

from .stream import Stream, StreamFactory

_CHUNK = 1 << 20

# -- authentication hook (the reference's hdfs backend was an
# authenticated store, ref: include/multiverso/io/hdfs_stream.h:10-60;
# real GCS/S3 interop endpoints need credential headers too).
# Either a static header dict or a callable uri -> headers (for signed
# URLs / refreshing tokens). The MV_HTTP_AUTH_TOKEN env var provides a
# zero-code Bearer default.
_auth: Optional[Union[Dict[str, str],
                      Callable[[str], Dict[str, str]]]] = None


def set_auth(auth: Optional[Union[Dict[str, str],
                                  Callable[[str], Dict[str, str]]]]
             ) -> None:
    """Install auth headers for all http(s) streams: a header dict, a
    ``uri -> headers`` callable, or None to clear."""
    global _auth
    _auth = auth


def _scoped_env_headers(uri: str) -> Dict[str, str]:
    """The ambient env token, STRICTLY host-scoped: it attaches only to
    requests for the host explicitly named by MV_HTTP_AUTH_HOST. With no
    host set the token is ignored — an any-https default would hand a
    bearer token to whatever endpoint a uri (or a redirect target)
    happens to name. Cleartext http is refused too (an on-path observer
    would read the token) except to loopback, where there is no path to
    observe — the standard dev-server carve-out. Multi-host or
    plain-http use cases must opt in explicitly via set_auth. Because
    this scope check is per-uri, it is safe to re-apply to a redirect
    target."""
    token = os.environ.get("MV_HTTP_AUTH_TOKEN")
    if not token:
        return {}
    from urllib.parse import urlsplit
    parts = urlsplit(uri)
    wanted = os.environ.get("MV_HTTP_AUTH_HOST")
    secure = parts.scheme == "https" or parts.hostname in (
        "localhost", "127.0.0.1", "::1")
    if wanted and parts.hostname == wanted and secure:
        return {"Authorization": f"Bearer {token}"}
    return {}


def _auth_headers(uri: str) -> Dict[str, str]:
    if callable(_auth):
        return dict(_auth(uri))
    headers = dict(_auth) if _auth else {}
    if "Authorization" not in headers:
        headers.update(_scoped_env_headers(uri))
    return headers


class _AuthScopedRedirectHandler(urllib.request.HTTPRedirectHandler):
    """urllib's default handler forwards ALL headers across redirects —
    including Authorization, so even a host-scoped token would leak to an
    arbitrary cross-host redirect target. Strip it whenever the redirect
    leaves the original host."""

    def redirect_request(self, req, fp, code, msg, headers, newurl):
        new = super().redirect_request(req, fp, code, msg, headers, newurl)
        if new is not None:
            from urllib.parse import urlsplit

            def origin(url):
                # Full origin, not just hostname: a same-host https->http
                # downgrade would re-send the token in cleartext, and
                # another port on the same host is another service.
                p = urlsplit(url)
                port = p.port if p.port is not None \
                    else {"https": 443, "http": 80}.get(p.scheme)
                return (p.scheme, p.hostname, port)

            if origin(newurl) != origin(req.full_url):
                # Strip EVERY credential the auth hook installed for the
                # original url (a static set_auth dict may carry
                # X-Api-Key/Cookie-style headers, not just the Bearer
                # form), plus Authorization itself.
                for name in {"Authorization",
                             *(k.capitalize()
                               for k in _auth_headers(req.full_url))}:
                    new.headers.pop(name, None)
                # Re-consult the per-uri auth forms FOR THE TARGET: the
                # set_auth CALLABLE (it inspects the url and mints
                # headers per host — presigned/CDN redirect patterns)
                # and the host-scoped env token (its scope check is
                # per-uri, so it re-attaches exactly when the redirect
                # lands on MV_HTTP_AUTH_HOST). A static set_auth dict is
                # NOT re-applied — it would return the original
                # credentials unconditionally and recreate the leak.
                fresh = dict(_auth(newurl)) if callable(_auth) \
                    else _scoped_env_headers(newurl)
                for name, value in fresh.items():
                    if name.capitalize() not in new.headers:
                        new.add_header(name, value)
        return new


_opener = urllib.request.build_opener(_AuthScopedRedirectHandler)


def _urlopen(req: urllib.request.Request):
    return _opener.open(req)


def _request(uri: str, **kw) -> urllib.request.Request:
    req = urllib.request.Request(uri, **kw)
    for name, value in _auth_headers(uri).items():
        req.add_header(name, value)
    return req


class _HttpReadStream(Stream):
    def __init__(self, uri: str):
        self._resp = _urlopen(  # noqa: S310 - scheme-gated
            _request(uri))
        super().__init__(self._resp, uri)
        self._closed = False

    def read(self, size: int = -1) -> bytes:
        return self._resp.read(None if size is None or size < 0 else size)

    def write(self, data: bytes) -> int:
        raise IOError("http stream opened for read")

    def good(self) -> bool:
        return not self._closed

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self._closed = True
        self._resp.close()


class _HttpWriteStream(Stream):
    """Buffer locally; a single PUT ships the object on close."""

    def __init__(self, uri: str):
        self._buf = _io.BytesIO()
        super().__init__(self._buf, uri)
        self._uri = uri
        self._closed = False

    def read(self, size: int = -1) -> bytes:
        raise IOError("http stream opened for write")

    def good(self) -> bool:
        return not self._closed

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        payload = self._buf.getvalue()
        req = _request(self._uri, data=payload, method="PUT")
        req.add_header("Content-Type", "application/octet-stream")
        try:
            with _urlopen(req):  # noqa: S310 - scheme-gated
                pass
        except urllib.error.HTTPError as exc:
            # The whole buffered object rides this one PUT: a rejection
            # here means NOTHING was stored, and the generic HTTPError
            # ("HTTP Error 507: ...") names neither the uri nor the
            # fact that bytes were lost — the caller (checkpoint /
            # snapshot writers) needs both to act on the failure.
            raise IOError(
                f"http write stream: PUT {self._uri} failed with "
                f"status {exc.code} ({exc.reason}); {len(payload)} "
                f"buffered bytes were NOT stored") from exc


def _open_http(uri: str, mode: str) -> Stream:
    if "w" in mode:
        return _HttpWriteStream(uri)
    return _HttpReadStream(uri)


def register() -> None:
    StreamFactory.register_scheme("http", _open_http)
    StreamFactory.register_scheme("https", _open_http)


register()
