"""Stream IO and checkpointing."""

from .stream import (CheckpointError, Stream,  # noqa: F401
                     StreamFactory, TextReader, load_checkpoint,
                     read_bytes_or_none, save_checkpoint,
                     write_bytes_atomic)
