"""Stream IO and checkpointing."""

from .stream import (Stream, StreamFactory, TextReader,  # noqa: F401
                     load_checkpoint, save_checkpoint)
