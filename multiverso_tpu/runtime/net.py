"""Transport layer: abstract NetInterface + in-process fabric.

TPU-native re-design of the reference's transport stack
(ref: include/multiverso/net.h:15-49, src/net.cpp:13-24). The reference
selects MPI or ZeroMQ point-to-point backends at compile time; on TPU the
*data plane* (tensor traffic) rides XLA collectives over ICI inside jitted
programs and never touches this layer — what remains is the *control plane*
(registration, barriers, table-request routing between ranks), for which we
provide:

- ``LocalFabric``/``LocalNet``: an in-process mesh of mailbox queues. One
  Python process hosts N virtual ranks (threads), which is both the
  single-process degenerate mode (rank 0 = worker+server, the reference's
  key testing trick, ref: Test/unittests/multiverso_env.h:9-31) and the
  equivalent of the reference's ``mpirun -np N`` single-host integration
  tests — without needing MPI.
- Multi-host deployment maps to ``jax.distributed`` + one LocalFabric per
  host; cross-host tensor traffic is XLA-over-DCN inside the jitted step,
  so a cross-host control transport is only needed for table RPC (a TCP
  message-stream backend implementing this same interface — planned).

Messages are delivered whole (no serialization needed in-process; device
arrays ride inside Blobs with zero copies).
"""

from __future__ import annotations

import threading
from typing import List, Optional

from ..core.message import Message
from ..util.mt_queue import MtQueue


class NetInterface:
    """Abstract transport (ref: include/multiverso/net.h:15-49)."""

    #: True when every rank shares this OS process (messages pass by
    #: reference, so Blob payloads — including device arrays — arrive
    #: zero-copy). Transports that serialize to a wire set this False.
    in_process = False

    @property
    def rank(self) -> int:
        raise NotImplementedError

    @property
    def size(self) -> int:
        raise NotImplementedError

    def send(self, msg: Message) -> int:
        """Dispatch a message toward ``msg.dst``; returns bytes queued."""
        raise NotImplementedError

    def recv(self, timeout: Optional[float] = None) -> Optional[Message]:
        """Block for the next inbound message; None once finalized."""
        raise NotImplementedError

    def finalize(self) -> None:
        raise NotImplementedError

    def interrupt_recv(self) -> None:
        """Make one pending/future ``recv`` return None without tearing the
        endpoint down (used for non-finalizing shutdown)."""
        self.finalize()

    # -- recv ownership: exactly one consumer may drain the endpoint --
    def acquire_recv_owner(self) -> None:
        """Mark this endpoint as drained by an actor (the communicator's
        recv thread). While owned, the default transport-level allreduce
        must refuse to run: it would race the recv thread for messages and
        corrupt both streams."""
        self._recv_owned = True

    def release_recv_owner(self) -> None:
        self._recv_owned = False

    def allreduce(self, array: "np.ndarray") -> "np.ndarray":
        """Sum-allreduce a host array across ranks (the transport-level
        collective behind MV_Aggregate, ref: mpi_net.h:147-151). The
        default drives the AllreduceEngine over this endpoint's raw
        send/recv (ma mode only — the PS actors must not own the endpoint);
        transports with a native collective override this (LocalNet uses
        shared memory, an MPI-like transport would use its own).

        One engine is cached per endpoint: its stash of early-arriving
        messages must survive across calls, since in back-to-back
        allreduces a fast peer's next-call message (tags restart at fixed
        bases) can be drained during the previous call and would otherwise
        be lost, deadlocking the next collective."""
        if getattr(self, "_recv_owned", False):
            raise RuntimeError(
                "transport-level allreduce (mv.aggregate) requires ma mode "
                "on this transport: the PS actors own the endpoint's recv "
                "stream (start with -ma=true, ref: src/net.cpp:27-35)")
        from .allreduce_engine import AllreduceEngine
        engine = getattr(self, "_allreduce_engine", None)
        if engine is None:
            engine = self._allreduce_engine = AllreduceEngine(self)
        return engine.allreduce(array)

    @property
    def name(self) -> str:
        return type(self).__name__


_RECV_INTERRUPT = object()  # sentinel: unblocks recv without finalizing


class LocalFabric:
    """Shared in-process wire: one inbox queue per virtual rank."""

    def __init__(self, size: int):
        if size < 1:
            raise ValueError("fabric needs >= 1 rank")
        self._size = size
        self._inboxes: List[MtQueue] = [MtQueue() for _ in range(size)]
        self._lock = threading.Lock()
        # Shared-memory allreduce state (one in-flight collective at a time,
        # like the reference's serialized MPI_Allreduce).
        self._ar_cond = threading.Condition()
        self._ar_acc = None
        self._ar_result = None
        self._ar_joined = 0
        self._ar_generation = 0

    @property
    def size(self) -> int:
        return self._size

    def endpoint(self, rank: int) -> "LocalNet":
        if not 0 <= rank < self._size:
            raise ValueError(f"rank {rank} out of range [0,{self._size})")
        return LocalNet(self, rank)

    def deliver(self, msg: Message) -> None:
        self._inboxes[msg.dst].push(msg)

    def inbox(self, rank: int) -> MtQueue:
        return self._inboxes[rank]

    def allreduce(self, array) -> "np.ndarray":
        import numpy as np
        contribution = np.asarray(array)
        with self._ar_cond:
            generation = self._ar_generation
            self._ar_acc = contribution.copy() if self._ar_acc is None \
                else self._ar_acc + contribution
            self._ar_joined += 1
            if self._ar_joined == self._size:
                self._ar_result = self._ar_acc
                self._ar_acc = None
                self._ar_joined = 0
                self._ar_generation += 1
                self._ar_cond.notify_all()
            else:
                if not self._ar_cond.wait_for(
                        lambda: self._ar_generation > generation,
                        timeout=120):
                    raise TimeoutError(
                        "allreduce: peers never joined the collective")
            # Per-rank copy: a caller mutating its result in place must not
            # corrupt what sibling ranks see.
            return self._ar_result.copy()


class LocalNet(NetInterface):
    in_process = True

    def __init__(self, fabric: LocalFabric, rank: int):
        self._fabric = fabric
        self._rank = rank

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def size(self) -> int:
        return self._fabric.size

    def send(self, msg: Message) -> int:
        if not 0 <= msg.dst < self.size:
            raise ValueError(f"bad dst rank {msg.dst}")
        self._fabric.deliver(msg)
        return sum(b.size for b in msg.data) + 32

    def recv(self, timeout: Optional[float] = None) -> Optional[Message]:
        item = self._fabric.inbox(self._rank).pop(timeout=timeout)
        if item is _RECV_INTERRUPT:
            return None
        return item

    def finalize(self) -> None:
        self._fabric.inbox(self._rank).exit()

    def interrupt_recv(self) -> None:
        self._fabric.inbox(self._rank).push(_RECV_INTERRUPT)

    def allreduce(self, array):
        return self._fabric.allreduce(array)
