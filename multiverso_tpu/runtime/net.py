"""Transport layer: abstract NetInterface + in-process fabric.

TPU-native re-design of the reference's transport stack
(ref: include/multiverso/net.h:15-49, src/net.cpp:13-24). The reference
selects MPI or ZeroMQ point-to-point backends at compile time; on TPU the
*data plane* (tensor traffic) rides XLA collectives over ICI inside jitted
programs and never touches this layer — what remains is the *control plane*
(registration, barriers, table-request routing between ranks), for which we
provide:

- ``LocalFabric``/``LocalNet``: an in-process mesh of mailbox queues. One
  Python process hosts N virtual ranks (threads), which is both the
  single-process degenerate mode (rank 0 = worker+server, the reference's
  key testing trick, ref: Test/unittests/multiverso_env.h:9-31) and the
  equivalent of the reference's ``mpirun -np N`` single-host integration
  tests — without needing MPI.
- Multi-host deployment maps to ``jax.distributed`` + one LocalFabric per
  host; cross-host tensor traffic is XLA-over-DCN inside the jitted step,
  so a cross-host control transport is only needed for table RPC: the TCP
  message-stream backend (``tcp.py``) implements this interface, and
  ``shm.py`` wraps it so frames between same-host peers travel through
  per-pair shared-memory rings instead of kernel loopback (negotiated per
  peer at registration; docs/MEMORY.md "Below the socket").

Messages are delivered whole (no serialization needed in-process; device
arrays ride inside Blobs with zero copies).
"""

from __future__ import annotations

from typing import List, Optional

from ..core.message import Message
from ..util.lock_witness import named_condition, named_lock
from ..util.mt_queue import MtQueue


class PeerLostError(RuntimeError):
    """A peer endpoint died while the mesh was supposed to be up: a
    writer thread hit a broken connection, a reader saw a dirty close,
    or the controller's liveness monitor declared the rank dead.
    Raised to senders blocked on that peer (instead of leaving them
    enqueueing into a dead connection) and to table ``wait`` calls whose
    request was in flight toward it. RETRYABLE: with ``-rpc_retry_max``
    set, sync table calls back off and re-issue — a restarted peer that
    rejoins then serves the retry."""


class NetInterface:
    """Abstract transport (ref: include/multiverso/net.h:15-49).

    Transports that can detect peer death (tcp.py) expose an
    ``on_peer_lost`` callback attribute: called with the dead peer's
    rank when known, or ``None`` when a connection died before
    identifying itself. The Zoo installs its failure handler there at
    start."""

    #: True when every rank shares this OS process (messages pass by
    #: reference, so Blob payloads — including device arrays — arrive
    #: zero-copy). Transports that serialize to a wire set this False.
    in_process = False

    @property
    def rank(self) -> int:
        raise NotImplementedError

    @property
    def size(self) -> int:
        raise NotImplementedError

    def send(self, msg: Message) -> int:
        """Dispatch a message toward ``msg.dst``; returns bytes queued."""
        raise NotImplementedError

    def send_async(self, msg: Message) -> int:
        """Queue a message for delivery and return immediately; returns
        bytes queued. Per-destination FIFO order is preserved, both among
        async sends and relative to later blocking ``send`` calls to the
        same peer. The caller must not mutate the message's payload until
        the frame is on the wire (``flush_sends``) — the allreduce engine
        satisfies this by never rewriting a segment it has queued.

        Default: alias of the blocking ``send`` (correct on any
        transport; in-process delivery is already instantaneous).
        Transports with real wire time override this with a writer
        thread so multiple frames can be in flight (tcp.py)."""
        return self.send(msg)

    def flush_sends(self, dst: Optional[int] = None,
                    timeout: Optional[float] = None) -> None:
        """Block until queued async sends (to ``dst``, or all peers) are
        on the wire. No-op on transports whose send is synchronous."""

    #: Total payload bytes this endpoint has pushed toward peers
    #: (wire-framing included where the transport serializes). Bench
    #: instrumentation; transports that care override/maintain it.
    @property
    def bytes_sent(self) -> int:
        return 0

    def recv(self, timeout: Optional[float] = None) -> Optional[Message]:
        """Block for the next inbound message; None once finalized."""
        raise NotImplementedError

    def finalize(self) -> None:
        raise NotImplementedError

    def interrupt_recv(self) -> None:
        """Make one pending/future ``recv`` return None without tearing the
        endpoint down (used for non-finalizing shutdown)."""
        self.finalize()

    # -- recv ownership: exactly one consumer may drain the endpoint --
    def acquire_recv_owner(self) -> None:
        """Mark this endpoint as drained by an actor (the communicator's
        recv thread). While owned, the default transport-level allreduce
        must refuse to run: it would race the recv thread for messages and
        corrupt both streams."""
        self._recv_owned = True

    def release_recv_owner(self) -> None:
        self._recv_owned = False

    def allreduce(self, array: "np.ndarray",
                  slot: Optional[int] = None) -> "np.ndarray":
        """Sum-allreduce a host array across ranks (the transport-level
        collective behind MV_Aggregate, ref: mpi_net.h:147-151). The
        default drives the AllreduceEngine over this endpoint's raw
        send/recv (ma mode only — the PS actors must not own the endpoint);
        transports with a native collective override this (LocalNet uses
        shared memory, an MPI-like transport would use its own).

        One engine is cached per endpoint: its stash of early-arriving
        messages must survive across calls, since in back-to-back
        allreduces a fast peer's next-call message can be drained during
        the previous call and would otherwise be lost, deadlocking the
        next collective (per-call generation stamps in the msg_id keep
        such early frames from ever cross-matching).

        FIFO-serialized per endpoint: collectives are matched
        POSITIONALLY across ranks, so execution order must equal
        application call order on every rank. Each call runs in turn
        behind a ticket — taken here on the calling thread, or
        reserved earlier via ``reserve_collective_slot`` and passed as
        ``slot`` (how model_average_async pins its place in line from
        the submitting thread while the work happens on a worker)."""
        if getattr(self, "_recv_owned", False):
            raise RuntimeError(
                "transport-level allreduce (mv.aggregate) requires ma mode "
                "on this transport: the PS actors own the endpoint's recv "
                "stream (start with -ma=true, ref: src/net.cpp:27-35)")
        from .allreduce_engine import AllreduceEngine

        def run():
            engine = getattr(self, "_allreduce_engine", None)
            if engine is None:
                engine = self._allreduce_engine = AllreduceEngine(self)
            return engine.allreduce(array)

        return self._run_collective(run, slot)

    def sharded_average(self, array: "np.ndarray",
                        slot: Optional[int] = None) -> "np.ndarray":
        """Cross-rank MEAN with sharded reduce state: each rank
        reduce-scatters sparse codec frames for the shard it owns,
        divides that shard locally, and allgathers the averaged
        segments (AllreduceEngine.sharded_average — the model-average
        fast path; docs/ALLREDUCE.md). Same ma-mode contract and
        per-endpoint FIFO ticketing as ``allreduce``: sharded averages
        and allreduces issued on one endpoint are matched positionally
        across ranks in call order."""
        if getattr(self, "_recv_owned", False):
            raise RuntimeError(
                "transport-level sharded_average requires ma mode on "
                "this transport: the PS actors own the endpoint's recv "
                "stream (start with -ma=true, ref: src/net.cpp:27-35)")
        from .allreduce_engine import AllreduceEngine

        def run():
            engine = getattr(self, "_allreduce_engine", None)
            if engine is None:
                engine = self._allreduce_engine = AllreduceEngine(self)
            return engine.sharded_average(array)

        return self._run_collective(run, slot)

    # -- per-endpoint collective FIFO --
    def _collective_fifo(self) -> dict:
        # Lazily created; the instance-dict setdefault is atomic under
        # the GIL. The fast-path get avoids building a throwaway
        # dict + Condition per call once initialized (setdefault
        # evaluates its default eagerly).
        state = self.__dict__.get("_coll_fifo")
        if state is None:
            state = self.__dict__.setdefault(
                "_coll_fifo",
                {"next": 0, "serving": 0,
                 "cond": named_condition(f"{self.name}.collective_fifo")})
        return state

    def reserve_collective_slot(self) -> int:
        """Take the next FIFO ticket on THIS thread. Pass it to a later
        ``allreduce(..., slot=...)`` call (possibly from another
        thread) to run that collective in the order the slot was
        reserved rather than the order workers get scheduled."""
        state = self._collective_fifo()
        with state["cond"]:
            slot = state["next"]
            state["next"] += 1
        return slot

    def _run_collective(self, fn, slot: Optional[int] = None):
        state = self._collective_fifo()
        if slot is None:
            slot = self.reserve_collective_slot()
        with state["cond"]:
            state["cond"].wait_for(lambda: state["serving"] == slot)
        try:
            return fn()
        finally:
            with state["cond"]:
                state["serving"] += 1
                state["cond"].notify_all()

    @property
    def name(self) -> str:
        return type(self).__name__


_RECV_INTERRUPT = object()  # sentinel: unblocks recv without finalizing


class LocalFabric:
    """Shared in-process wire: one inbox queue per virtual rank."""

    def __init__(self, size: int):
        if size < 1:
            raise ValueError("fabric needs >= 1 rank")
        self._size = size
        self._inboxes: List[MtQueue] = [
            MtQueue(name=f"fabric.inbox[{r}]") for r in range(size)]
        self._lock = named_lock("fabric.lock")
        # Shared-memory allreduce state (one in-flight collective at a time,
        # like the reference's serialized MPI_Allreduce).
        self._ar_cond = named_condition("fabric.allreduce")
        self._ar_parts = {}  # rank -> contribution for the open collective
        self._ar_result = None
        self._ar_generation = 0

    @property
    def size(self) -> int:
        return self._size

    def endpoint(self, rank: int) -> "LocalNet":
        if not 0 <= rank < self._size:
            raise ValueError(f"rank {rank} out of range [0,{self._size})")
        return LocalNet(self, rank)

    def deliver(self, msg: Message) -> None:
        self._inboxes[msg.dst].push(msg)

    def inbox(self, rank: int) -> MtQueue:
        return self._inboxes[rank]

    def allreduce(self, array, rank: int = -1) -> "np.ndarray":
        import numpy as np
        contribution = np.asarray(array)
        with self._ar_cond:
            generation = self._ar_generation
            # Contributions are kept per rank and summed in RANK order at
            # completion: summing in thread-arrival order would make the
            # float result depend on scheduling, and the MA overlap tests
            # assert sync-vs-async trainer runs are bit-identical.
            self._ar_parts[len(self._ar_parts) if rank < 0 else rank] = \
                contribution
            if len(self._ar_parts) == self._size:
                acc = None
                for r in sorted(self._ar_parts):
                    part = self._ar_parts[r]
                    acc = part.copy() if acc is None else acc + part
                self._ar_result = acc
                self._ar_parts = {}
                self._ar_generation += 1
                self._ar_cond.notify_all()
            else:
                if not self._ar_cond.wait_for(
                        lambda: self._ar_generation > generation,
                        timeout=120):
                    raise TimeoutError(
                        "allreduce: peers never joined the collective")
            # Per-rank copy: a caller mutating its result in place must not
            # corrupt what sibling ranks see.
            return self._ar_result.copy()


class LocalNet(NetInterface):
    in_process = True

    def __init__(self, fabric: LocalFabric, rank: int):
        self._fabric = fabric
        self._rank = rank

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def size(self) -> int:
        return self._fabric.size

    def send(self, msg: Message) -> int:
        if not 0 <= msg.dst < self.size:
            raise ValueError(f"bad dst rank {msg.dst}")
        self._fabric.deliver(msg)
        return sum(b.size for b in msg.data) + 32

    def recv(self, timeout: Optional[float] = None) -> Optional[Message]:
        item = self._fabric.inbox(self._rank).pop(timeout=timeout)
        if item is _RECV_INTERRUPT:
            return None
        return item

    def finalize(self) -> None:
        self._fabric.inbox(self._rank).exit()

    def interrupt_recv(self) -> None:
        self._fabric.inbox(self._rank).push(_RECV_INTERRUPT)

    def allreduce(self, array, slot=None):
        return self._run_collective(
            lambda: self._fabric.allreduce(array, self._rank), slot)

    def sharded_average(self, array, slot=None):
        # Shared memory has no wire to save and no per-rank memory
        # budget to shard (every virtual rank is one process): the
        # native rank-ordered fabric sum + divide is the same
        # deterministic math with none of the frame round trips.
        return self._run_collective(
            lambda: self._fabric.allreduce(array, self._rank)
            / self.size, slot)
