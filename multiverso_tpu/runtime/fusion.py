"""Server-side request fusion: drain the mailbox, one device program
per (table, op) group (docs/SERVER_ENGINE.md).

Every inbound Get/Add costs the server actor one mailbox pop plus one
jitted XLA dispatch — a fixed launch cost that dominates small-row
traffic. When the mailbox holds more than one message, the server
drains a bounded batch (``MtQueue.pop_batch``, capped by
``-server_fuse_max`` / ``-server_fuse_bytes``) and fuses compatible
requests: eligible Get/Add/BatchAdd units group by (table, op) and
each group executes ONE device program — a concatenated-id gather with
cross-request row dedup for Gets, a concatenated scatter-add (stateless
rules sum duplicate ids inside the program) for Adds.

The planner in this module is pure bookkeeping — no device work, no
table state — so its invariants are unit-testable in isolation:

* **Barriers.** Any message that cannot join a fused window (control,
  shard, replica, fwd traffic — or a Get/Add the table declares
  ineligible via ``ServerTable.fuse_eligible``) is a barrier: every
  pending group executes and replies before the barrier dispatches
  through the ordinary serial handler.
* **Per-table op exclusivity.** Within one window a table holds only
  ONE op kind; a Get arriving for a table with pending Adds (or vice
  versa) flushes the window first. Groups are therefore
  order-independent: fused Gets observe exactly the adds that preceded
  them (bit-identity), fused Adds commute only with each other
  (sum-equivalence under a deterministic arrival-order fold).
* **Reply order.** Replies are deferred and emitted in arrival order
  at each barrier (and at batch end); a parent Request_BatchAdd's
  single batched ack waits for all of its sub-adds.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from ..core.message import Message, MsgType, unpack_add_batch
from ..util.configure import define_int

define_int("server_fuse_max", 16,
           "max requests the server actor drains from its mailbox per "
           "batch for request fusion (docs/SERVER_ENGINE.md); 1 "
           "disables fusion (strict one-message-at-a-time dispatch). "
           "Force-disabled in -sync mode: the BSP vector clocks count "
           "one request per worker per step")
define_int("server_fuse_bytes", 16 << 20,
           "byte cap on a drained fusion batch (payload bytes, summed "
           "over messages); the first message always pops regardless "
           "of size, so the cap bounds the batch tail, not a single "
           "oversized request")


class PartialFuseError(RuntimeError):
    """``process_fused_add`` failed after ``applied`` requests were
    already folded into table state. The server bumps the version for
    the applied prefix and replays only the unapplied tail serially —
    replaying an applied request would double-count its delta.
    Implementations that parse/validate every request BEFORE the first
    state mutation raise plain exceptions instead (nothing applied,
    the whole group replays)."""

    def __init__(self, applied: int, cause: BaseException):
        super().__init__(str(cause))
        self.applied = int(applied)
        self.cause = cause


class FuseEntry:
    """One fusable unit: a standalone Get/Add request, or one sub-add
    of a Request_BatchAdd (tagged with its parent via ``batch_index``
    so the batched ack reassembles per original message)."""

    __slots__ = ("batch_index", "table_id", "table", "is_get", "blobs",
                 "msg_id", "result", "version", "error")

    def __init__(self, batch_index: int, table_id: int, table,
                 is_get: bool, blobs, msg_id: int):
        self.batch_index = batch_index
        self.table_id = table_id
        self.table = table
        self.is_get = is_get
        self.blobs = blobs
        self.msg_id = msg_id
        self.result = None        # reply blobs (Gets)
        self.version = -1         # post-apply version stamp
        self.error: Optional[BaseException] = None


def message_nbytes(msg: Message) -> int:
    """Payload size of one queued message — the ``size_of`` callable
    for ``MtQueue.pop_batch``'s byte cap."""
    return sum(b.size for b in msg.data)


def classify(server, batch_index: int,
             msg: Message) -> Optional[List[FuseEntry]]:
    """The fusable units of one drained message, or None (barrier).

    A Request_BatchAdd is all-or-nothing: if ANY sub-add is ineligible
    (or the batch fails to unpack) the whole message dispatches
    serially — partial fusion would interleave the batch's own subs
    around a barrier. Table-lookup failures (rejoin gate) are barriers
    too: the serial handler owns the retryable-NACK reply shape.
    """
    t = msg.type_int
    if t in (int(MsgType.Request_Get), int(MsgType.Request_Add)):
        if not msg.data:
            return None  # sync-mode clock tick (empty payload)
        try:
            table = server._table(msg.table_id)
        except Exception:  # noqa: BLE001 - rejoin gap: serial NACK
            return None
        is_get = t == int(MsgType.Request_Get)
        try:
            eligible = table.fuse_eligible(msg.data, is_get)
        except Exception:  # noqa: BLE001 - malformed blobs: the serial
            return None    # handler owns the error-reply shape
        if not eligible:
            return None
        return [FuseEntry(batch_index, msg.table_id, table, is_get,
                          msg.data, msg.msg_id)]
    if t == int(MsgType.Request_BatchAdd):
        try:
            subs = unpack_add_batch(msg)
        except Exception:  # noqa: BLE001 - malformed batch: the
            return None    # serial handler acks every named sub failed
        entries = []
        for sub in subs:
            try:
                table = server._table(sub.table_id)
            except Exception:  # noqa: BLE001
                return None
            try:
                eligible = table.fuse_eligible(sub.data, False)
            except Exception:  # noqa: BLE001 - see above
                return None
            if not eligible:
                return None
            entries.append(FuseEntry(batch_index, sub.table_id, table,
                                     False, sub.data, sub.msg_id))
        return entries or None
    return None


#: One executable unit of a plan: ``("serial", batch_index)`` — flush
#: replies up to here, then dispatch the message through the ordinary
#: handler — or ``("fused", groups)`` with ``groups`` an ordered list
#: of ``(table, is_get, [FuseEntry])``.
PlanStep = Tuple[str, object]


def split_plan(batch: List[Message],
               infos: List[Optional[List[FuseEntry]]]) -> List[PlanStep]:
    """Turn a drained batch + its per-message classification into an
    ordered execution plan enforcing the barrier and per-table
    op-exclusivity invariants (module docstring). Pure: no table or
    device state is touched, so the plan shape is unit-testable with
    stub tables."""
    steps: List[PlanStep] = []
    groups: List[list] = []   # ordered [table, is_get, entries]
    by_key: dict = {}         # (table_id, is_get) -> group
    op_of: dict = {}          # table_id -> is_get in current window

    def flush() -> None:
        if groups:
            steps.append(("fused",
                          [(g[0], g[1], g[2]) for g in groups]))
        groups.clear()
        by_key.clear()
        op_of.clear()

    for i, msg in enumerate(batch):
        entries = infos[i]
        if not entries:
            flush()
            steps.append(("serial", i))
            continue
        for e in entries:
            cur = op_of.get(e.table_id)
            if cur is not None and cur != e.is_get:
                # Opposite op on a table already in the window: the
                # Get must observe the pending Adds (or the Adds must
                # not leak into an already-planned Get) — flush.
                flush()
            op_of[e.table_id] = e.is_get
            key = (e.table_id, e.is_get)
            g = by_key.get(key)
            if g is None:
                g = by_key[key] = [e.table, e.is_get, []]
                groups.append(g)
            g[2].append(e)
    flush()
    return steps
