"""Rank-0 coordination actor: registration + barrier + liveness.

TPU-native equivalent of the reference's ``Controller``
(ref: include/multiverso/controller.h:9-22, src/controller.cpp:12-104).
Two sub-controllers:

- ``BarrierController``: collects one Control_Barrier per rank, then replies
  Control_Reply_Barrier to every sender (ref: src/controller.cpp:12-36).
- ``RegisterController``: collects one Control_Register (carrying the rank's
  declared role) per rank, assigns dense worker_id/server_id in rank order,
  then broadcasts the full node table + counts to every rank
  (ref: src/controller.cpp:38-80).

Fault-tolerance extensions (absent in the reference, SURVEY.md 5.3):

- **rejoin handshake**: once the initial registration round has
  broadcast, a later ``Control_Register`` from an already-known rank is
  a RESTARTED process re-registering (``-rejoin=true`` on its command
  line skips the start barrier). It gets an immediate solo reply with
  the stored node table, and its liveness record is reset.
- **liveness**: every control message a rank sends (register, barrier,
  heartbeat) refreshes its last-seen stamp. With
  ``-heartbeat_interval_s > 0`` each rank runs a ``HeartbeatMonitor``
  thread that pings the controller; the controller's monitor declares a
  rank dead after ``-heartbeat_timeout_s`` of silence and fans a
  ``Control_Dead_Peer`` notice out to the survivors, whose zoos fail
  that rank's in-flight requests with a retryable ``PeerLostError``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..core.blob import Blob
from ..core.message import (PEER_LOST_MARK, Message, MsgType,
                            mark_error)
from ..core.node import Node, is_server, is_worker
# Module-level, not lazy: autotune's define_* calls must run before
# zoo.start's parse_cmd_flags, or -autotune_* flags on a real command
# line are silently left unparsed (the admission.py eager-import
# lesson).
from . import autotune as autotune_mod
from . import metrics as metrics_mod
from . import replica as replica_mod
from ..util import log
from ..util.configure import define_double, get_flag
from ..util.lock_witness import named_condition, named_lock
from . import actor as actors
from . import thread_roles
from .actor import Actor
from .net import PeerLostError

define_double("heartbeat_interval_s", 0.0,
              "liveness heartbeat period: every rank pings the "
              "controller at this interval and the controller declares "
              "silent ranks dead (fanning Control_Dead_Peer out to the "
              "survivors). 0 (default) disables the monitor — crash "
              "detection then rests on the transport's broken-"
              "connection reporting alone")
define_double("heartbeat_timeout_s", 5.0,
              "a rank silent (no register/barrier/heartbeat traffic) "
              "for this long is declared dead by the controller's "
              "liveness monitor; survivors fail its in-flight requests "
              "with PeerLostError. Must comfortably exceed "
              "-heartbeat_interval_s")
define_double("rejoin_grace_s", 30.0,
              "how long a declared-dead rank may stay gone before the "
              "controller fails PENDING BARRIERS with a retryable "
              "PeerLostError (a barrier can never complete without the "
              "dead rank, and without this bound the survivors would "
              "block in barrier() forever when the rank never "
              "restarts). A rejoin within the grace clears the timer "
              "and the parked barrier completes normally")


class Controller(Actor):
    def __init__(self, zoo) -> None:
        super().__init__(actors.CONTROLLER, zoo)
        self._barrier_waiting: List[Message] = []
        self._register_waiting: List[Message] = []
        # Frozen after the initial registration round broadcasts; a
        # late register (rejoin) replies from this immediately.
        self._node_reply: Optional[tuple] = None
        # Liveness: last control traffic per rank (controller-actor
        # thread writes, the HeartbeatMonitor thread reads — guarded by
        # _live_lock; only dict/scalar ops run under it).
        self._live_lock = named_lock(f"controller[r{zoo.rank}].liveness")
        self._last_seen: Dict[int, float] = {}
        self._declared_dead: set = set()
        self._dead_since: Dict[int, float] = {}
        self.register_handler(MsgType.Control_Barrier, self._process_barrier)
        self.register_handler(MsgType.Control_Register, self._process_register)
        self.register_handler(MsgType.Control_Heartbeat,
                              self._process_heartbeat)
        self.register_handler(MsgType.Control_Check_Barriers,
                              self._process_check_barriers)
        # Hot-shard replication: aggregate per-server hot-row reports
        # into the promoted-row map and broadcast it on change
        # (docs/SHARDING.md; runtime/replica.py has the policy).
        self._replicas = replica_mod.ReplicaCoordinator()
        self.register_handler(MsgType.Control_Replica_Report,
                              self._process_replica_report)
        # Observability: per-rank metric reports merge into the cluster
        # view the -metrics_port scrape surface serves
        # (runtime/metrics.py, docs/OBSERVABILITY.md).
        self.metrics = metrics_mod.ClusterMetrics()
        self.register_handler(MsgType.Control_Metrics,
                              self._process_metrics)
        # Live elastic resharding (runtime/shard_map.py,
        # docs/SHARDING.md): the controller owns the authoritative
        # per-table shard maps, drives one migration at a time, and
        # rolls back on endpoint death.
        from . import shard_map as shard_map_mod
        self.reshards = shard_map_mod.ReshardManager(zoo)
        self.register_handler(MsgType.Control_Shard_Done,
                              self._process_shard_done)
        self.register_handler(MsgType.Control_Shard_Request,
                              self._process_shard_request)
        self.register_handler(MsgType.Control_Shard_Tick,
                              self._process_shard_tick)
        # Serving-fleet pressure (docs/SERVING.md fleet section):
        # per-frontend admission stats, aggregated and echoed back so
        # every frontend's /v1/status can expose the fleet view.
        self._serving_fleet: Dict[int, tuple] = {}
        self.register_handler(MsgType.Control_Serving_Report,
                              self._process_serving_report)
        # Closed-loop self-tuning (runtime/autotune.py,
        # docs/AUTOTUNE.md): the manager consumes the ClusterMetrics
        # view above and broadcasts epoch-stamped Control_Config
        # updates; its evaluation thread only starts when
        # -autotune_interval_s > 0 (zoo._start_observability).
        self.autotune = autotune_mod.AutotuneManager(zoo, self.metrics)
        self.register_handler(MsgType.Control_Reply_Config,
                              self._process_config_ack)

    def _process_config_ack(self, msg: Message) -> None:
        """A rank's applied-config watermark (int64 [rank, epoch,
        applied]) — pure observability: the mv_autotune_rank_epoch
        gauges show config convergence per rank."""
        self._note_alive(msg.src)
        if not msg.data:
            return
        ack = msg.data[0].as_array(np.int64)
        if ack.size >= 2:
            self.autotune.note_ack(int(ack[0]), int(ack[1]))

    def _process_shard_done(self, msg: Message) -> None:
        self._note_alive(msg.src)
        desc = msg.data[0].as_array(np.int64)
        self.reshards.on_done(msg.table_id, int(desc[0]),
                              bool(int(desc[1])))

    def _process_shard_request(self, msg: Message) -> None:
        """An application asked for a table respread
        (Zoo.reshard_table): blob = int64 [num_items, kind,
        active server ids...]."""
        self._note_alive(msg.src)
        desc = msg.data[0].as_array(np.int64)
        num_items, kind = int(desc[0]), int(desc[1])
        active = [int(s) for s in desc[2:]]
        if kind == 1:
            # KV tables' frozen layout is the modulo bucket spread —
            # seed the map accordingly before planning
            # (tables/kv_table.py).
            from . import shard_map as shard_map_mod
            import numpy as _np
            if msg.table_id not in self.reshards.maps:
                a = shard_map_mod.initial_active_servers(
                    self._zoo.num_servers)
                bounds = _np.arange(num_items + 1, dtype=_np.int64)
                owners = _np.arange(num_items, dtype=_np.int64) \
                    % max(a, 1)
                self.reshards.maps[msg.table_id] = \
                    shard_map_mod.ShardMap(bounds, owners, epoch=0)
        self.reshards.request(msg.table_id, num_items, active)
        # Even a zero-move plan broadcasts the current map, so the
        # requester's epoch poll completes.
        self.reshards.broadcast(msg.table_id)

    def _process_shard_tick(self, msg: Message) -> None:
        """HeartbeatMonitor nudge (actor thread owns the reshard
        state): abort the in-flight move if an endpoint died, re-send
        a possibly-lost Begin, re-broadcast maps."""
        with self._live_lock:
            dead = list(self._declared_dead)
        for rank in dead:
            self.reshards.on_peer_dead(rank)
        self.reshards.tick()

    def _process_metrics(self, msg: Message) -> None:
        """A rank's periodic metrics snapshot (fire-and-forget; also
        counts as liveness traffic — a reporting rank is an alive
        rank)."""
        self._note_alive(msg.src)
        payload = metrics_mod.parse_report(msg)
        if payload is None:
            log.error("controller: undecodable metrics report from "
                      "rank %d", msg.src)
            return
        self.metrics.ingest(payload)

    #: A frontend whose report is older than this drops out of the
    #: fleet aggregate (it stopped, or its rank died — the aggregate
    #: must not advertise capacity that is gone).
    _FLEET_STALE_S = 15.0

    def _process_serving_report(self, msg: Message) -> None:
        """One frontend's admission pressure ([rank, admitted, shed,
        inflight] int64). Record it, prune stale reporters, and echo
        the fleet aggregate back to the reporter — via send_async (the
        heartbeat-reply discipline: the communicator mailbox can park
        toward a dead peer), or directly into the zoo when the
        reporter shares this rank."""
        self._note_alive(msg.src)
        if not msg.data:
            return
        stats = msg.data[0].as_array(np.int64)
        if stats.size < 4:
            return
        now = time.monotonic()
        self._serving_fleet[int(stats[0])] = (
            int(stats[1]), int(stats[2]), int(stats[3]), now)
        for rank in [r for r, ent in self._serving_fleet.items()
                     if now - ent[3] > self._FLEET_STALE_S]:
            del self._serving_fleet[rank]
        doc = self.serving_fleet_view()
        if msg.src == self._zoo.rank:
            self._zoo.note_serving_fleet(doc)
            return
        import json
        reply = Message(src=self._zoo.rank, dst=msg.src,
                        msg_type=MsgType.Control_Reply_Serving)
        reply.push(Blob(np.frombuffer(
            json.dumps(doc).encode(), dtype=np.uint8)))
        try:
            self._zoo.net.send_async(reply)
        except Exception as exc:  # noqa: BLE001 - an unreachable
            # reporter will re-report or be declared dead
            log.debug("controller: fleet reply to rank %d failed: %s",
                      msg.src, exc)

    def serving_fleet_view(self) -> dict:
        """Fleet-aggregate admission pressure (controller actor
        thread; also read by the local zoo for /v1/status on the
        controller rank — plain dict build over GIL-atomic reads)."""
        now = time.monotonic()
        frontends = {
            str(rank): {"admitted": adm, "shed": shed,
                        "inflight": inf,
                        "age_s": round(now - ts, 3)}
            for rank, (adm, shed, inf, ts)
            in sorted(self._serving_fleet.items())}
        return {
            "frontends": frontends,
            "aggregate": {
                "frontends": len(frontends),
                "admitted": sum(f["admitted"]
                                for f in frontends.values()),
                "shed": sum(f["shed"] for f in frontends.values()),
                "inflight": sum(f["inflight"]
                                for f in frontends.values())}}

    # -- liveness bookkeeping --
    def _note_alive(self, rank: int) -> None:
        with self._live_lock:
            self._last_seen[rank] = time.monotonic()
            self._declared_dead.discard(rank)
            self._dead_since.pop(rank, None)

    def silent_ranks(self, timeout: float) -> List[int]:
        """Ranks not heard from within ``timeout`` and not yet declared
        dead; marks them declared so each death fans out once (a rejoin
        register clears the mark)."""
        now = time.monotonic()
        stale = []
        with self._live_lock:
            for rank, seen in self._last_seen.items():
                if (now - seen > timeout and rank != self._zoo.rank
                        and rank not in self._declared_dead):
                    self._declared_dead.add(rank)
                    self._dead_since[rank] = now
                    stale.append(rank)
        return stale

    def expired_dead_ranks(self, grace: float) -> List[int]:
        """Declared-dead ranks gone longer than ``grace`` without
        re-registering (HeartbeatMonitor thread; read-only)."""
        now = time.monotonic()
        with self._live_lock:
            return [rank for rank, since in self._dead_since.items()
                    if now - since > grace]

    def _process_check_barriers(self, msg: Message) -> None:
        """Monitor-thread nudge (runs HERE on the actor thread, which
        owns ``_barrier_waiting``): fail the pending barrier round when
        a declared-dead rank has overstayed -rejoin_grace_s — the round
        can never complete without it, and the parked ranks would
        otherwise block forever. Each parked entry gets an error reply
        whose text carries PEER_LOST_MARK, so ``zoo.barrier()`` raises
        a retryable PeerLostError (a later rejoin lets the next
        barrier succeed)."""
        if not self._barrier_waiting:
            return
        grace = float(get_flag("rejoin_grace_s"))
        expired = self.expired_dead_ranks(grace)
        if not expired:
            return
        parked = self._barrier_waiting
        self._barrier_waiting = []
        log.error("controller: failing a %d-entry barrier round — "
                  "rank(s) %s dead for more than %.1fs without "
                  "rejoining", len(parked), expired, grace)
        for request in parked:
            reply = request.create_reply_message()
            mark_error(reply, PeerLostError(
                f"{PEER_LOST_MARK} barrier cannot complete: rank(s) "
                f"{expired} declared dead and absent past "
                f"-rejoin_grace_s={grace}"))
            self.send_to(actors.COMMUNICATOR, reply)

    def _process_replica_report(self, msg: Message) -> None:
        """A server's hot-row window (table named by msg.table_id,
        blob 0 = rows, blob 1 = counts). On a promoted-set change,
        broadcast the full map to every rank — including this one, so
        the local worker/server actors apply it through the same
        routing path."""
        self._note_alive(msg.src)
        if not msg.data or len(msg.data) < 2:
            return
        rows = msg.data[0].as_array(np.int32)
        counts = msg.data[1].as_array(np.int32)
        # The same load windows feed the -reshard_auto skew planner
        # (runtime/shard_map.py): blob 2, when present, names the
        # table's row space and the reporting shard.
        num_items, sid = -1, self._zoo.rank_to_server_id(msg.src)
        if len(msg.data) >= 3:
            extra = msg.data[2].as_array(np.int64)
            num_items, sid = int(extra[0]), int(extra[1])
        self.reshards.note_report(msg.table_id, sid, rows, counts,
                                  num_items=num_items)
        if not self._replicas.ingest(msg.table_id, rows, counts,
                                     reporter=msg.src):
            return
        blobs = replica_mod.pack_replica_map(
            self._replicas.epoch, self._replicas.promoted,
            alive_sids=self.reshards.alive_sids())
        log.info("controller: replica map epoch %d (%s)",
                 self._replicas.epoch,
                 {t: int(r.size)
                  for t, r in self._replicas.promoted.items()})
        for dst in range(self._zoo.net_size):
            notice = Message(src=self._zoo.rank, dst=dst,
                             msg_type=MsgType.Control_Replica_Map)
            for arr in blobs:
                notice.push(Blob(arr))
            self.send_to(actors.COMMUNICATOR, notice)

    def _process_heartbeat(self, msg: Message) -> None:
        self._note_alive(msg.src)
        reply = msg.create_reply_message()
        # The reply is the sender's only proof the controller lives —
        # it must NOT queue in the communicator mailbox, whose dispatch
        # thread can park in a -connect_timeout_s connect-retry toward
        # a dead peer (on a combined controller+worker rank): starved
        # replies make every healthy rank conclude the controller died
        # and abort. send_async hands the frame to the destination's
        # own writer thread, so one unreachable peer cannot delay the
        # others' replies either (see HeartbeatMonitor._tick).
        try:
            self._zoo.net.send_async(reply)
        except Exception as exc:  # noqa: BLE001 - an unreachable
            # sender will re-heartbeat or be declared dead; never let
            # its failure kill the controller actor.
            log.debug("controller: heartbeat reply to rank %d failed: "
                      "%s", msg.src, exc)

    def _process_barrier(self, msg: Message) -> None:
        self._note_alive(msg.src)
        # One pending barrier per RANK: barrier() blocks until its
        # reply, so a second entry from the same rank means the rank
        # died mid-barrier and its restarted process is barriering
        # again — the stale entry must be REPLACED, or it would pair a
        # future barrier with a ghost and release the cluster early
        # (observed: a SIGKILLed server's parked shutdown barrier
        # matching its replacement's, completing a 2-rank barrier with
        # two rank-1 entries and zero rank-0 ones).
        stale = [m for m in self._barrier_waiting if m.src == msg.src]
        for m in stale:
            self._barrier_waiting.remove(m)
            log.error("controller: dropping stale barrier entry from "
                      "rank %d (rank re-entered the barrier)", m.src)
        self._barrier_waiting.append(msg)
        log.debug("controller: barrier %d/%d (+rank %d)",
                  len(self._barrier_waiting), self._zoo.net_size, msg.src)
        if len(self._barrier_waiting) == self._zoo.net_size:
            for request in self._barrier_waiting:
                self.send_to(actors.COMMUNICATOR,
                             request.create_reply_message())
            self._barrier_waiting = []

    def _process_register(self, msg: Message) -> None:
        self._note_alive(msg.src)
        if self._node_reply is not None:
            # Rejoin handshake: the cluster is already registered — this
            # is a restarted process re-announcing itself. Solo reply
            # with the frozen table; waiting for net_size registrations
            # again would hang both sides.
            reg = msg.data[0].as_array(np.int32)
            log.info("controller: rank %d re-registered (rejoin)",
                     int(reg[0]))
            # The dead predecessor may have left a barrier entry
            # parked here (e.g. killed during its shutdown barrier);
            # purge it so the restarted rank's next barrier cannot
            # pair with a ghost.
            self._barrier_waiting = [m for m in self._barrier_waiting
                                     if m.src != msg.src]
            table, counts, caps, host_ids, token = self._node_reply
            reply = msg.create_reply_message()
            reply.push(Blob(table.copy()))
            reply.push(Blob(counts.copy()))
            reply.push(Blob(caps.copy()))
            # Frozen shm-negotiation blobs (runtime/shm.py): the SAME
            # token keeps segment names stable across a rejoin, so
            # survivors' announce/attach state stays coherent.
            reply.push(Blob(host_ids.copy()))
            reply.push(Blob(token.copy()))
            self.send_to(actors.COMMUNICATOR, reply)
            # Re-anchor the rejoined rank (and any lagging worker) on
            # the CURRENT shard maps: its snapshot restored the
            # elastic state it had, but only the controller knows the
            # live epoch (docs/SHARDING.md rejoin-into-the-right-map).
            self.reshards.broadcast_all()
            # Same for the live config: the restarted rank came up on
            # construction-time flag values; re-broadcast the
            # cumulative autotuned config at the current epoch so it
            # converges immediately (docs/AUTOTUNE.md; idempotent
            # elsewhere — epoch regression is ignored on apply).
            self.autotune.broadcast_current()
            return
        self._register_waiting.append(msg)
        if len(self._register_waiting) != self._zoo.net_size:
            return
        # Assign dense worker/server ids in rank order
        # (ref: src/controller.cpp:46-66).
        nodes = [Node(rank=r) for r in range(self._zoo.net_size)]
        # Wire-capability word per rank (register blob int 2; absent on
        # pre-codec peers, which therefore stay at 0 = passthrough).
        caps = np.zeros(self._zoo.net_size, dtype=np.int32)
        # Host fingerprint per rank (register blob int 3; -1 = unknown,
        # never matches): the shm transport's co-location detector.
        host_ids = np.full(self._zoo.net_size, -1, dtype=np.int32)
        for request in self._register_waiting:
            reg = request.data[0].as_array(np.int32)
            rank, role = int(reg[0]), int(reg[1])
            nodes[rank].role = role
            if reg.size >= 3:
                caps[rank] = int(reg[2])
            if reg.size >= 4:
                host_ids[rank] = int(reg[3])
        num_workers = num_servers = 0
        for node in nodes:
            if is_worker(node.role):
                node.worker_id = num_workers
                num_workers += 1
            if is_server(node.role):
                node.server_id = num_servers
                num_servers += 1
        table = np.array(
            [[n.rank, n.role, n.worker_id, n.server_id] for n in nodes],
            dtype=np.int32)
        counts = np.array([num_workers, num_servers], dtype=np.int32)
        # Cluster-wide shm segment-naming token, chosen ONCE and frozen
        # with the reply: rejoining ranks get the same value, so ring
        # segment names (mvshm-{token}-{src}-{dst}, runtime/shm.py)
        # stay consistent for the life of the cluster.
        token = np.array(
            [int.from_bytes(os.urandom(4), "little") & 0x7FFFFFFF],
            dtype=np.int32)
        self._node_reply = (table, counts, caps, host_ids, token)
        for request in self._register_waiting:
            reply = request.create_reply_message()
            reply.push(Blob(table.copy()))
            reply.push(Blob(counts.copy()))
            reply.push(Blob(caps.copy()))
            reply.push(Blob(host_ids.copy()))
            reply.push(Blob(token.copy()))
            self.send_to(actors.COMMUNICATOR, reply)
        self._register_waiting = []


class HeartbeatMonitor:
    """Per-rank liveness thread (enabled by ``-heartbeat_interval_s``).

    Every rank pings the controller each interval. On the controller
    rank the same thread scans the controller's last-seen table and
    fans ``Control_Dead_Peer`` out to the survivors for each newly
    silent rank; on other ranks it watches for heartbeat REPLIES and
    reports the controller itself dead after the timeout (a dead
    controller is unrecoverable — every barrier and registration runs
    through it — so the zoo aborts)."""

    def __init__(self, zoo) -> None:
        self._zoo = zoo
        self._interval = float(get_flag("heartbeat_interval_s"))
        self._timeout = max(float(get_flag("heartbeat_timeout_s")),
                            self._interval * 2)
        self._stop_cond = named_condition(
            f"heartbeat[r{zoo.rank}].stop")
        self._stopped = False  # guarded_by: _stop_cond
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._interval <= 0 or self._thread is not None:
            return
        self._thread = thread_roles.spawn(
            thread_roles.LIVENESS, target=self._main,
            name=f"mv-heartbeat-r{self._zoo.rank}")

    def stop(self) -> None:
        with self._stop_cond:
            self._stopped = True
            self._stop_cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _main(self) -> None:
        from .zoo import CONTROLLER_RANK
        while True:
            with self._stop_cond:
                if self._stopped:
                    return
                self._stop_cond.wait(timeout=self._interval)
                if self._stopped:
                    return
            try:
                self._tick(CONTROLLER_RANK)
            except Exception:  # noqa: BLE001 - a monitor hiccup (e.g.
                # teardown race) must not kill liveness for the run
                log.debug("heartbeat monitor tick failed on rank %d",
                          self._zoo.rank)

    def _tick(self, controller_rank: int) -> None:
        # Liveness traffic goes DIRECTLY over the net from this thread
        # via send_async, never through the communicator's actor
        # mailbox: its single dispatch thread can park for up to
        # -connect_timeout_s in a blocking connect-retry toward a
        # dead/restarting peer, and a heartbeat queued behind that
        # starves past -heartbeat_timeout_s — the controller would then
        # declare this perfectly healthy rank dead, cascading one crash
        # into false death declarations. send_async (non-blocking,
        # per-destination writer threads on TCP; instantaneous on the
        # in-process fabrics) additionally keeps this thread itself
        # from blocking toward an unreachable destination. Liveness
        # frames carry no payload, so skipping the communicator's
        # codec stage loses nothing.
        zoo = self._zoo
        if zoo.rank != controller_rank:
            msg = Message(src=zoo.rank, dst=controller_rank,
                          msg_type=MsgType.Control_Heartbeat)
            try:
                zoo.net.send_async(msg)
            except Exception as exc:  # noqa: BLE001 - an unreachable
                # controller reads as silence; the timeout check below
                # decides when that becomes fatal.
                log.debug("rank %d: heartbeat send failed: %s",
                          zoo.rank, exc)
            if zoo.controller_silent_for() > self._timeout:
                zoo.peer_lost(controller_rank,
                              f"controller silent for more than "
                              f"{self._timeout}s")
            return
        # Controller rank: no self-heartbeat needed (silent_ranks skips
        # its own rank); scan for newly silent ranks and fan the death
        # notices to the survivors, per-destination so one unreachable
        # survivor cannot stop the rest from hearing.
        controller = zoo._actors.get(actors.CONTROLLER)
        if controller is None:
            return
        for dead in controller.silent_ranks(self._timeout):
            log.error("controller: rank %d silent for %.1fs — "
                      "declaring it dead", dead, self._timeout)
            for dst in range(zoo.net_size):
                if dst == dead:
                    continue
                if dst == zoo.rank:
                    # The controller is a survivor too: apply locally
                    # (same path its communicator would have routed a
                    # self-addressed notice through).
                    zoo.peer_lost(dead, "declared dead by the "
                                        "controller's liveness monitor")
                    continue
                notice = Message(src=zoo.rank, dst=dst,
                                 msg_type=MsgType.Control_Dead_Peer)
                notice.push(Blob(np.array([dead], dtype=np.int32)))
                try:
                    zoo.net.send_async(notice)
                except Exception as exc:  # noqa: BLE001
                    log.debug("rank %d: Dead_Peer notice to rank %d "
                              "failed: %s", zoo.rank, dst, exc)
        if controller.expired_dead_ranks(float(get_flag("rejoin_grace_s"))):
            # A dead rank overstayed its rejoin grace: nudge the
            # controller ACTOR to fail any parked barrier round (the
            # round's state belongs to the actor thread; receive() is
            # a thread-safe mailbox push).
            controller.receive(Message(
                src=zoo.rank, dst=zoo.rank,
                msg_type=MsgType.Control_Check_Barriers))
        # Elastic-resharding nudge, same pattern: the actor thread owns
        # the reshard state — it aborts an in-flight move whose
        # endpoint died, re-sends a lost Begin, re-broadcasts maps.
        controller.receive(Message(
            src=zoo.rank, dst=zoo.rank,
            msg_type=MsgType.Control_Shard_Tick))
