"""Rank-0 coordination actor: registration + barrier.

TPU-native equivalent of the reference's ``Controller``
(ref: include/multiverso/controller.h:9-22, src/controller.cpp:12-104).
Two sub-controllers:

- ``BarrierController``: collects one Control_Barrier per rank, then replies
  Control_Reply_Barrier to every sender (ref: src/controller.cpp:12-36).
- ``RegisterController``: collects one Control_Register (carrying the rank's
  declared role) per rank, assigns dense worker_id/server_id in rank order,
  then broadcasts the full node table + counts to every rank
  (ref: src/controller.cpp:38-80).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.blob import Blob
from ..core.message import Message, MsgType
from ..core.node import Node, is_server, is_worker
from . import actor as actors
from .actor import Actor


class Controller(Actor):
    def __init__(self, zoo) -> None:
        super().__init__(actors.CONTROLLER, zoo)
        self._barrier_waiting: List[Message] = []
        self._register_waiting: List[Message] = []
        self.register_handler(MsgType.Control_Barrier, self._process_barrier)
        self.register_handler(MsgType.Control_Register, self._process_register)

    def _process_barrier(self, msg: Message) -> None:
        self._barrier_waiting.append(msg)
        if len(self._barrier_waiting) == self._zoo.net_size:
            for request in self._barrier_waiting:
                self.send_to(actors.COMMUNICATOR,
                             request.create_reply_message())
            self._barrier_waiting = []

    def _process_register(self, msg: Message) -> None:
        self._register_waiting.append(msg)
        if len(self._register_waiting) != self._zoo.net_size:
            return
        # Assign dense worker/server ids in rank order
        # (ref: src/controller.cpp:46-66).
        nodes = [Node(rank=r) for r in range(self._zoo.net_size)]
        # Wire-capability word per rank (register blob int 2; absent on
        # pre-codec peers, which therefore stay at 0 = passthrough).
        caps = np.zeros(self._zoo.net_size, dtype=np.int32)
        for request in self._register_waiting:
            reg = request.data[0].as_array(np.int32)
            rank, role = int(reg[0]), int(reg[1])
            nodes[rank].role = role
            if reg.size >= 3:
                caps[rank] = int(reg[2])
        num_workers = num_servers = 0
        for node in nodes:
            if is_worker(node.role):
                node.worker_id = num_workers
                num_workers += 1
            if is_server(node.role):
                node.server_id = num_servers
                num_servers += 1
        table = np.array(
            [[n.rank, n.role, n.worker_id, n.server_id] for n in nodes],
            dtype=np.int32)
        counts = np.array([num_workers, num_servers], dtype=np.int32)
        for request in self._register_waiting:
            reply = request.create_reply_message()
            reply.push(Blob(table.copy()))
            reply.push(Blob(counts.copy()))
            reply.push(Blob(caps.copy()))
            self.send_to(actors.COMMUNICATOR, reply)
        self._register_waiting = []
