"""Cluster metrics export: per-rank reporters + controller aggregation.

The read side of the observability layer (docs/OBSERVABILITY.md). Each
rank runs a ``MetricsReporter`` thread (enabled by
``-metrics_interval_s``) that serializes its ``Dashboard``/``Samples``
registries (``util.dashboard.metrics_snapshot``) plus the trace events
recorded since its last report (``util.tracing.drain_since``) into a
JSON blob and ships it to the controller as a fire-and-forget
``Control_Metrics`` message. Remote ranks send via ``net.send_async``
— the same non-blocking path the liveness heartbeats take, for the
same reason: the communicator's dispatch thread can park in a
connect-retry toward a dead peer, and a metrics report queued behind
that would stall (and, worse, add to the backlog).

The controller folds every report into a ``ClusterMetrics`` view:
per-rank and summed monitor counters, cluster percentiles merged from
the raw sample windows each report carries (summary snapshots cannot
be merged; windows can), and one bounded merged trace-event buffer.
``io/metrics_http.py`` serves that view as ``/metrics`` (Prometheus
text exposition) and ``/trace.json`` (Chrome-trace JSON) on
``-metrics_port``.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.blob import Blob
from ..core.message import Message, MsgType
from ..util import log, tracing
from ..util.configure import define_double, define_int, get_flag
from ..util.dashboard import (METRICS_SNAPSHOT_VERSION, Samples, count,
                              metrics_snapshot)
from ..util.lock_witness import named_condition, named_lock
from . import thread_roles

define_double("metrics_interval_s", 0.0,
              "ship this rank's Dashboard/Samples snapshot (+ new "
              "trace events) to the controller as a Control_Metrics "
              "message at this period, feeding the cluster-aggregated "
              "/metrics and /trace.json scrape surfaces "
              "(docs/OBSERVABILITY.md). 0 (default) disables the "
              "reporter; per-rank registries still accumulate locally")
define_int("metrics_port", 0,
           "serve /metrics (Prometheus text exposition, cluster "
           "aggregate) and /trace.json (merged Chrome trace) over "
           "HTTP on this port ON THE CONTROLLER RANK "
           "(io/metrics_http.py). 0 (default) = no scrape surface")

#: Merged trace events the controller retains (newest win) — a
#: multiple of the per-rank ring so a short cluster's full windows fit.
MERGED_TRACE_CAP = 32768


class MetricsReporter:
    """Per-rank export thread (enabled by ``-metrics_interval_s``)."""

    def __init__(self, zoo) -> None:
        self._zoo = zoo
        self._interval = float(get_flag("metrics_interval_s"))
        self._stop_cond = named_condition(
            f"metrics_reporter[r{zoo.rank}].stop")
        self._stopped = False  # guarded_by: _stop_cond
        self._thread: Optional[threading.Thread] = None
        # flush() runs on app threads while the reporter thread ticks:
        # serializing reports keeps _sent_seq consistent (a racing pair
        # would ship the same trace events twice).
        self._report_lock = named_lock(
            f"metrics_reporter[r{zoo.rank}].report")
        self._sent_seq = 0  # guarded_by: _report_lock
        # Report ordering guard: every report carries this reporter
        # INCARNATION (unique per reporter lifetime — a restarted/
        # rejoined rank gets a fresh one) plus a monotonic sequence,
        # so the controller can drop out-of-order or stale reports
        # instead of folding them into the cluster view
        # (ClusterMetrics.ingest).
        self._incarnation = f"{os.getpid():x}-{id(self):x}-" \
                            f"{time.time_ns():x}"
        self._report_seq = 0  # guarded_by: _report_lock

    def start(self) -> None:
        if self._interval <= 0 or self._thread is not None:
            return
        self._thread = thread_roles.spawn(
            thread_roles.BACKGROUND, target=self._main,
            name=f"mv-metrics-r{self._zoo.rank}")

    def stop(self) -> None:
        with self._stop_cond:
            self._stopped = True
            self._stop_cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _main(self) -> None:
        while True:
            with self._stop_cond:
                if self._stopped:
                    return
                self._stop_cond.wait(timeout=self._interval)
                if self._stopped:
                    # Final best-effort flush so shutdown-window counts
                    # reach the controller (apps that need a guaranteed
                    # final cut call flush() + barrier themselves).
                    self._report_once()
                    return
            self._report_once()

    def flush(self) -> None:
        """One immediate report from the calling thread (tests / apps
        that want a deterministic final cut before scraping)."""
        self._report_once()

    def _report_once(self) -> None:
        with self._report_lock:
            self._report_locked()

    def _report_locked(self) -> None:
        try:
            from . import actor as actors
            from .zoo import CONTROLLER_RANK
            events = tracing.drain_since(self._sent_seq)
            payload = metrics_snapshot()
            payload["rank"] = self._zoo.rank
            payload["trace_events"] = events
            self._report_seq += 1
            payload["inc"] = self._incarnation
            payload["seq"] = self._report_seq
            msg = Message(src=self._zoo.rank, dst=CONTROLLER_RANK,
                          msg_type=MsgType.Control_Metrics)
            text = json.dumps(payload).encode()
            msg.push(Blob(np.frombuffer(text, np.uint8).copy()))
            if self._zoo.rank == CONTROLLER_RANK:
                controller = self._zoo._actors.get(actors.CONTROLLER)
                if controller is None:
                    return
                controller.receive(msg)
            else:
                # Non-blocking like the liveness frames: the
                # communicator's dispatch thread can park toward a dead
                # peer, and this thread must never block on the wire.
                self._zoo.net.send_async(msg)
            if events:
                self._sent_seq = max(e["seq"] for e in events)
            count("METRICS_REPORT")
        except Exception as exc:  # noqa: BLE001 - a failed report is a
            # lost sample, never a crashed reporter (the next tick
            # retries; drain_since re-sends undelivered events).
            log.debug("rank %d: metrics report failed: %s",
                      self._zoo.rank, exc)


def parse_report(msg: Message) -> Optional[Dict]:
    """Decode one Control_Metrics payload; None when undecodable or a
    version this build does not understand (mis-merging a foreign
    layout is worse than dropping it)."""
    if not msg.data:
        return None
    try:
        payload = json.loads(msg.text_payload())
    except Exception:  # noqa: BLE001
        return None
    if not isinstance(payload, dict) \
            or payload.get("v") != METRICS_SNAPSHOT_VERSION:
        return None
    return payload


def split_family(name: str) -> tuple:
    """``DISPATCH_MS[d1]`` -> (``DISPATCH_MS``, ``d1``); plain names
    keep an empty key."""
    if name.endswith("]") and "[" in name:
        base, _, key = name.partition("[")
        return base, key[:-1]
    return name, ""


def _escape_label(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt(value) -> str:
    if isinstance(value, float):
        return format(value, ".10g")
    return str(value)


class ClusterMetrics:
    """Controller-side merge of per-rank metric reports."""

    def __init__(self) -> None:
        self._lock = named_lock("cluster_metrics")
        # rank -> latest snapshot
        self._ranks: Dict[int, Dict] = {}  # guarded_by: _lock
        self._trace: collections.deque = collections.deque(  # guarded_by: _lock
            maxlen=MERGED_TRACE_CAP)
        # Per-rank report-ordering watermark: (incarnation, seq) of
        # the newest report folded in. A report whose seq does not
        # advance WITHIN the same incarnation is out-of-order or stale
        # (async send reordering; a de-parked frame from before a
        # rank's crash) and must not roll the rank's view backward. A
        # NEW incarnation (rank restarted/rejoined) resets the
        # watermark — its counters legitimately start over — but a
        # SUPERSEDED incarnation (seen before, then replaced) is a
        # de-parked pre-crash frame and is dropped: folding it would
        # roll the rank's view back to the dead process AND reset the
        # watermark under it.
        self._report_mark: Dict[int, Tuple[str, int]] = {}  # guarded_by: _lock
        # Ordered (dict-as-ordered-set): the cap must evict the OLDEST
        # superseded incarnation, never the most recent predecessor —
        # whose de-parked frames are exactly the ones to drop.
        self._prior_incs: Dict[int, Dict[str, None]] = {}  # guarded_by: _lock
        self.dropped_stale = 0  # guarded_by: _lock

    #: Superseded incarnations remembered per rank (a de-parked frame
    #: can only be from a recent predecessor; a tiny cap bounds a
    #: crash-looping rank's footprint).
    _PRIOR_INC_CAP = 8

    def ingest(self, payload: Dict) -> None:
        rank = int(payload.get("rank", -1))
        events = payload.get("trace_events") or []
        inc = payload.get("inc")
        seq = payload.get("seq")
        dropped = False
        with self._lock:
            if seq is not None:  # pre-seq builds always fold (legacy)
                mark = self._report_mark.get(rank)
                if mark is not None and mark[0] == inc \
                        and int(seq) <= mark[1]:
                    # Same incarnation, non-advancing seq: reordered
                    # or replayed frame.
                    self.dropped_stale += 1
                    dropped = True
                elif inc in self._prior_incs.get(rank, ()):
                    # A SUPERSEDED incarnation: a de-parked frame from
                    # before the rank's crash arriving after its
                    # replacement already reported.
                    self.dropped_stale += 1
                    dropped = True
                else:
                    if mark is not None and mark[0] != inc:
                        prior = self._prior_incs.setdefault(rank, {})
                        prior[mark[0]] = None
                        while len(prior) > self._PRIOR_INC_CAP:
                            del prior[next(iter(prior))]  # oldest
                    self._report_mark[rank] = (inc, int(seq))
            if not dropped:
                self._ranks[rank] = {
                    "monitors": dict(payload.get("monitors") or {}),
                    "samples": dict(payload.get("samples") or {}),
                }
                self._trace.extend(events)
        if dropped:
            log.debug("cluster metrics: dropped stale/out-of-order "
                      "report from rank %d (seq %s)", rank, seq)
            count("METRICS_DROPPED_STALE")

    def cluster_view(self) -> Dict:
        """Per-rank and cluster-summed counters + merged percentile
        windows, as one versioned dict."""
        with self._lock:
            ranks = {r: {"monitors": dict(s["monitors"]),
                         "samples": {n: dict(v)
                                     for n, v in s["samples"].items()}}
                     for r, s in self._ranks.items()}
            # Captured WITH the snapshots: ingest increments it
            # concurrently, and the view should be one consistent cut.
            dropped = self.dropped_stale
        monitors_sum: Dict[str, Dict] = {}
        windows: Dict[str, List[float]] = {}
        counts: Dict[str, int] = {}
        for snap in ranks.values():
            for name, m in snap["monitors"].items():
                agg = monitors_sum.setdefault(
                    name, {"count": 0, "elapsed_ms": 0.0})
                agg["count"] += int(m.get("count", 0))
                agg["elapsed_ms"] += float(m.get("elapsed_ms", 0.0))
            for name, s in snap["samples"].items():
                windows.setdefault(name, []).extend(
                    float(v) for v in s.get("recent") or [])
                counts[name] = counts.get(name, 0) \
                    + int(s.get("count", 0))
        samples_merged = {}
        for name, window in windows.items():
            if not window:
                samples_merged[name] = {"count": counts.get(name, 0)}
                continue
            data = sorted(window)
            samples_merged[name] = {
                "count": counts.get(name, 0),
                "p50": Samples._nearest_rank(data, 50),
                "p90": Samples._nearest_rank(data, 90),
                "p99": Samples._nearest_rank(data, 99),
                "max": data[-1]}
        return {"v": METRICS_SNAPSHOT_VERSION, "ranks": ranks,
                "monitors_sum": monitors_sum,
                "samples_merged": samples_merged,
                "dropped_reports": dropped}

    # -- scrape renderings --
    def prometheus_text(self) -> str:
        """The cluster view in Prometheus text exposition format 0.0.4:
        per-rank series labeled ``rank``, cluster sums as
        ``mv_cluster_*``, sample reservoirs as quantile gauges."""
        view = self.cluster_view()
        lines = [
            "# HELP mv_monitor_count_total cumulative call count of a "
            "named Dashboard monitor (per rank)",
            "# TYPE mv_monitor_count_total counter",
        ]
        for rank in sorted(view["ranks"]):
            for name, m in sorted(
                    view["ranks"][rank]["monitors"].items()):
                lines.append(
                    f'mv_monitor_count_total{{name='
                    f'"{_escape_label(name)}",rank="{rank}"}} '
                    f'{_fmt(int(m.get("count", 0)))}')
        lines += [
            "# HELP mv_monitor_elapsed_ms_total cumulative elapsed "
            "milliseconds of a named Dashboard monitor (per rank)",
            "# TYPE mv_monitor_elapsed_ms_total counter",
        ]
        for rank in sorted(view["ranks"]):
            for name, m in sorted(
                    view["ranks"][rank]["monitors"].items()):
                lines.append(
                    f'mv_monitor_elapsed_ms_total{{name='
                    f'"{_escape_label(name)}",rank="{rank}"}} '
                    f'{_fmt(float(m.get("elapsed_ms", 0.0)))}')
        lines += [
            "# HELP mv_cluster_monitor_count_total cluster-wide sum of "
            "a named Dashboard monitor's call count",
            "# TYPE mv_cluster_monitor_count_total counter",
        ]
        for name, m in sorted(view["monitors_sum"].items()):
            lines.append(
                f'mv_cluster_monitor_count_total{{name='
                f'"{_escape_label(name)}"}} '
                f'{_fmt(int(m["count"]))}')
        lines += [
            "# HELP mv_cluster_monitor_elapsed_ms_total cluster-wide "
            "summed elapsed milliseconds of a named Dashboard monitor",
            "# TYPE mv_cluster_monitor_elapsed_ms_total counter",
        ]
        for name, m in sorted(view["monitors_sum"].items()):
            lines.append(
                f'mv_cluster_monitor_elapsed_ms_total{{name='
                f'"{_escape_label(name)}"}} '
                f'{_fmt(float(m["elapsed_ms"]))}')
        lines += [
            "# HELP mv_cluster_samples cluster-merged percentile of a "
            "named Samples reservoir's retained window",
            "# TYPE mv_cluster_samples gauge",
            "# HELP mv_cluster_samples_count cluster-wide total "
            "observations of a named Samples reservoir",
            "# TYPE mv_cluster_samples_count counter",
        ]
        for name, snap in sorted(view["samples_merged"].items()):
            base, key = split_family(name)
            label = (f'name="{_escape_label(base)}",'
                     f'key="{_escape_label(key)}"')
            for q, field in (("0.5", "p50"), ("0.9", "p90"),
                             ("0.99", "p99"), ("1", "max")):
                if field in snap:
                    lines.append(
                        f'mv_cluster_samples{{{label},'
                        f'quantile="{q}"}} {_fmt(float(snap[field]))}')
            lines.append(f'mv_cluster_samples_count{{{label}}} '
                         f'{_fmt(int(snap.get("count", 0)))}')
        return "\n".join(lines) + "\n"

    def chrome_trace_json(self) -> Dict:
        """Merged Chrome-trace JSON of every rank's shipped span
        events (plus nothing else: the controller's own events arrive
        through its local reporter like any rank's)."""
        with self._lock:
            events = list(self._trace)
        return tracing.chrome_trace([events])
