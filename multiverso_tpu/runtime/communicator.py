"""Bridge between local actors and the wire.

TPU-native equivalent of the reference's ``Communicator``
(ref: include/multiverso/communicator.h:11-28, src/communicator.cpp:31-107).
The in-process transport is thread-safe (THREAD_MULTIPLE in reference
terms), so this uses the reference's ZMQ shape: the actor thread handles
outbound traffic while a separate receive thread drains the net endpoint
(ref: src/communicator.cpp:42-48,77-91). Inbound and loop-back messages are
routed to the right local actor by message type — requests to the server,
replies to the worker, control requests to the controller, control replies
to the Zoo mailbox (ref: src/communicator.cpp:13-29,93-105).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ..core.blob import Blob
from ..core.message import (PEER_LOST_MARK, Message, MsgType,
                            is_controller_bound, is_server_bound,
                            is_wire_encoded, is_worker_bound, mark_error)
from ..util import log
from ..util.configure import get_flag
from ..util.wire_codec import (CAP_WIRE_CODEC, decode_message,
                               encode_message)
from . import actor as actors
from .actor import Actor


class Communicator(Actor):
    def __init__(self, zoo) -> None:
        super().__init__(actors.COMMUNICATOR, zoo)
        self._net = zoo.net
        self._recv_thread: Optional[threading.Thread] = None
        # Filter stage: encode only over a real wire (in-process blobs
        # move by reference — filtering would burn CPU and flatten
        # device payloads to host bytes for nothing), only when this
        # rank runs with the codec, and — checked per message — only
        # toward peers that ADVERTISED it during registration.
        self._codec = (not self._net.in_process
                       and bool(get_flag("wire_codec")))

    def start(self) -> None:
        super().start()
        self._net.acquire_recv_owner()
        self._recv_thread = threading.Thread(
            target=self._recv_main,
            name=f"mv-comm-recv-r{self._zoo.rank}", daemon=True)
        self._recv_thread.start()

    def stop(self, finalize_net: bool = True) -> None:
        # Drain-exit the actor thread BEFORE closing the transport: replies
        # the controller queued for remote ranks may not have hit the wire
        # yet, and finalizing first silently drops them — the peer then
        # hangs forever in its final barrier. (LocalNet's direct in-process
        # delivery masks this; a real wire transport does not.)
        super().stop()
        if finalize_net:
            self._net.finalize()
        else:
            self._net.interrupt_recv()
        if self._recv_thread is not None:
            self._recv_thread.join(timeout=30)
        self._net.release_recv_owner()

    # Outbound path: actor mailbox -> wire (or loop back locally); every
    # message type goes through the same route-or-send dispatch. The
    # codec filter stage runs here — per message, gated on the PEER's
    # advertised capability so a passthrough peer keeps getting plain
    # frames (mixed-version clusters stay correct, merely uncompressed).
    def _dispatch(self, msg: Message) -> None:
        if msg.dst != self._zoo.rank:
            if self._net.in_process and self._net.size > 1 \
                    and any(b.on_device for b in msg.data):
                # Materialize device payloads BEFORE they cross into a
                # sibling virtual rank (LocalFabric multi-rank = tests
                # and single-host multi-rank runs only; real one-zoo-
                # per-process deployments never take this branch). A
                # sibling's jit consuming a still-in-flight foreign
                # array can wedge XLA's CPU runtime on a small host:
                # the consumer occupies the execution pool waiting for
                # a producer that needs the pool to run (the cross-rank
                # twin of the Server._table_lock deadlock, observed as
                # a server gather parked forever on a worker-produced
                # id array in test_ps_device_pipeline_two_workers).
                import jax
                for blob in msg.data:
                    if blob.on_device:
                        jax.block_until_ready(blob.data)
            if self._codec and \
                    self._zoo.peer_caps(msg.dst) & CAP_WIRE_CODEC:
                encode_message(msg)
            try:
                self._net.send(msg)
            except Exception as exc:  # noqa: BLE001 - a dead peer must
                # not strand the requester's waiter (the actor loop
                # would only log): synthesize the error reply the peer
                # can no longer send, so wait() raises a retryable
                # PeerLostError instead of blocking forever.
                self._on_send_failed(msg, exc)
        else:
            self._local_forward(msg)

    def _on_send_failed(self, msg: Message, exc: BaseException) -> None:
        log.error("rank %d: send of %r to rank %d failed: %s",
                  self._zoo.rank, msg, msg.dst, exc)
        reason = f"{PEER_LOST_MARK} rank {msg.dst} unreachable: {exc}"
        reply = self._synth_error_reply(msg, reason)
        if reply is not None:
            self._local_forward(reply)
            return
        # Control traffic (or a reply toward the dead peer): nothing to
        # synthesize locally — report the peer so the zoo can decide
        # (abort, or fail that rank's in-flight work).
        self._zoo.peer_lost(msg.dst, f"send failed: {exc}")

    def _synth_error_reply(self, msg: Message,
                           reason: str) -> Optional[Message]:
        """The error reply a request's server can no longer (or not
        yet) send, built locally so the requester's waiter completes
        with a retryable failure instead of hanging. None for
        non-request messages."""
        msg_type = msg.type_int
        if msg_type in (int(MsgType.Request_Get), int(MsgType.Request_Add)):
            reply = msg.create_reply_message()
            mark_error(reply, RuntimeError(reason))
            return reply
        if msg_type == int(MsgType.Request_BatchAdd):
            # Per-sub failed acks from the request's own descriptor
            # (blob 0: [n, (table_id, msg_id, n_blobs)...]) — a
            # whole-batch error reply would make the worker abort every
            # table, which is the wrong severity for a retryable peer
            # loss.
            reply = msg.create_reply_message()
            try:
                req = msg.data[0].as_array(np.int32)
                desc = [int(req[0])]
                text = np.frombuffer(reason.encode(errors="replace"),
                                     np.uint8).copy()
                err_blobs = []
                for i in range(int(req[0])):
                    desc.extend((int(req[1 + 3 * i]), int(req[2 + 3 * i]),
                                 1, -1))
                    err_blobs.append(Blob(text.copy()))
                reply.push(Blob(np.asarray(desc, dtype=np.int32)))
                reply.data.extend(err_blobs)
            except Exception:  # noqa: BLE001 - undecodable batch (e.g.
                # already codec-encoded): fall back to the whole-batch
                # error; the worker's loud-abort path is still better
                # than a silent hang.
                mark_error(reply, RuntimeError(reason))
            return reply
        return None

    # Inbound path: wire -> local actor mailboxes
    # (ref: src/communicator.cpp:77-91).
    def _recv_main(self) -> None:
        codec_in = bool(get_flag("wire_codec"))
        while True:
            msg = self._net.recv()
            if msg is None:
                break
            # Traffic from a declared-dead rank means its restarted
            # process is back: clear the death mark so a SECOND death
            # of the same rank is reported fresh (peer_lost dedups on
            # the mark) — cheap set probe on the common path.
            self._zoo.notice_peer_alive(msg.src)
            if is_wire_encoded(msg):
                if not codec_in:
                    # A peer encoded toward a rank that never advertised
                    # the codec: negotiation bug. Fail loudly instead of
                    # routing garbage bytes into table logic.
                    log.error("rank %d: codec frame received but "
                              "-wire_codec is off; dropping message %r",
                              self._zoo.rank, msg)
                    continue
                try:
                    decode_message(msg)
                except Exception:  # noqa: BLE001 - poison frame must
                    # not kill the recv thread (every later message
                    # would silently vanish)
                    log.error("rank %d: undecodable codec frame %r",
                              self._zoo.rank, msg)
                    import traceback
                    traceback.print_exc()
                    continue
            self._safe_dispatch(msg)

    # Routing rule (ref: src/communicator.cpp:13-29).
    def _local_forward(self, msg: Message) -> None:
        msg_type = int(msg.type_int)
        # Fault-tolerance control frames are intercepted BY NAME before
        # the band rules: both are < -32, so the fallthrough would park
        # them in the Zoo mailbox where a blocked barrier() would
        # consume them and trip its reply-type assert.
        if msg_type == int(MsgType.Control_Reply_Heartbeat):
            self._zoo.note_controller_alive()
            return
        if msg_type == int(MsgType.Control_Dead_Peer):
            dead = int(msg.data[0].as_array(np.int32)[0]) if msg.data \
                else -1
            self._zoo.peer_lost(dead, "declared dead by the controller's "
                                      "liveness monitor")
            return
        if is_server_bound(msg_type):
            try:
                self._zoo.route(actors.SERVER, msg)
            except RuntimeError as exc:
                # A REJOINING restarted rank serves its communicator
                # before its server actor and tables exist; a request
                # landing in that window must NACK retryably (the
                # requester backs off and re-issues), not vanish into a
                # log line while its waiter blocks forever.
                reply = self._synth_error_reply(
                    msg, f"{PEER_LOST_MARK} rank {self._zoo.rank}: "
                         f"server not ready ({exc})")
                if reply is None:
                    raise
                log.error("rank %d: NACKing %r — server actor not "
                          "ready", self._zoo.rank, msg)
                self._dispatch(reply)
        elif is_worker_bound(msg_type):
            self._zoo.route(actors.WORKER, msg)
        elif is_controller_bound(msg_type):
            self._zoo.route(actors.CONTROLLER, msg)
        else:
            self._zoo.mailbox.push(msg)
