"""Bridge between local actors and the wire.

TPU-native equivalent of the reference's ``Communicator``
(ref: include/multiverso/communicator.h:11-28, src/communicator.cpp:31-107).
The reference gives the communicator its own actor thread because its ZMQ
sockets are single-threaded; this port's transports are thread-safe, and
outbound frames land in per-destination queues drained by the transport's
event loop — so there is no communicator thread to serialize behind.
``receive`` routes ON THE CALLER'S THREAD: a remote-bound message is
encoded and submitted to its destination's peer queue right there (the
queue's ``-send_queue_mb`` cap is the backpressure, felt by the producer
that is actually overrunning the wire), and a loop-back message is
forwarded to the right local actor by message type — requests to the
server, replies to the worker, control requests to the controller,
control replies to the Zoo mailbox (ref: src/communicator.cpp:13-29,
93-105). One dedicated receive thread drains the net endpoint
(ref: src/communicator.cpp:42-48,77-91); it is the only thread this
class owns.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ..core.blob import Blob
from ..util import chaos
from ..core.message import (PEER_LOST_MARK, Message, MsgType,
                            is_controller_bound, is_server_bound,
                            is_wire_encoded, is_worker_bound, mark_error,
                            trace_of)
from ..util import log, tracing
from ..util.configure import get_flag
from ..util.wire_codec import (CAP_WIRE_CODEC, decode_message,
                               encode_message)
from . import actor as actors
from . import thread_roles


class Communicator:
    """Message router between this rank's actors and the transport.

    Deliberately NOT an ``Actor``: it owns no mailbox and no dispatch
    thread. The old single communicator thread was the repo's most
    persistent failure class (dispatch starvation behind a dead or slow
    peer), and the per-destination WRITER threads that cured it cost
    O(peers) threads; both collapsed into the transport's event loop,
    leaving ``receive`` a plain synchronous call."""

    def __init__(self, zoo) -> None:
        self.name = actors.COMMUNICATOR
        self._zoo = zoo
        self._net = zoo.net
        self._recv_thread: Optional[threading.Thread] = None
        # Filter stage: encode only over a real wire (in-process blobs
        # move by reference — filtering would burn CPU and flatten
        # device payloads to host bytes for nothing), only when this
        # rank runs with the codec, and — checked per message — only
        # toward peers that ADVERTISED it during registration.
        self._codec = (not self._net.in_process
                       and bool(get_flag("wire_codec")))
        # Shm-transport probe (runtime/shm.py): frames toward a
        # ring-routed peer skip the codec filter — compressing below
        # the socket buys no syscalls or kernel copies, so the codec
        # CPU is pure loss there (the codec is lossless by default, so
        # results are identical either way).
        self._shm_probe = getattr(self._net, "is_shm_peer", None)
        zoo.register_actor(self)

    def start(self) -> None:
        self._net.acquire_recv_owner()
        # DISPATCH: the recv thread routes inbound frames into actor
        # mailboxes — anything blocking it starves replies.
        self._recv_thread = thread_roles.spawn(
            thread_roles.DISPATCH, target=self._recv_main,
            name=f"mv-comm-recv-r{self._zoo.rank}")

    def stop(self, finalize_net: bool = True) -> None:
        # Callers route straight into the transport, so there is no
        # actor mailbox to drain first: any reply another actor queued
        # is already sitting in a peer queue, and finalize flushes
        # those (goodbye-after-traffic) before closing — the peer's
        # final barrier still gets its frames.
        if finalize_net:
            self._net.finalize()
        else:
            self._net.interrupt_recv()
        if self._recv_thread is not None:
            self._recv_thread.join(timeout=30)
        self._net.release_recv_owner()
        self._zoo.deregister_actor(self)

    def queue_depths(self) -> dict:
        """Live per-destination outbound queue depths (bench/monitor
        observability; empty on transports without peer queues)."""
        return getattr(self._net, "queue_depths", lambda: {})()

    # -- messaging (zoo.route/send_to call this like any actor's) --
    def receive(self, msg: Message) -> None:
        self._safe_dispatch(msg)

    def _safe_dispatch(self, msg: Message) -> None:
        """Dispatch one message; a routing failure must not kill the
        calling actor's loop (same contract as Actor._safe_dispatch)."""
        try:
            self._dispatch(msg)
        except Exception:  # noqa: BLE001
            log.error("actor %s: handling message type %d raised",
                      self.name, msg.type_int)
            import traceback
            traceback.print_exc()

    # Outbound path: caller's thread -> wire (or loop back locally);
    # every message type goes through the same route-or-send dispatch.
    # The codec filter stage runs here — per message, gated on the
    # PEER's advertised capability so a passthrough peer keeps getting
    # plain frames (mixed-version clusters stay correct, merely
    # uncompressed).
    def _dispatch(self, msg: Message) -> None:
        if msg.dst != self._zoo.rank:
            self._encode_and_send(msg)
        else:
            self._local_forward(msg)

    def _encode_and_send(self, msg: Message) -> None:
        """Outbound tail: settle in-process device payloads, run the
        codec filter for capable peers, submit to the destination's
        peer queue, and route any transport failure into the
        synthesized-error path. The chaos harness's frame faults
        (-chaos_frames, util/chaos.py) hook HERE — one message-level
        choke point for every communicator-routed frame on either
        transport; a dropped frame counts as sent."""
        faulted = chaos.filter_frames(msg)
        if faulted is not None:
            for m in faulted:
                self._encode_and_send_real(m)
            return
        self._encode_and_send_real(msg)

    def _encode_and_send_real(self, msg: Message) -> None:
        if self._net.in_process and self._net.size > 1 \
                and any(b.on_device for b in msg.data):
            # Materialize device payloads BEFORE they cross into a
            # sibling virtual rank (LocalFabric multi-rank = tests
            # and single-host multi-rank runs only; real one-zoo-
            # per-process deployments never take this branch). A
            # sibling's jit consuming a still-in-flight foreign
            # array can wedge XLA's CPU runtime on a small host:
            # the consumer occupies the execution pool waiting for
            # a producer that needs the pool to run (the cross-rank
            # twin of the Server._table_lock deadlock, observed as
            # a server gather parked forever on a worker-produced
            # id array in test_ps_device_pipeline_two_workers).
            import jax
            for blob in msg.data:
                if blob.on_device:
                    jax.block_until_ready(blob.data)
        if self._codec and \
                self._zoo.peer_caps(msg.dst) & CAP_WIRE_CODEC and \
                not (self._shm_probe is not None
                     and self._shm_probe(msg.dst)):
            encode_message(msg)
        try:
            # send_async: enqueue on the destination's peer state
            # machine and return. The call blocks only under that
            # peer's -send_queue_mb backpressure (timed waits), never
            # on a socket; a peer already marked dead raises the
            # parked PeerLostError immediately.
            self._net.send_async(msg)
        except Exception as exc:  # noqa: BLE001 - a dead peer must
            # not strand the requester's waiter (the actor loop
            # would only log): synthesize the error reply the peer
            # can no longer send, so wait() raises a retryable
            # PeerLostError instead of blocking forever.
            self._on_send_failed(msg, exc)

    def _on_send_failed(self, msg: Message, exc: BaseException) -> None:
        log.error("rank %d: send of %r to rank %d failed: %s",
                  self._zoo.rank, msg, msg.dst, exc)
        if msg.type_int == int(MsgType.Request_ReplicaSync):
            # Best-effort fire-and-forget refresh: no waiter exists to
            # strand, and a dead HOLDER must not escalate into aborting
            # the owner. But the lost chunk's rows must be RE-DIRTIED at
            # the owner — a later watermark-carrying flush would
            # otherwise certify the holder's un-refreshed entries as
            # current, and the worker's read-your-writes floor would
            # accept pre-write values (the holder's sync-seq gap guard
            # is the backstop; this echo is the proactive heal). A real
            # inbound sync always carries the OWNER's src rank, so the
            # server actor recognizes the echo by src == own rank.
            if is_wire_encoded(msg):
                decode_message(msg)
            if self._zoo._actors.get(actors.SERVER) is not None:
                self._zoo.route(actors.SERVER, msg)
            return
        reason = f"{PEER_LOST_MARK} rank {msg.dst} unreachable: {exc}"
        if msg.type_int in (int(MsgType.Request_FwdGet),
                            int(MsgType.Request_FwdAdd)):
            # A FORWARDED request's requester lives on another rank
            # (this rank relayed it into a dual-read window,
            # docs/SHARDING.md): synthesize the retryable error toward
            # THAT rank's worker, and report the dead destination so
            # the controller's monitor rolls the move back.
            reply_type = MsgType.Reply_Get \
                if msg.type_int == int(MsgType.Request_FwdGet) \
                else MsgType.Reply_Add
            if msg.msg_id >= 0:
                reply = Message(src=self._zoo.rank, dst=msg.src,
                                msg_type=reply_type,
                                table_id=msg.table_id,
                                msg_id=msg.msg_id)
                mark_error(reply, RuntimeError(reason))
                if reply.dst != self._zoo.rank:
                    self._dispatch(reply)
                else:
                    self._local_forward(reply)
            self._zoo.peer_lost(msg.dst, f"send failed: {exc}")
            return
        reply = self._synth_error_reply(msg, reason)
        if reply is not None:
            self._local_forward(reply)
            return
        # Control traffic (or a reply toward the dead peer): nothing to
        # synthesize locally — report the peer so the zoo can decide
        # (abort, or fail that rank's in-flight work).
        self._zoo.peer_lost(msg.dst, f"send failed: {exc}")

    def _synth_error_reply(self, msg: Message,
                           reason: str) -> Optional[Message]:
        """The error reply a request's server can no longer (or not
        yet) send, built locally so the requester's waiter completes
        with a retryable failure instead of hanging. None for
        non-request messages."""
        msg_type = msg.type_int
        if msg_type in (int(MsgType.Request_Get), int(MsgType.Request_Add)):
            reply = msg.create_reply_message()
            mark_error(reply, RuntimeError(reason))
            return reply
        if msg_type == int(MsgType.Request_BatchAdd):
            # Per-sub failed acks from the request's own descriptor
            # (blob 0: [n, (table_id, msg_id, n_blobs)...]) — a
            # whole-batch error reply would make the worker abort every
            # table, which is the wrong severity for a retryable peer
            # loss.
            reply = msg.create_reply_message()
            try:
                req = msg.data[0].as_array(np.int32)
                desc = [int(req[0])]
                text = np.frombuffer(reason.encode(errors="replace"),
                                     np.uint8).copy()
                err_blobs = []
                for i in range(int(req[0])):
                    desc.extend((int(req[1 + 3 * i]), int(req[2 + 3 * i]),
                                 1, -1))
                    err_blobs.append(Blob(text.copy()))
                reply.push(Blob(np.asarray(desc, dtype=np.int32)))
                reply.data.extend(err_blobs)
            except Exception:  # noqa: BLE001 - undecodable batch (e.g.
                # already codec-encoded): fall back to the whole-batch
                # error; the worker's loud-abort path is still better
                # than a silent hang.
                mark_error(reply, RuntimeError(reason))
            return reply
        return None

    # Inbound path: wire -> local actor mailboxes
    # (ref: src/communicator.cpp:77-91).
    def _recv_main(self) -> None:
        codec_in = bool(get_flag("wire_codec"))
        while True:
            msg = self._net.recv()
            if msg is None:
                break
            # Traffic from a declared-dead rank means its restarted
            # process is back: clear the death mark so a SECOND death
            # of the same rank is reported fresh (peer_lost dedups on
            # the mark) — cheap set probe on the common path.
            self._zoo.notice_peer_alive(msg.src)
            if is_wire_encoded(msg):
                if not codec_in:
                    # A peer encoded toward a rank that never advertised
                    # the codec: negotiation bug. Fail loudly instead of
                    # routing garbage bytes into table logic.
                    log.error("rank %d: codec frame received but "
                              "-wire_codec is off; dropping message %r",
                              self._zoo.rank, msg)
                    continue
                try:
                    decode_message(msg)
                except Exception:  # noqa: BLE001 - poison frame must
                    # not kill the recv thread (every later message
                    # would silently vanish)
                    log.error("rank %d: undecodable codec frame %r",
                              self._zoo.rank, msg)
                    import traceback
                    traceback.print_exc()
                    continue
            self._safe_dispatch(msg)

    # Routing rule (ref: src/communicator.cpp:13-29).
    def _local_forward(self, msg: Message) -> None:
        msg_type = int(msg.type_int)
        # Fault-tolerance control frames are intercepted BY NAME before
        # the band rules: both are < -32, so the fallthrough would park
        # them in the Zoo mailbox where a blocked barrier() would
        # consume them and trip its reply-type assert.
        if msg_type == int(MsgType.Control_Reply_Heartbeat):
            self._zoo.note_controller_alive()
            return
        if msg_type == int(MsgType.Control_Reply_Serving):
            # Fleet-aggregate serving pressure from the controller
            # (docs/SERVING.md fleet section): parsed here and stored
            # on the zoo for /v1/status — like the heartbeat reply, it
            # must not fall through to the Zoo mailbox.
            try:
                import json
                doc = json.loads(msg.text_payload())
            except Exception:  # noqa: BLE001 - a malformed aggregate
                # must not kill the recv thread; the next report
                # replaces it
                log.error("rank %d: undecodable serving-fleet reply",
                          self._zoo.rank)
                return
            self._zoo.note_serving_fleet(doc)
            return
        if msg_type == int(MsgType.Control_Dead_Peer):
            dead = int(msg.data[0].as_array(np.int32)[0]) if msg.data \
                else -1
            self._zoo.peer_lost(dead, "declared dead by the controller's "
                                      "liveness monitor")
            return
        if msg_type == int(MsgType.Control_Shard_Map):
            # Epoch-stamped shard-map broadcast (elastic resharding,
            # docs/SHARDING.md): the worker's tables re-route, the
            # server's tables commit/prune migration state — cloned to
            # each actor like Control_Replica_Map below.
            for name in (actors.WORKER, actors.SERVER):
                if self._zoo._actors.get(name) is not None:
                    copy = Message(src=msg.src, dst=msg.dst,
                                   msg_type=MsgType.Control_Shard_Map,
                                   table_id=msg.table_id)
                    copy.data = list(msg.data)
                    self._zoo.route(name, copy)
            return
        if msg_type == int(MsgType.Control_Config):
            # Epoch-stamped live-config broadcast (closed-loop
            # autotune, docs/AUTOTUNE.md): applied HERE through the
            # dynamic-flag layer — set_flag + per-flag apply hooks so
            # construction-time caches re-knob — then acked back to
            # the controller so its gauges show per-rank convergence.
            # Like Control_Shard_Map it must not fall through to the
            # Zoo mailbox.
            self._apply_config(msg)
            return
        if msg_type == int(MsgType.Control_Replica_Map):
            # Promoted-row map broadcast: both sides of this rank need
            # it — the worker's tables re-route their Gets, the
            # server's tables start/stop the owner-side write-through
            # fan-out and prune demoted replica entries. Forward a
            # clone to each actor so each applies it on its own thread
            # (payload blobs are shared read-only).
            for name in (actors.WORKER, actors.SERVER):
                if self._zoo._actors.get(name) is not None:
                    copy = Message(src=msg.src, dst=msg.dst,
                                   msg_type=MsgType.Control_Replica_Map)
                    copy.data = list(msg.data)
                    self._zoo.route(name, copy)
            return
        if is_server_bound(msg_type):
            # Hop marker for sampled requests: the gap between this
            # enqueue and the server span's start is mailbox queue time
            # in the merged trace.
            tracing.event(trace_of(msg), "server_mailbox_enqueue",
                          self._zoo.rank)
            try:
                self._zoo.route(actors.SERVER, msg)
            except RuntimeError as exc:
                # A REJOINING restarted rank serves its communicator
                # before its server actor and tables exist; a request
                # landing in that window must NACK retryably (the
                # requester backs off and re-issues), not vanish into a
                # log line while its waiter blocks forever.
                reply = self._synth_error_reply(
                    msg, f"{PEER_LOST_MARK} rank {self._zoo.rank}: "
                         f"server not ready ({exc})")
                if reply is None:
                    raise
                log.error("rank %d: NACKing %r — server actor not "
                          "ready", self._zoo.rank, msg)
                self._dispatch(reply)
        elif is_worker_bound(msg_type):
            self._zoo.route(actors.WORKER, msg)
        elif is_controller_bound(msg_type):
            self._zoo.route(actors.CONTROLLER, msg)
        else:
            self._zoo.mailbox.push(msg)

    def _apply_config(self, msg: Message) -> None:
        """Apply one ``Control_Config`` broadcast through the dynamic-
        flag layer (util/configure.py ``apply_config``: epoch
        regression ignored, non-tunable flags rejected whole) and ack
        the applied watermark back to the controller. Runs on the recv
        thread — hooks must stay cheap (their contract)."""
        import json
        from ..util import configure
        try:
            doc = json.loads(msg.text_payload())
            epoch = int(doc["epoch"])
            flags = dict(doc["flags"])
        except Exception:  # noqa: BLE001 - a malformed broadcast must
            # not kill the recv thread; the controller's next broadcast
            # supersedes it
            log.error("rank %d: undecodable Control_Config broadcast",
                      self._zoo.rank)
            return
        try:
            applied = configure.apply_config(epoch, flags)
        except Exception as exc:  # noqa: BLE001 - a refused broadcast
            # (non-tunable flag, garbage value: controller bug or
            # version skew) was rejected WHOLE and must not kill the
            # recv thread — say so loudly, and ack the UNCHANGED
            # watermark so the controller sees this rank not
            # converging.
            log.error("rank %d: Control_Config refused: %s",
                      self._zoo.rank, exc)
            applied = False
        reply = msg.create_reply_message()
        reply.push(Blob(np.array(
            [self._zoo.rank, configure.applied_config_epoch(),
             1 if applied else 0], dtype=np.int64)))
        if reply.dst == self._zoo.rank:
            self._zoo.route(actors.CONTROLLER, reply)
            return
        try:
            # send_async, like every control-plane frame: this thread
            # must never block toward a dead controller.
            self._zoo.net.send_async(reply)
        except Exception as exc:  # noqa: BLE001 - an unreachable
            # controller re-broadcasts; the ack is observability, not
            # correctness
            log.debug("rank %d: config ack failed: %s",
                      self._zoo.rank, exc)
