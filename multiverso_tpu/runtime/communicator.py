"""Bridge between local actors and the wire.

TPU-native equivalent of the reference's ``Communicator``
(ref: include/multiverso/communicator.h:11-28, src/communicator.cpp:31-107).
The in-process transport is thread-safe (THREAD_MULTIPLE in reference
terms), so this uses the reference's ZMQ shape: the actor thread handles
outbound traffic while a separate receive thread drains the net endpoint
(ref: src/communicator.cpp:42-48,77-91). Inbound and loop-back messages are
routed to the right local actor by message type — requests to the server,
replies to the worker, control requests to the controller, control replies
to the Zoo mailbox (ref: src/communicator.cpp:13-29,93-105).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..core.message import (Message, is_controller_bound, is_server_bound,
                            is_wire_encoded, is_worker_bound)
from ..util import log
from ..util.configure import get_flag
from ..util.wire_codec import (CAP_WIRE_CODEC, decode_message,
                               encode_message)
from . import actor as actors
from .actor import Actor


class Communicator(Actor):
    def __init__(self, zoo) -> None:
        super().__init__(actors.COMMUNICATOR, zoo)
        self._net = zoo.net
        self._recv_thread: Optional[threading.Thread] = None
        # Filter stage: encode only over a real wire (in-process blobs
        # move by reference — filtering would burn CPU and flatten
        # device payloads to host bytes for nothing), only when this
        # rank runs with the codec, and — checked per message — only
        # toward peers that ADVERTISED it during registration.
        self._codec = (not self._net.in_process
                       and bool(get_flag("wire_codec")))

    def start(self) -> None:
        super().start()
        self._net.acquire_recv_owner()
        self._recv_thread = threading.Thread(
            target=self._recv_main,
            name=f"mv-comm-recv-r{self._zoo.rank}", daemon=True)
        self._recv_thread.start()

    def stop(self, finalize_net: bool = True) -> None:
        # Drain-exit the actor thread BEFORE closing the transport: replies
        # the controller queued for remote ranks may not have hit the wire
        # yet, and finalizing first silently drops them — the peer then
        # hangs forever in its final barrier. (LocalNet's direct in-process
        # delivery masks this; a real wire transport does not.)
        super().stop()
        if finalize_net:
            self._net.finalize()
        else:
            self._net.interrupt_recv()
        if self._recv_thread is not None:
            self._recv_thread.join(timeout=30)
        self._net.release_recv_owner()

    # Outbound path: actor mailbox -> wire (or loop back locally); every
    # message type goes through the same route-or-send dispatch. The
    # codec filter stage runs here — per message, gated on the PEER's
    # advertised capability so a passthrough peer keeps getting plain
    # frames (mixed-version clusters stay correct, merely uncompressed).
    def _dispatch(self, msg: Message) -> None:
        if msg.dst != self._zoo.rank:
            if self._net.in_process and self._net.size > 1 \
                    and any(b.on_device for b in msg.data):
                # Materialize device payloads BEFORE they cross into a
                # sibling virtual rank (LocalFabric multi-rank = tests
                # and single-host multi-rank runs only; real one-zoo-
                # per-process deployments never take this branch). A
                # sibling's jit consuming a still-in-flight foreign
                # array can wedge XLA's CPU runtime on a small host:
                # the consumer occupies the execution pool waiting for
                # a producer that needs the pool to run (the cross-rank
                # twin of the Server._table_lock deadlock, observed as
                # a server gather parked forever on a worker-produced
                # id array in test_ps_device_pipeline_two_workers).
                import jax
                for blob in msg.data:
                    if blob.on_device:
                        jax.block_until_ready(blob.data)
            if self._codec and \
                    self._zoo.peer_caps(msg.dst) & CAP_WIRE_CODEC:
                encode_message(msg)
            self._net.send(msg)
        else:
            self._local_forward(msg)

    # Inbound path: wire -> local actor mailboxes
    # (ref: src/communicator.cpp:77-91).
    def _recv_main(self) -> None:
        codec_in = bool(get_flag("wire_codec"))
        while True:
            msg = self._net.recv()
            if msg is None:
                break
            if is_wire_encoded(msg):
                if not codec_in:
                    # A peer encoded toward a rank that never advertised
                    # the codec: negotiation bug. Fail loudly instead of
                    # routing garbage bytes into table logic.
                    log.error("rank %d: codec frame received but "
                              "-wire_codec is off; dropping message %r",
                              self._zoo.rank, msg)
                    continue
                try:
                    decode_message(msg)
                except Exception:  # noqa: BLE001 - poison frame must
                    # not kill the recv thread (every later message
                    # would silently vanish)
                    log.error("rank %d: undecodable codec frame %r",
                              self._zoo.rank, msg)
                    import traceback
                    traceback.print_exc()
                    continue
            self._safe_dispatch(msg)

    # Routing rule (ref: src/communicator.cpp:13-29).
    def _local_forward(self, msg: Message) -> None:
        msg_type = int(msg.type_int)
        if is_server_bound(msg_type):
            self._zoo.route(actors.SERVER, msg)
        elif is_worker_bound(msg_type):
            self._zoo.route(actors.WORKER, msg)
        elif is_controller_bound(msg_type):
            self._zoo.route(actors.CONTROLLER, msg)
        else:
            self._zoo.mailbox.push(msg)
