"""Thread-role registry, the ``spawn`` wrapper, and the blocking
watchdog — the runtime twin of mvlint pass 9 (``thread-role``).

Every thread the package starts carries a declared **role**:

* ``EVENTLOOP`` — the transport's selector loop (one per endpoint):
  every socket accept/connect/read/write, retry and pacing timers,
  and the shm doorbell multiplex onto it. The ONLY call that may park
  it is ``selector.select(timeout)`` in its entry frame — pass 9
  proves nothing else blocking is reachable from a handler.
* ``DISPATCH`` — the communicator's receive loop. A blocked dispatch
  thread starves every control/liveness frame behind it (the PR-6/
  PR-9/PR-12 failure class, ROADMAP item 3).
* ``LIVENESS`` — the heartbeat monitor. Blocking here turns a healthy
  cluster into a false-positive death sentence.
* ``ACTOR`` — worker/server/controller run loops. May block on their
  own mailbox and on bounded table work.
* ``WRITER`` — the shm ring writers, the one queue-drainer class left:
  a full ring blocks the producer by design (bounded backpressure),
  which the event loop must never do. Blocking on the transport is
  their *job*: they exist so nothing latency-critical has to.
* ``BACKGROUND`` — everything else (metrics, snapshots, autotune,
  serving, prefetchers). Bounded-blocking by design, no budget
  enforced.

Threads register their role at spawn through :func:`spawn` (mvlint
pass 9 bans raw ``threading.Thread`` in the package), and the literal
:data:`THREAD_ROLES` table below is the canonical inventory — pass 9
cross-checks it BOTH directions against the spawn sites it discovers
through the call graph, and against the ``docs/THREADS.md`` table
(the WIRE_FORMAT.md registry precedent). Keys are
``<path-under-multiverso_tpu>::<qualname>`` of the *bound* entry
point: ``Actor._main`` spawned by a ``Server`` registers as
``runtime/server.py::Server._main`` — the role follows the
receiver's class, not where the ``def`` lexically lives.

Under ``-debug_locks`` a watchdog samples ``sys._current_frames()``
and reports any DISPATCH/LIVENESS thread whose innermost frame has
not moved for ``-role_block_budget_ms``, with the stack — the dynamic
confirmation of pass 9's static claim, exercised by the chaos
harness. A thread parked in its own entry frame or in the mailbox
(``mt_queue.py``) is *idle*, not blocked — idling in the run loop is
the healthy state the budget must not flag.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

from ..util import log
from ..util.configure import define_double, get_flag

define_double("role_block_budget_ms", 250.0,
              "blocking-watchdog budget for DISPATCH/LIVENESS threads "
              "(-debug_locks only): a latency-critical thread whose "
              "stack sits still longer than this is reported with the "
              "stack and stamped into ROLE_BLOCKED_MS[role]")

DISPATCH = "DISPATCH"
ACTOR = "ACTOR"
LIVENESS = "LIVENESS"
WRITER = "WRITER"
BACKGROUND = "BACKGROUND"
EVENTLOOP = "EVENTLOOP"

ROLES = (DISPATCH, ACTOR, LIVENESS, WRITER, BACKGROUND, EVENTLOOP)

#: Roles the watchdog budgets (and pass 9 proves non-blocking).
CRITICAL_ROLES = (DISPATCH, LIVENESS, EVENTLOOP)

#: Canonical thread inventory: entry point -> role. mvlint pass 9
#: derives the same table from the spawn sites + call graph and
#: fails on any disagreement in either direction; docs/THREADS.md
#: mirrors it for humans (also cross-checked). Literal on purpose —
#: the linter parses, never imports.
THREAD_ROLES = {
    "runtime/actor.py::Actor._main": ACTOR,
    "runtime/worker.py::Worker._main": ACTOR,
    "runtime/server.py::Server._main": ACTOR,
    "runtime/server.py::SyncServer._main": ACTOR,
    "runtime/controller.py::Controller._main": ACTOR,
    "runtime/communicator.py::Communicator._recv_main": DISPATCH,
    "runtime/controller.py::HeartbeatMonitor._main": LIVENESS,
    "runtime/tcp.py::_EventLoop._main": EVENTLOOP,
    "runtime/shm.py::_ShmPeerWriter._main": WRITER,
    "runtime/metrics.py::MetricsReporter._main": BACKGROUND,
    "runtime/snapshot.py::SnapshotManager._main": BACKGROUND,
    "runtime/autotune.py::AutotuneManager._main": BACKGROUND,
    "runtime/cluster.py::LocalCluster._run.rank_main": BACKGROUND,
    "util/async_buffer.py::ASyncBuffer._prefetch.run": BACKGROUND,
    "parallel/ma.py::model_average_async.run": BACKGROUND,
    "parallel/ma.py::sharded_model_average_async.run": BACKGROUND,
    "models/logreg/reader.py::PrefetchReader._fill": BACKGROUND,
    "models/wordembedding/data.py::BlockLoader._fill": BACKGROUND,
    "serving/frontend.py::ServingFrontend._fleet_main": BACKGROUND,
    "serving/batch.py::BatchedTableReader._run": BACKGROUND,
    "io/http_server.py::serve_forever": BACKGROUND,
}


# -- live registry ----------------------------------------------------

class _Entry:
    __slots__ = ("role", "thread", "entry_code")

    def __init__(self, role: str, thread: threading.Thread,
                 entry_code) -> None:
        self.role = role
        self.thread = thread
        self.entry_code = entry_code


_registry: Dict[int, _Entry] = {}
_registry_lock = threading.Lock()
_watchdog: Optional[threading.Thread] = None

#: Watchdog diagnostics, in order (tests assert on this — its own
#: list, separate from lock_witness.reports(), so lock-order
#: assertions stay unpolluted).
_reports: List[str] = []


def spawn(role: str, target, *, name: Optional[str] = None,
          args: Tuple = (), kwargs: Optional[dict] = None,
          daemon: bool = True) -> threading.Thread:
    """``threading.Thread`` with a declared role: the only sanctioned
    way to start a thread inside ``multiverso_tpu`` (pass 9 enforces
    this). Registers the thread for the blocking watchdog and starts
    the watchdog lazily the first time a critical role appears while
    ``-debug_locks`` is on."""
    if role not in ROLES:
        raise ValueError(f"unknown thread role {role!r} "
                         f"(choose from {ROLES})")
    entry_code = getattr(target, "__code__", None)

    def _main(*a, **k):
        ident = threading.get_ident()
        with _registry_lock:
            _registry[ident] = _Entry(role, threading.current_thread(),
                                      entry_code)
        try:
            target(*a, **k)
        finally:
            with _registry_lock:
                _registry.pop(ident, None)

    thread = threading.Thread(target=_main, name=name, daemon=daemon,
                              args=args, kwargs=kwargs or {})
    if role in CRITICAL_ROLES and bool(get_flag("debug_locks")):
        _ensure_watchdog()
    thread.start()
    return thread


def roles_alive() -> Dict[str, int]:
    """Live thread count per role (observability/tests)."""
    out: Dict[str, int] = {}
    with _registry_lock:
        for entry in _registry.values():
            out[entry.role] = out.get(entry.role, 0) + 1
    return out


def reports() -> List[str]:
    with _registry_lock:
        return list(_reports)


def reset_reports() -> None:
    with _registry_lock:
        _reports.clear()


# -- blocking watchdog (-debug_locks only) ----------------------------

def _ensure_watchdog() -> None:
    global _watchdog
    with _registry_lock:
        if _watchdog is not None and _watchdog.is_alive():
            return
        _watchdog = threading.Thread(  # the watchdog itself carries no
            target=_watchdog_main,     # role: it must outlive budgets
            name="mv-role-watchdog", daemon=True)
        _watchdog.start()


def _budget_ms() -> float:
    try:
        return float(get_flag("role_block_budget_ms"))
    except Exception:  # noqa: BLE001 - unparsed flags must not kill it
        return 250.0


def _idle(entry: _Entry, frame) -> bool:
    """Parked-not-blocked: the innermost package frame is the thread's
    own entry function (a run loop waiting for work), or any frame
    sits in the mailbox (``mt_queue.pop`` is the idle state of every
    actor)."""
    innermost_pkg = None
    f = frame
    while f is not None:
        fname = f.f_code.co_filename
        if fname.endswith("mt_queue.py"):
            return True
        if innermost_pkg is None and "multiverso_tpu" in fname:
            innermost_pkg = f.f_code
        f = f.f_back
    return innermost_pkg is None or innermost_pkg is entry.entry_code


def _watchdog_main() -> None:
    # signature -> first-seen monotonic time; reported signatures.
    first_seen: Dict[Tuple[int, str, int], float] = {}
    reported: Dict[Tuple[int, str, int], bool] = {}
    while True:
        budget_ms = _budget_ms()
        time.sleep(max(budget_ms / 4000.0, 0.01))
        with _registry_lock:
            critical = {ident: entry for ident, entry
                        in _registry.items()
                        if entry.role in CRITICAL_ROLES}
        # Stays alive through empty windows: registration happens on
        # the spawned thread, so exiting on a transiently-empty
        # registry would race the very first registrant. A parked
        # daemon sampler is cheap.
        if not critical:
            continue
        frames = sys._current_frames()
        now = time.monotonic()
        live: set = set()
        for ident, entry in critical.items():
            frame = frames.get(ident)
            if frame is None or _idle(entry, frame):
                continue
            sig = (ident, frame.f_code.co_filename, frame.f_lineno)
            live.add(sig)
            start = first_seen.setdefault(sig, now)
            blocked_ms = (now - start) * 1000.0
            if blocked_ms > budget_ms and not reported.get(sig):
                reported[sig] = True
                _report(entry, frame, blocked_ms)
        for sig in list(first_seen):
            if sig not in live:
                first_seen.pop(sig, None)
                reported.pop(sig, None)


def _report(entry: _Entry, frame, blocked_ms: float) -> None:
    from ..util.dashboard import samples  # local: avoid import cycle
    stack = "".join(traceback.format_stack(frame))
    text = (f"{entry.role} thread {entry.thread.name!r} blocked "
            f"{blocked_ms:.0f}ms (budget "
            f"{_budget_ms():.0f}ms) at "
            f"{frame.f_code.co_filename}:{frame.f_lineno}\n{stack}")
    with _registry_lock:
        _reports.append(text)
    samples(f"ROLE_BLOCKED_MS[{entry.role}]").add(blocked_ms)
    log.error("role watchdog: %s", text)
