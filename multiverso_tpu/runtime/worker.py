"""Worker actor: routes table requests to server shards.

TPU-native equivalent of the reference's ``Worker``
(ref: include/multiverso/worker.h:12-25, src/worker.cpp:12-89). On Get/Add
it asks the table to ``partition`` the request into per-server-shard blob
lists, re-arms the table's waiter to the shard count, and sends one message
per shard through the communicator; on replies it hands the payload back to
the table and counts down the waiter.

Extension over the reference: SHARD-MESSAGE COALESCING. Over a real wire
every message pays a dispatch roundtrip (~92 ms measured on the tunneled
bench platform), so Add shards bound for the same server are staged and
flushed as ONE ``Request_BatchAdd`` wire message. The window is the actor
mailbox itself: while more requests are queued the batch grows (bounded by
count/byte caps); the moment the mailbox drains — i.e. the trainer thread
is about to wait on a reply — everything pending flushes. Gets flush first
(per-connection FIFO keeps add-before-get ordering only if the adds are
actually on the wire), and BSP sync mode disables coalescing outright (the
sync server's vector clocks count one request per worker per step).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..core.message import (PEER_LOST_MARK, Message, MsgType,
                            pack_add_batch, replica_row_count,
                            reply_version, stamp_trace, take_error,
                            trace_of)
from ..util import mt_queue, tracing
from ..util.configure import (define_bool, define_double, define_int,
                              get_flag, register_tunable_hook)
from ..util.dashboard import count as count_event
from ..util.dashboard import monitor
from . import actor as actors
from . import device_lock
from . import replica as replica_mod
from .actor import Actor
from .server import Server

define_bool("coalesce_adds", True,
            "batch pending Add shards to the same server into one wire "
            "message (async mode over a wire transport only)")
define_double("rpc_timeout_s", 0.0,
              "diagnostic timeout on table request waiters: a Get/Add "
              "whose replies do not all arrive within this many seconds "
              "raises RpcTimeoutError naming the table, msg_id and the "
              "peer ranks still pending — instead of blocking forever "
              "on a reply that a silently-failed peer will never send. "
              "0 (default) = wait without bound (the reference's "
              "behavior)")

define_int("coalesce_max_msgs", 64,
           "flush a server's staged coalesced-Add batch at this many "
           "messages even while the mailbox is still busy — an "
           "unbounded batch would trade latency for no extra win. "
           "Live-retunable (docs/AUTOTUNE.md): the autotune "
           "controller backs this off when outbound send queues sit "
           "deep")
define_int("coalesce_max_kb", 4096,
           "flush a server's staged coalesced-Add batch at this many "
           "KILOBYTES of payload (the byte twin of "
           "-coalesce_max_msgs). Live-retunable (docs/AUTOTUNE.md)")


class Worker(Actor):
    def __init__(self, zoo) -> None:
        super().__init__(actors.WORKER, zoo)
        # Depth samples feed the serving tier's pressure surface and
        # the bench's mailbox report (docs/SERVING.md); gated so a
        # training-only run pays nothing per push.
        if mt_queue.depth_sampling_enabled():
            self.mailbox.track_depth("MAILBOX_DEPTH[worker]")
        self._cache: List = []  # registered WorkerTables, indexed by table id
        self.register_handler(MsgType.Request_Get, self._process_get)
        self.register_handler(MsgType.Request_Add, self._process_add)
        self.register_handler(MsgType.Reply_Get, self._process_reply_get)
        self.register_handler(MsgType.Reply_Add, self._process_reply_add)
        self.register_handler(MsgType.Reply_BatchAdd,
                              self._process_reply_batch_add)
        # Coalescing only pays where messages pay: a wire transport in
        # async mode. In-process fabrics move object references (zero
        # per-message wire cost) and the BSP sync server counts one
        # request per worker per step on its vector clocks.
        self._coalesce = (bool(get_flag("coalesce_adds"))
                          and not self._zoo.net.in_process
                          and not get_flag("sync", False))
        self._pending: Dict[int, List[Message]] = {}  # dst rank -> shards
        self._pending_bytes: Dict[int, int] = {}
        # Flush caps, cached here off the hot staging path and
        # live-retunable through the dynamic-flag layer
        # (docs/AUTOTUNE.md): plain int rebinds, GIL-atomic against
        # the actor thread's reads.
        self._max_batch_msgs = max(int(get_flag("coalesce_max_msgs")),
                                   1)
        self._max_batch_bytes = \
            max(int(get_flag("coalesce_max_kb")), 1) << 10
        register_tunable_hook("coalesce_max_msgs",
                              self._retune_batch_msgs)
        register_tunable_hook("coalesce_max_kb",
                              self._retune_batch_kb)
        # In-flight shard requests: (dst, table_id, msg_id) tracked when
        # a shard is sent (or staged), untracked when its reply lands.
        # Written only on this actor's thread; read from requester
        # threads for timeout diagnostics (GIL-atomic dict ops; a torn
        # read only costs diagnostic precision). Kept as a MULTISET
        # (key -> outstanding count): a replica REPAIR deliberately
        # reuses the original
        # request's (dst, table, msg_id) toward the rows' owner, and
        # with a plain set the original reply's discard would untrack
        # the still-outstanding repair — the dead-peer sweep could then
        # no longer fail its waiter (a crash mid-repair would hang
        # wait() forever). The count is also what the sweep owes in
        # notifies.
        self._inflight: Dict[tuple, int] = {}
        self.register_handler(MsgType.Control_Dead_Peer,
                              self._process_dead_peer)
        self.register_handler(MsgType.Control_Replica_Map,
                              self._process_replica_map)
        # Elastic resharding (runtime/shard_map.py, docs/SHARDING.md):
        # the epoch-stamped shard-map broadcast re-routes this worker's
        # tables on THIS thread (the same thread that partitions).
        self.register_handler(MsgType.Control_Shard_Map,
                              self._process_shard_map)
        # Per-destination-server shard counters (bench observability:
        # per-server request counts localize a hot shard). Plain dict,
        # actor-thread only; read via snapshot copy.
        self._reqs_by_dst: Dict[int, int] = {}

    def register_table(self, worker_table) -> int:
        self._cache.append(worker_table)
        return len(self._cache) - 1

    def abort_tables(self, reason: str) -> None:
        for table in self._cache:
            table.abort(reason)

    # -- main loop: drain mailbox, flush staged adds on idle --
    def _main(self) -> None:
        while True:
            msg = self.mailbox.pop()
            if msg is None:
                # Drain-exit: whatever is still staged must hit the wire
                # — a worker stopping with unsent adds would lose them.
                self._flush_pending()
                break
            self._safe_dispatch(msg)
            if self._pending and self.mailbox.empty():
                # The mailbox just went idle: the requester is (or is
                # about to be) blocked in wait(); holding the batch any
                # longer adds latency without adding batch members.
                self._flush_pending()

    # ref: src/worker.cpp:30-51
    def _process_get(self, msg: Message) -> None:
        with monitor("WORKER_PROCESS_GET"):
            # Per-connection FIFO only orders what is actually ON the
            # wire: staged adds must flush before a Get so the server
            # observes add-before-get program order.
            self._flush_pending()
            self._partition_and_send(msg, MsgType.Request_Get)

    # ref: src/worker.cpp:53-76
    def _process_add(self, msg: Message) -> None:
        with monitor("WORKER_PROCESS_ADD"):
            self._partition_and_send(msg, MsgType.Request_Add)

    def request_counts(self) -> Dict[int, int]:
        """Shards sent per destination rank (bench observability;
        snapshot copy — the actor thread owns the dict)."""
        return dict(self._reqs_by_dst)

    def _process_replica_map(self, msg: Message) -> None:
        """Promoted-row map broadcast from the controller: each table's
        router adopts its row set ON THIS THREAD (the same thread that
        partitions), so routing decisions never race the map."""
        try:
            epoch, promoted, alive = replica_mod.unpack_replica_map_alive(
                [b.as_array(np.int32) for b in msg.data])
        except Exception:  # noqa: BLE001 - a malformed map must not
            # kill the worker loop; the next broadcast replaces it.
            from ..util import log
            log.error("worker: undecodable replica map %r", msg)
            return
        for table_id, rows in promoted.items():
            if 0 <= table_id < len(self._cache):
                self._cache[table_id].apply_replica_map(epoch, rows)
        if alive is not None:
            # Reconcile every router's dead marks against the
            # controller's authoritative live-server view: a rejoined
            # server resumes serving replicas without waiting for
            # organic reply traffic (docs/SHARDING.md).
            for table in self._cache:
                table.replica_reconcile(alive)

    def _process_shard_map(self, msg: Message) -> None:
        """Epoch-stamped shard-map broadcast from the controller: the
        named table adopts the new row->server layout, invalidates
        client caches for moved ranges (the PR-6 generation-change
        path) and reconciles its replica router's liveness marks
        against the controller's authoritative view."""
        from . import shard_map as shard_map_mod
        try:
            table_id, smap, alive = shard_map_mod.ShardMap.unpack(
                [b.as_array(np.int64) for b in msg.data])
        except Exception:  # noqa: BLE001 - a malformed broadcast must
            # not kill the worker loop; the next broadcast replaces it.
            from ..util import log
            log.error("worker: undecodable shard map %r", msg)
            return
        if 0 <= table_id < len(self._cache):
            self._cache[table_id].apply_shard_map(smap.epoch, smap,
                                                  alive)

    def _partition_and_send(self, msg: Message, msg_type: MsgType) -> None:
        table = self._cache[msg.table_id]
        # Partition context: tables that record per-shard routing (the
        # replica router's repair bookkeeping) key it by request id.
        table._partition_msg_id = msg.msg_id
        try:
            # Partitions of DEVICE-carrying requests dispatch eager
            # device ops (per-server delta slices). Those must
            # serialize on the same process-wide lock as server table
            # logic: a worker actor's eager dispatch interleaving a
            # sibling zoo's server jit deadlocks XLA's CPU runtime
            # exactly like the server-vs-server case the lock was
            # introduced for (observed: stack parked in partition's
            # device slice while a server holds a jitted gather).
            # Pure-host partitions — the wire hot path — skip the lock
            # entirely, mirroring needs_device_lock on the server side.
            lock = Server._table_lock \
                if any(b.on_device for b in msg.data) else Server._no_lock
            with lock:
                partitions = table.partition(msg.data, msg_type)
                # Multi-zoo mode: per-server device slices must land
                # before the lock releases (device_lock.py) — an
                # in-flight slice escaping here overlaps a sibling
                # rank's server jit and can wedge XLA's CPU pool.
                # (active() gate: don't build the blob list on the
                # production hot path, where it can never matter.)
                if device_lock.active():
                    device_lock.settle([b.data
                                        for blobs in partitions.values()
                                        for b in blobs if b.on_device])
            table._partition_msg_id = -1
        except Exception as exc:
            table._partition_msg_id = -1
            # Record the failure on the request and release the caller's
            # waiter — wait() raises instead of returning 'success' over
            # an untouched destination buffer (the actor loop only logs).
            if get_flag("sync", False):
                # BSP: the sync servers must still observe one request
                # from this worker or its vector clock falls permanently
                # behind and the gate caches every OTHER worker's
                # requests forever. Send an empty shard to every server:
                # it takes the server's tick-only path (benign reply,
                # no table logic) and the sync server's finally-tick
                # keeps the clocks level; the caller still raises from
                # the failure recorded here.
                table.fail(msg.msg_id, f"partition failed: {exc}",
                           count=False)
                table.reset(msg.msg_id, self._zoo.num_servers)
                for server_id in range(self._zoo.num_servers):
                    shard = Message(src=self._zoo.rank,
                                    dst=self._zoo.server_rank(server_id),
                                    msg_type=msg_type,
                                    table_id=msg.table_id,
                                    msg_id=msg.msg_id)
                    self.send_to(actors.COMMUNICATOR, shard)
            else:
                table.fail(msg.msg_id, f"partition failed: {exc}")
            raise
        # BSP full coverage: the sync server counts ONE request per
        # worker per step on its vector clocks, but a hash/range
        # partition may touch only a subset of servers (a kv add to a
        # single key reaches one shard). Every uncovered server gets an
        # EMPTY clock-tick shard — no table logic runs (the server's
        # tick-only path), the benign reply just counts down this
        # waiter — so no server's clock falls permanently behind and
        # gates the other workers' requests forever. The
        # partition-failure path below has always ticked this way; this
        # is its success-path twin.
        num_servers = self._zoo.num_servers
        pad_sync = (get_flag("sync", False)
                    and len(partitions) < num_servers)
        table.reset(msg.msg_id,
                    num_servers if pad_sync else len(partitions))
        targets = range(num_servers) if pad_sync else partitions.keys()
        tid = trace_of(msg)
        for server_id in targets:
            dst = self._zoo.server_rank(server_id)
            shard = Message(src=self._zoo.rank, dst=dst,
                            msg_type=msg_type,
                            table_id=msg.table_id, msg_id=msg.msg_id)
            if tid:
                # Every shard of a sampled request carries the trace id
                # on the wire so the serving rank's spans pair with it.
                stamp_trace(shard, tid)
            blobs = partitions.get(server_id)
            if blobs is not None:
                shard.data = list(blobs)
            self._track((dst, msg.table_id, msg.msg_id))
            self._reqs_by_dst[dst] = self._reqs_by_dst.get(dst, 0) + 1
            if (self._coalesce and msg_type == MsgType.Request_Add
                    and dst != self._zoo.rank):
                self._stage_add(dst, shard)
            else:
                self.send_to(actors.COMMUNICATOR, shard)

    # -- coalescing --
    def _retune_batch_msgs(self, value) -> None:
        self._max_batch_msgs = max(int(value), 1)

    def _retune_batch_kb(self, value) -> None:
        self._max_batch_bytes = max(int(value), 1) << 10

    def _stage_add(self, dst: int, shard: Message) -> None:
        staged = self._pending.setdefault(dst, [])
        staged.append(shard)
        self._pending_bytes[dst] = self._pending_bytes.get(dst, 0) \
            + sum(b.size for b in shard.data)
        if (len(staged) >= self._max_batch_msgs
                or self._pending_bytes[dst] >= self._max_batch_bytes):
            self._flush_dst(dst)

    def _flush_pending(self) -> None:
        for dst in list(self._pending):
            self._flush_dst(dst)

    def _flush_dst(self, dst: int) -> None:
        staged = self._pending.pop(dst, None)
        self._pending_bytes.pop(dst, None)
        if not staged:
            return
        if len(staged) == 1:
            # A lone shard skips the batch framing (no descriptor
            # overhead, and the server's plain-Add path stays hot).
            self.send_to(actors.COMMUNICATOR, staged[0])
            return
        with monitor("WORKER_COALESCE_FLUSH"):
            batch = pack_add_batch(staged)
            tracing.event(trace_of(batch), "coalesce_flush",
                          self._zoo.rank,
                          args={"batched": len(staged), "dst": dst})
            self.send_to(actors.COMMUNICATOR, batch)

    def _reply_server_id(self, msg: Message) -> int:
        """Server id of the shard a reply came from (version stamps are
        per server shard)."""
        return self._zoo.rank_to_server_id(msg.src)

    def _track(self, key: tuple) -> None:
        self._inflight[key] = self._inflight.get(key, 0) + 1

    def _untrack(self, key: tuple) -> None:
        n = self._inflight.get(key, 0)
        if n <= 1:
            self._inflight.pop(key, None)
        else:
            self._inflight[key] = n - 1

    def pending_peers(self, table_id: int, msg_id: int) -> List[int]:
        """Destination ranks a request is still awaiting replies from
        (timeout diagnostics; best-effort read from requester threads)."""
        return sorted(d for d, t, m in list(self._inflight)
                      if t == table_id and m == msg_id)

    def forget_request(self, table_id: int, msg_id: int) -> None:
        """Drop a timed-out (abandoned) request's in-flight entries so
        they don't accumulate or pollute later diagnostics. Called from
        the REQUESTER thread: per-element discard is GIL-atomic, and a
        racing reply on the actor thread discards the same tuples
        harmlessly."""
        for key in [k for k in list(self._inflight)
                    if k[1] == table_id and k[2] == msg_id]:
            self._inflight.pop(key, None)  # abandoned: drop ALL counts

    def _process_dead_peer(self, msg: Message) -> None:
        """A peer rank died (zoo.peer_lost): every in-flight shard
        request toward it will never be answered — fail each one NOW
        with a retryable marker so blocked wait() calls raise
        PeerLostError instead of hanging. Runs on the actor thread, so
        it serializes with sends and replies: no notify can race the
        sweep."""
        dead = int(msg.data[0].as_array(np.int32)[0])
        # Staged (coalesced, not yet sent) shards toward the dead rank
        # would fail at send time anyway; fail them here in one place.
        staged = self._pending.pop(dead, None) or []
        self._pending_bytes.pop(dead, None)
        for shard in staged:
            self._untrack((dead, shard.table_id, shard.msg_id))
            table = self._cache[shard.table_id]
            table.fail(shard.msg_id,
                       f"{PEER_LOST_MARK} rank {dead} died with this Add "
                       f"staged", count=False)
            table.notify(shard.msg_id)
        # list() copy: forget_request on a requester thread may discard
        # concurrently, and bare set iteration would raise on a resize.
        # Replica routing must stop striping hot rows to the corpse
        # (fall back to owners) — otherwise every retry re-routes to
        # the dead holder and replicated reads hard-fail while their
        # owners are alive.
        dead_sid = self._zoo.rank_to_server_id(dead)
        if dead_sid >= 0:
            for table in self._cache:
                table.replica_server_dead(dead_sid)
        lost = [(key, n) for key, n in list(self._inflight.items())
                if key[0] == dead]
        for key, n in lost:
            self._inflight.pop(key, None)
            _dst, table_id, msg_id = key
            table = self._cache[table_id]
            table.fail(msg_id,
                       f"{PEER_LOST_MARK} rank {dead} died before "
                       f"replying (table {table_id}, msg {msg_id})",
                       count=False)
            for _ in range(n):  # one notify per outstanding shard
                table.notify(msg_id)

    # ref: src/worker.cpp:78-84
    def _process_reply_get(self, msg: Message) -> None:
        table = self._cache[msg.table_id]
        self._untrack((msg.src, msg.table_id, msg.msg_id))
        # Every shard reply — error or not — counts exactly one notify
        # (the finally), so the waiter completes only after ALL shards
        # report; wait() then raises on any recorded failure. Releasing
        # early on the first error would let a late sibling reply write
        # into a subsequent request's destination registers. EXCEPTION:
        # a replica-routed shard that came back short (holder missing
        # rows / below a read-your-writes floor) TRANSFERS its notify
        # onto the repair request(s) it stages — the waiter then
        # completes only when the repaired rows landed too.
        handoff = False
        try:
            error = take_error(msg)
            if error is not None:
                table.fail(msg.msg_id, error, count=False)
            elif not msg.data:
                # Benign tick reply (sync-mode full-coverage padding):
                # nothing to hand to the table — just count it down.
                pass
            else:
                # Reply context (origin server, version stamp, replica
                # row count, request id): lets the table attribute the
                # payload to a shard version for the client cache and
                # route prefetch replies — single worker thread, so
                # plain attributes.
                table._begin_reply(self._reply_server_id(msg),
                                   reply_version(msg), msg.msg_id,
                                   replica_row_count(msg))
                try:
                    # NOT under the table lock: reply handling may
                    # MATERIALIZE device payloads (host-buffer gets),
                    # which blocks on server-produced computations —
                    # holding the lock across that wait starves the
                    # producing side.
                    with tracing.span(trace_of(msg), "reply_handle:get",
                                      self._zoo.rank):
                        table.process_reply_get(msg.data)
                finally:
                    table._end_reply()
                handoff = self._send_repairs(table, msg)
        except Exception as exc:
            table.fail(msg.msg_id, f"reply handling failed: {exc}",
                       count=False)
            raise
        finally:
            if not handoff:
                tracing.event(trace_of(msg), "waiter_notify",
                              self._zoo.rank,
                              args={"from": msg.src})
                table.notify(msg.msg_id)

    def _send_repairs(self, table, msg: Message) -> bool:
        """Drain the repairs ``process_reply_get`` staged (rows a
        replica holder could not serve validly) into follow-up shard
        requests toward the rows' OWNERS, under the SAME request id.
        Returns True when the caller must skip this reply's notify —
        it was transferred onto the repairs (extended by
        ``extend_request`` when several owners are involved)."""
        repairs = table.take_repairs()
        if not repairs:
            return False
        table.extend_request(msg.msg_id, len(repairs) - 1)
        for server_id, blobs in repairs:
            dst = self._zoo.server_rank(server_id)
            shard = Message(src=self._zoo.rank, dst=dst,
                            msg_type=MsgType.Request_Get,
                            table_id=msg.table_id, msg_id=msg.msg_id)
            shard.data = list(blobs)
            self._track((dst, msg.table_id, msg.msg_id))
            self._reqs_by_dst[dst] = self._reqs_by_dst.get(dst, 0) + 1
            count_event(replica_mod.REPLICA_REPAIR)
            self.send_to(actors.COMMUNICATOR, shard)
        return True

    # ref: src/worker.cpp:86-88
    def _process_reply_add(self, msg: Message) -> None:
        table = self._cache[msg.table_id]
        self._untrack((msg.src, msg.table_id, msg.msg_id))
        # The piggybacked version bump must land BEFORE the notify: the
        # adder's completion callback reads the tracker to resolve its
        # self-invalidated cache slots (read-your-writes); it also
        # raises this worker's read-your-writes floor for the shard
        # (replica groups below the floor repair to the owner).
        table.note_add_ack(self._reply_server_id(msg), reply_version(msg))
        error = take_error(msg)
        if error is not None:
            table.fail(msg.msg_id, error, count=False)
        tracing.event(trace_of(msg), "waiter_notify", self._zoo.rank,
                      args={"from": msg.src})
        table.notify(msg.msg_id)

    def _process_reply_batch_add(self, msg: Message) -> None:
        """One coalesced ack: notify every sub-add's waiter, surfacing
        per-sub server errors through the same fail-then-wait path an
        individual Reply_Add would take."""
        error = take_error(msg)
        if error is not None:
            # Whole-batch failure with no descriptor: the server could
            # not even parse which subs the batch carried, so the
            # waiters cannot be mapped to acks. A stranded waiter is
            # the one unacceptable outcome — abort the table layer so
            # every blocked wait() raises instead of hanging (this only
            # happens on frame corruption, where transport integrity is
            # gone anyway).
            from ..util import log
            log.error("worker: batch add rejected wholesale by the "
                      "server (%s); aborting table waits", error)
            self.abort_tables(
                f"batch add rejected wholesale by rank {msg.src}: "
                f"{error}")
            return
        desc = msg.data[0].as_array(np.int32)
        if desc.size != 1 + 4 * int(desc[0]):
            # A stride mismatch is a pre-version peer's stride-3 ack
            # (or frame corruption): parsing it would notify the WRONG
            # requests' waiters and crash mid-loop, stranding the rest.
            # Same escape hatch as the whole-batch-error path above —
            # loud abort over silent ack misrouting.
            from ..util import log
            log.error("worker: batch ack descriptor stride mismatch "
                      "(%d ints for %d subs) — mixed-build coalesced "
                      "cluster? (docs/WIRE_FORMAT.md)", desc.size,
                      int(desc[0]))
            self.abort_tables(
                f"unparseable batch ack from rank {msg.src}: "
                f"{desc.size} descriptor ints for {int(desc[0])} subs")
            return
        err_blobs = msg.data[1:]
        err_idx = 0
        server_id = self._reply_server_id(msg)
        tracing.event(trace_of(msg), "waiter_notify:batch",
                      self._zoo.rank,
                      args={"from": msg.src, "subs": int(desc[0])})
        for i in range(int(desc[0])):
            table_id, msg_id, failed, version = (
                int(v) for v in desc[1 + 4 * i:5 + 4 * i])
            self._untrack((msg.src, table_id, msg_id))
            table = self._cache[table_id]
            # Per-sub version stamp, noted before the notify (the
            # adder's cache-resolution callback reads it; the
            # read-your-writes floor rises with it).
            table.note_add_ack(server_id, version)
            if failed:
                # Error texts are blobs 1..k of the batch reply; the
                # helper decodes straight off the wire view.
                text = msg.text_payload(1 + err_idx) \
                    if err_idx < len(err_blobs) \
                    else "batched add failed on the server"
                err_idx += 1
                table.fail(msg_id, text, count=False)
            table.notify(msg_id)
