"""Worker actor: routes table requests to server shards.

TPU-native equivalent of the reference's ``Worker``
(ref: include/multiverso/worker.h:12-25, src/worker.cpp:12-89). On Get/Add
it asks the table to ``partition`` the request into per-server-shard blob
lists, re-arms the table's waiter to the shard count, and sends one message
per shard through the communicator; on replies it hands the payload back to
the table and counts down the waiter.
"""

from __future__ import annotations

from typing import List

from ..core.message import Message, MsgType
from ..util.dashboard import monitor
from . import actor as actors
from .actor import Actor


class Worker(Actor):
    def __init__(self, zoo) -> None:
        super().__init__(actors.WORKER, zoo)
        self._cache: List = []  # registered WorkerTables, indexed by table id
        self.register_handler(MsgType.Request_Get, self._process_get)
        self.register_handler(MsgType.Request_Add, self._process_add)
        self.register_handler(MsgType.Reply_Get, self._process_reply_get)
        self.register_handler(MsgType.Reply_Add, self._process_reply_add)

    def register_table(self, worker_table) -> int:
        self._cache.append(worker_table)
        return len(self._cache) - 1

    def abort_tables(self, reason: str) -> None:
        for table in self._cache:
            table.abort(reason)

    # ref: src/worker.cpp:30-51
    def _process_get(self, msg: Message) -> None:
        with monitor("WORKER_PROCESS_GET"):
            self._partition_and_send(msg, MsgType.Request_Get)

    # ref: src/worker.cpp:53-76
    def _process_add(self, msg: Message) -> None:
        with monitor("WORKER_PROCESS_ADD"):
            self._partition_and_send(msg, MsgType.Request_Add)

    def _partition_and_send(self, msg: Message, msg_type: MsgType) -> None:
        table = self._cache[msg.table_id]
        try:
            partitions = table.partition(msg.data, msg_type)
        except Exception:
            # Release the caller's waiter before surfacing the error — a
            # hung Wait() would mask the real failure.
            table.reset(msg.msg_id, 0)
            raise
        table.reset(msg.msg_id, len(partitions))
        for server_id, blobs in partitions.items():
            shard = Message(src=self._zoo.rank,
                            dst=self._zoo.server_rank(server_id),
                            msg_type=msg_type,
                            table_id=msg.table_id, msg_id=msg.msg_id)
            shard.data = list(blobs)
            self.send_to(actors.COMMUNICATOR, shard)

    # ref: src/worker.cpp:78-84
    def _process_reply_get(self, msg: Message) -> None:
        table = self._cache[msg.table_id]
        # notify() must run even if reply handling raises — a swallowed
        # notify deadlocks the requester's wait().
        try:
            table.process_reply_get(msg.data)
        finally:
            table.notify(msg.msg_id)

    # ref: src/worker.cpp:86-88
    def _process_reply_add(self, msg: Message) -> None:
        self._cache[msg.table_id].notify(msg.msg_id)
