"""Worker actor: routes table requests to server shards.

TPU-native equivalent of the reference's ``Worker``
(ref: include/multiverso/worker.h:12-25, src/worker.cpp:12-89). On Get/Add
it asks the table to ``partition`` the request into per-server-shard blob
lists, re-arms the table's waiter to the shard count, and sends one message
per shard through the communicator; on replies it hands the payload back to
the table and counts down the waiter.
"""

from __future__ import annotations

from typing import List

from ..core.message import Message, MsgType, take_error
from ..util.configure import get_flag
from ..util.dashboard import monitor
from . import actor as actors
from .actor import Actor


class Worker(Actor):
    def __init__(self, zoo) -> None:
        super().__init__(actors.WORKER, zoo)
        self._cache: List = []  # registered WorkerTables, indexed by table id
        self.register_handler(MsgType.Request_Get, self._process_get)
        self.register_handler(MsgType.Request_Add, self._process_add)
        self.register_handler(MsgType.Reply_Get, self._process_reply_get)
        self.register_handler(MsgType.Reply_Add, self._process_reply_add)

    def register_table(self, worker_table) -> int:
        self._cache.append(worker_table)
        return len(self._cache) - 1

    def abort_tables(self, reason: str) -> None:
        for table in self._cache:
            table.abort(reason)

    # ref: src/worker.cpp:30-51
    def _process_get(self, msg: Message) -> None:
        with monitor("WORKER_PROCESS_GET"):
            self._partition_and_send(msg, MsgType.Request_Get)

    # ref: src/worker.cpp:53-76
    def _process_add(self, msg: Message) -> None:
        with monitor("WORKER_PROCESS_ADD"):
            self._partition_and_send(msg, MsgType.Request_Add)

    def _partition_and_send(self, msg: Message, msg_type: MsgType) -> None:
        table = self._cache[msg.table_id]
        try:
            partitions = table.partition(msg.data, msg_type)
        except Exception as exc:
            # Record the failure on the request and release the caller's
            # waiter — wait() raises instead of returning 'success' over
            # an untouched destination buffer (the actor loop only logs).
            if get_flag("sync", False):
                # BSP: the sync servers must still observe one request
                # from this worker or its vector clock falls permanently
                # behind and the gate caches every OTHER worker's
                # requests forever. Send an empty shard to every server:
                # its table logic fails (error reply — first recorded
                # error wins at the caller) but the sync server's
                # finally-tick keeps the clocks level.
                table.fail(msg.msg_id, f"partition failed: {exc}",
                           count=False)
                table.reset(msg.msg_id, self._zoo.num_servers)
                for server_id in range(self._zoo.num_servers):
                    shard = Message(src=self._zoo.rank,
                                    dst=self._zoo.server_rank(server_id),
                                    msg_type=msg_type,
                                    table_id=msg.table_id,
                                    msg_id=msg.msg_id)
                    self.send_to(actors.COMMUNICATOR, shard)
            else:
                table.fail(msg.msg_id, f"partition failed: {exc}")
            raise
        table.reset(msg.msg_id, len(partitions))
        for server_id, blobs in partitions.items():
            shard = Message(src=self._zoo.rank,
                            dst=self._zoo.server_rank(server_id),
                            msg_type=msg_type,
                            table_id=msg.table_id, msg_id=msg.msg_id)
            shard.data = list(blobs)
            self.send_to(actors.COMMUNICATOR, shard)

    # ref: src/worker.cpp:78-84
    def _process_reply_get(self, msg: Message) -> None:
        table = self._cache[msg.table_id]
        # Every shard reply — error or not — counts exactly one notify
        # (the finally), so the waiter completes only after ALL shards
        # report; wait() then raises on any recorded failure. Releasing
        # early on the first error would let a late sibling reply write
        # into a subsequent request's destination registers.
        try:
            error = take_error(msg)
            if error is not None:
                table.fail(msg.msg_id, error, count=False)
            else:
                table.process_reply_get(msg.data)
        except Exception as exc:
            table.fail(msg.msg_id, f"reply handling failed: {exc}",
                       count=False)
            raise
        finally:
            table.notify(msg.msg_id)

    # ref: src/worker.cpp:86-88
    def _process_reply_add(self, msg: Message) -> None:
        table = self._cache[msg.table_id]
        error = take_error(msg)
        if error is not None:
            table.fail(msg.msg_id, error, count=False)
        table.notify(msg.msg_id)
