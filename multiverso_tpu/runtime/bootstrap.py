"""Multi-host bootstrap: one call wires both planes.

On a TPU pod each host runs one process; two meshes must come up:

- the DATA plane — ``jax.distributed.initialize`` so XLA sees every
  host's chips and collectives ride ICI/DCN inside jitted steps;
- the CONTROL plane — this framework's TCP message mesh (registration,
  barriers, table RPC), which needs every process's endpoint.

The reference leaves placement to mpirun/machine files
(ref: include/multiverso/net/zmq_net.h:20-28). Here the coordinator
service jax.distributed already runs doubles as the rendezvous: each
process publishes its control endpoint in the coordinator's key-value
store and reads everyone else's — no machine file, no second launcher.

    import multiverso_tpu as mv
    mv.init_distributed(coordinator_address="host0:9777",
                        num_processes=16, process_id=rank)
    ...                      # tables, barriers, jitted steps
    mv.shutdown()

With ``num_processes == 1`` (coordinator still required — jax's
cluster auto-detection only fills the arguments inside managed
environments) the call degenerates to the single-process worker+server
mode after initializing jax.distributed, so one launch script scales
from a single host to a pod by changing its arguments.
"""

from __future__ import annotations

import socket
from typing import List, Optional

from ..util import log
from ..util.net_util import outbound_address, reserve_listen_port
from .tcp import net_bind, net_connect

_KEY_PREFIX = "multiverso_tpu/control_endpoint/"


def _reachable_address() -> str:
    """Outbound-interface address (see net_util.outbound_address), with
    hostname/loopback fallbacks for isolated hosts."""
    addr = outbound_address()
    if addr is not None:
        return addr
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def _coordinator_client():
    """The process-level coordination-service client jax.distributed
    keeps after initialize(); exposed only via the internal state object,
    so probe defensively and fail with a clear message."""
    try:
        from jax._src.distributed import global_state
        client = getattr(global_state, "client", None)
    except Exception:  # noqa: BLE001 - jax internals moved
        client = None
    if client is None:
        raise RuntimeError(
            "jax.distributed has no coordination client; pass a "
            "-machine_file or use net_bind/net_connect for the control "
            "mesh instead")
    return client


def exchange_endpoints(process_id: int, num_processes: int,
                      my_endpoint: str,
                      timeout_ms: int = 120_000) -> List[str]:
    """All-gather of control endpoints through the jax.distributed
    coordinator's key-value store.

    Keys are deleted after a coordinator barrier confirms every process
    has read the full set: a re-init against a still-running coordinator
    (restart without a fresh coordinator) must not read the previous
    run's stale endpoints, and the coordinator KV store rejects
    overwrites of live keys."""
    client = _coordinator_client()
    my_key = f"{_KEY_PREFIX}{process_id}"
    try:  # clear a leftover from a run that died mid-bootstrap
        client.key_value_delete(my_key)
    except Exception:  # noqa: BLE001 - absent key / older jax
        pass
    client.key_value_set(my_key, my_endpoint)
    endpoints = [
        client.blocking_key_value_get(f"{_KEY_PREFIX}{i}", timeout_ms)
        for i in range(num_processes)]
    try:
        client.wait_at_barrier("multiverso_tpu_bootstrap", timeout_ms)
        if process_id == 0:
            client.key_value_delete(_KEY_PREFIX)  # directory delete
    except Exception as exc:  # noqa: BLE001 - cleanup is best-effort
        log.info("bootstrap key cleanup skipped: %s", exc)
    return endpoints


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     argv: Optional[List[str]] = None,
                     control_port: Optional[int] = None) -> List[str]:
    """Initialize jax.distributed (data plane), rendezvous the TCP
    control mesh through its coordinator, and mv.init. Arguments default
    to jax's own cluster-environment auto-detection (TPU pods fill them
    from the runtime). Returns the argv remainder from mv.init."""
    import jax

    already_up = False
    try:
        from jax._src.distributed import global_state
        already_up = getattr(global_state, "client", None) is not None
    except Exception:  # noqa: BLE001 - jax internals moved
        pass
    if not already_up:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    num_processes = jax.process_count()
    process_id = jax.process_index()
    from .. import init as mv_init

    if num_processes <= 1:
        # Single process: worker+server degenerate mode, no TCP needed.
        return mv_init(list(argv or []))

    addr = _reachable_address()
    # Hold the bound reservation socket through the (possibly slow)
    # rendezvous so a sibling process on this host cannot be handed the
    # same port; release it just before TcpNet's listener bind.
    reserved = None
    if control_port is not None:
        port = control_port
    else:
        reserved, port = reserve_listen_port()
    try:
        my_endpoint = f"{addr}:{port}"
        endpoints = exchange_endpoints(process_id, num_processes,
                                       my_endpoint)
        log.info("control mesh (%d processes): %s", num_processes,
                 endpoints)
        net_bind(process_id, my_endpoint)
    finally:
        if reserved is not None:
            # Release the reservation only now: net_connect constructs
            # the TCP endpoint (binding the listener) immediately, so
            # the unsafe window is microseconds, not the rendezvous.
            reserved.close()
    net_connect(list(range(num_processes)), endpoints)
    return mv_init(list(argv or []))
