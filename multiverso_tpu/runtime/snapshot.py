"""Periodic async server snapshots + manifest-consistent restore.

The paper's ``ServerTable Store/Load`` surface (ref:
include/multiverso/table_interface.h:68-75) only ever ran under the
manual ``save_checkpoint`` driver; this module turns it into a
fault-tolerance primitive (ROADMAP item 3):

- a **background snapshotter thread per server actor**
  (``-snapshot_interval_s`` > 0 and ``-snapshot_dir`` set) takes a
  consistent cut of every registered table: the CAPTURE runs under the
  server's table lock via ``ServerTable.snapshot_state()`` — a jitted
  device-side copy for device tables (the updater DONATES the live
  buffer away on the next add, so a bare reference would be deleted
  under the snapshotter) / a C-level dict copy for KV — and the
  expensive host transfer + serialize + write runs OFF the lock through
  the ``io/stream.py`` URI drivers, so ``Get``/``Add`` latency is
  barely affected by snapshotting;
- each round writes per-table files named by round sequence
  (``t{tid}.seq{n}.snap``), then an fsync'd atomically-renamed
  ``manifest.json`` recording ``{table, shard, version, file, bytes,
  crc32}`` per entry — a crash between writes leaves the previous
  manifest pointing at the previous round's (still present) files, so
  the newest manifest is ALWAYS internally consistent;
- a **restarted server** (``-rejoin=true``) loads the latest manifest at
  startup and restores each table — bytes verified against the recorded
  crc32/size — as the application re-registers it, then resumes serving;
  workers retry their failed requests against it (zoo/worker
  fault-containment paths) and their client caches invalidate on the
  shard's version regression (tables/client_cache.py generation guard).

See docs/FAULT_TOLERANCE.md for the full snapshot/rejoin story.
"""

from __future__ import annotations

import contextlib
import io
import json
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from ..util import log
from ..util.configure import define_double, define_string, get_flag
from ..util.dashboard import monitor
from ..util.lock_witness import named_condition
from . import thread_roles

define_double("snapshot_interval_s", 0.0,
              "period of the per-server background snapshotter: every "
              "interval it takes a consistent cut of all registered "
              "server tables (capture under the table lock, serialize+"
              "write off it) into -snapshot_dir. 0 (default) disables "
              "periodic snapshots; snapshot_once() remains callable")
define_string("snapshot_dir", "",
              "URI prefix snapshots live under (file path or any "
              "io/stream.py scheme; per-rank subtree "
              "{dir}/rank{r}/...). Empty (default) disables the "
              "snapshot subsystem entirely")

MANIFEST_FORMAT = 1


def _rank_prefix(base: str, rank: int) -> str:
    return f"{base.rstrip('/')}/rank{rank}"


def _state_lock_of(table):
    """The lock that pairs a table's state with its version
    (tables/table_interface.py ``_state_lock``). Host-only tables' adds
    always run under it; device-backed tables' adds run under it
    whenever multi-device serialization is inactive (the single-device
    relaxation in ``Server._lock_for``) and under the device table lock
    otherwise — the snapshotter takes BOTH (table lock + every state
    lock), so the capture is atomic against adders in either mode."""
    return getattr(table, "_state_lock", contextlib.nullcontext())


class SnapshotError(RuntimeError):
    """A snapshot manifest or payload failed validation (torn write,
    mixed rounds, missing file): restoring it would silently serve
    corrupt parameters, so it fails loudly instead."""


class SnapshotManager:
    """Owns snapshotting + restore for ONE server actor's tables.

    Created by the Server actor when ``-snapshot_dir`` is set. Tables
    are handed in via ``track`` as they register; with ``-rejoin`` the
    latest manifest is loaded up front and each tracked table restores
    immediately (the restarted process re-creates tables through the
    same application code, in the same order, so ids line up)."""

    def __init__(self, zoo, table_lock) -> None:
        self._zoo = zoo
        self._table_lock = table_lock
        self._base = str(get_flag("snapshot_dir"))
        self._prefix = _rank_prefix(self._base, zoo.rank)
        self._interval = float(get_flag("snapshot_interval_s"))
        self._tables: List[Tuple[int, object]] = []
        self._seq = 0
        self.rounds_written = 0   # test/bench observability
        self.tables_restored = 0
        self._stop_cond = named_condition(
            f"snapshot[r{zoo.rank}].stop")
        self._stopped = False  # guarded_by: _stop_cond
        self._thread: Optional[threading.Thread] = None
        self._restored_ids: set = set()
        #: Tables open to the snapshotter: a shard is tracked at
        #: REGISTRATION (inside the base constructor) but only safe to
        #: capture once the factory's table_ready hook fires.
        self._ready_ids: set = set()
        self._restore: Optional[dict] = None
        #: Payload files the loaded restore manifest still points at:
        #: _cleanup must never collect these while a restore is pending
        #: (the periodic rounds of a rejoining server would otherwise
        #: delete the very bytes a not-yet-recreated table needs).
        self._protected: set = set()
        self._idle_reason: Optional[str] = None
        if bool(get_flag("rejoin")):
            self._restore = self._load_manifest()
            if self._restore is None:
                log.error("rank %d: -rejoin set but no usable snapshot "
                          "manifest under %s — tables start from their "
                          "constructors (training will re-converge "
                          "from further away)", zoo.rank, self._prefix)
            else:
                self._seq = int(self._restore.get("seq", 0))
                self._protected = {e["file"] for e
                                   in self._restore["tables"].values()}

    # -- registration / restore --
    def track(self, table_id: int, table) -> None:
        """Called at REGISTRATION, which runs inside the table base
        constructor — the subclass's storage does not exist yet, so
        restore must wait for ``restore_if_pending`` (the table factory
        calls it once construction finishes)."""
        self._tables.append((table_id, table))

    def restore_if_pending(self, table) -> None:
        """Mark one fully-constructed table ready for snapshotting and
        — when a rejoin manifest is loaded — restore it (once)."""
        for table_id, tracked in self._tables:
            if tracked is table:
                break
        else:
            return
        if self._restore is not None and table_id not in self._restored_ids:
            self._restored_ids.add(table_id)
            self._restore_table(table_id, table)
            if not (set(self._restore["tables"])
                    - {str(t) for t in self._restored_ids}):
                # Every manifest table has restored: its payload files
                # no longer need _cleanup protection.
                self._protected = set()
        self._ready_ids.add(table_id)

    def _restore_table(self, table_id: int, table) -> None:
        entry = self._restore["tables"].get(str(table_id))
        if entry is None:
            # A table the manifest does not cover was (most plausibly)
            # created AFTER the snapshot round committed — at the cut's
            # point in time it had no state, so starting it fresh IS
            # the consistent restore. Loud, because its post-snapshot
            # updates are lost; creation-order drift (a genuinely
            # different table shape mapped onto a recorded id) still
            # fails hard at load time via the size/crc checks.
            log.error("rank %d: snapshot manifest seq %d has no entry "
                      "for table %d (created after the cut?) — it "
                      "starts fresh from its constructor",
                      self._zoo.rank, self._seq, table_id)
            return
        data = _read_uri(f"{self._prefix}/{entry['file']}")
        if data is None or len(data) != int(entry["bytes"]) \
                or zlib.crc32(data) != int(entry["crc32"]):
            raise SnapshotError(
                f"rank {self._zoo.rank}: snapshot payload "
                f"{entry['file']} for table {table_id} is torn "
                f"(got {0 if data is None else len(data)} bytes, "
                f"manifest says {entry['bytes']}) — refusing to "
                f"restore corrupt parameters")
        with self._table_lock, _state_lock_of(table):
            # The manifest sidecar carries the table's shard-map epoch
            # and elastic inventory (overlay/forwarding state) so a
            # rejoin restores into the RIGHT map (docs/SHARDING.md);
            # sidecar-less entries take the legacy load path.
            table.load_with_meta(io.BytesIO(data), entry.get("meta"))
            table.version = int(entry["version"])
        self.tables_restored += 1
        log.info("rank %d: restored table %d from %s (version %d)",
                 self._zoo.rank, table_id, entry["file"],
                 table.version)

    def _load_manifest(self) -> Optional[dict]:
        raw = _read_uri(f"{self._prefix}/manifest.json")
        if raw is None:
            return None
        try:
            manifest = json.loads(raw.decode("utf-8"))
        except ValueError as exc:
            raise SnapshotError(
                f"rank {self._zoo.rank}: snapshot manifest under "
                f"{self._prefix} is torn (unparseable JSON): {exc}"
            ) from exc
        if manifest.get("format") != MANIFEST_FORMAT \
                or "tables" not in manifest:
            raise SnapshotError(
                f"rank {self._zoo.rank}: snapshot manifest format "
                f"{manifest.get('format')!r} unsupported")
        # Internal consistency: every entry must come from the SAME
        # round — mixed seqs would splice two points in time.
        seqs = {int(e["seq"]) for e in manifest["tables"].values()}
        if len(seqs) > 1:
            raise SnapshotError(
                f"rank {self._zoo.rank}: snapshot manifest mixes "
                f"rounds {sorted(seqs)} — refusing a spliced restore")
        return manifest

    # -- periodic snapshotting --
    def start(self) -> None:
        if self._interval <= 0 or self._thread is not None:
            return
        self._thread = thread_roles.spawn(
            thread_roles.BACKGROUND, target=self._main,
            name=f"mv-snapshot-r{self._zoo.rank}")

    def stop(self) -> None:
        with self._stop_cond:
            self._stopped = True
            self._stop_cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def _rounds_blocked(self) -> Optional[str]:
        """Why the periodic thread must NOT take a round right now, or
        None when it may. Rounds hold off while the application is
        still (re)building the table set: a round taken then would
        commit a manifest MISSING the not-yet-ready tables — and on a
        rejoining rank, empty early rounds would overwrite the good
        manifest and then garbage-collect the payloads the pending
        restores still need (observed: a restarted server whose first
        table takes > 2 intervals to re-create loses its restore).
        Reads actor-thread-written sets without a lock: GIL-atomic, and
        staleness only delays a round."""
        if self._restore is not None:
            pending = (set(self._restore["tables"])
                       - {str(t) for t in self._restored_ids})
            if pending:
                return (f"waiting for {len(pending)} manifest table(s) "
                        f"still to be re-created and restored")
        if not self._ready_ids:
            return "no table is ready to capture yet"
        if any(tid not in self._ready_ids for tid, _ in self._tables):
            return "a registered table is still under construction"
        return None

    def _main(self) -> None:
        while True:
            with self._stop_cond:
                if self._stopped:
                    return
                self._stop_cond.wait(timeout=self._interval)
                if self._stopped:
                    return
            blocked = self._rounds_blocked()
            if blocked is not None:
                if blocked != self._idle_reason:
                    self._idle_reason = blocked
                    log.info("rank %d: snapshotter idle: %s",
                             self._zoo.rank, blocked)
                continue
            self._idle_reason = None
            try:
                self.snapshot_once()
            except Exception:  # noqa: BLE001 - one failed round (disk
                # full, teardown race) must not kill the snapshotter:
                # the next round retries and the previous manifest
                # stays valid.
                log.error("rank %d: snapshot round failed",
                          self._zoo.rank)
                import traceback
                traceback.print_exc()

    def snapshot_once(self) -> int:
        """Take one consistent cut of every tracked table and persist
        it. Returns the round's sequence number. Callable from tests/
        drivers even with the periodic thread disabled."""
        with monitor("SNAPSHOT_CAPTURE"):
            # Capture phase: under the server's table lock PLUS every
            # host-only table's per-instance state lock (their adds
            # bypass the device lock — without the state lock a KV
            # (state, version) pair could tear), so no add can
            # interleave a table's state and its version stamp, and the
            # cut is a single point in time ACROSS tables. Lock order
            # is table lock -> state locks in ascending table id;
            # adders only ever hold ONE of these at a time, so no
            # cycle. Cheap by contract (a device-side jitted copy /
            # C-level dict copy — no host transfer or serialization
            # under the locks).
            tracked = sorted(((tid, table) for tid, table in self._tables
                              if tid in self._ready_ids),
                             key=lambda entry: entry[0])
            with self._table_lock, contextlib.ExitStack() as stack:
                for tid, table in tracked:
                    stack.enter_context(_state_lock_of(table))
                captures = [(tid, table, table.snapshot_state(),
                             int(table.version), table.snapshot_meta())
                            for tid, table in tracked]
        seq = self._seq + 1
        entries: Dict[str, dict] = {}
        with monitor("SNAPSHOT_WRITE"):
            for tid, table, state, version, meta in captures:
                buf = io.BytesIO()
                table.write_snapshot(state, buf)
                data = buf.getvalue()
                fname = f"t{tid}.seq{seq}.snap"
                # fsync'd: the manifest below commits the round, so
                # every payload it names must be durable BEFORE the
                # manifest rename — without this, a power loss could
                # leave a durable manifest pointing at payloads whose
                # blocks never hit disk (and the previous round's
                # files already collected).
                _write_uri_atomic(f"{self._prefix}/{fname}", data,
                                  fsync=True)
                entries[str(tid)] = {
                    "table": tid, "shard": self._zoo.server_id,
                    "seq": seq, "version": version, "file": fname,
                    "bytes": len(data), "crc32": zlib.crc32(data)}
                if meta:
                    # Elastic sidecar: shard-map epoch + overlay/
                    # forwarding inventory (tables define it;
                    # docs/SHARDING.md).
                    entries[str(tid)]["meta"] = meta
            manifest = {"format": MANIFEST_FORMAT,
                        "rank": self._zoo.rank,
                        "server_id": self._zoo.server_id,
                        "seq": seq, "tables": entries}
            # fsync'd atomic rename: after this line the newest
            # manifest names only files that are fully on disk.
            _write_uri_atomic(f"{self._prefix}/manifest.json",
                              json.dumps(manifest, indent=1).encode(),
                              fsync=True)
        self._seq = seq
        self.rounds_written += 1
        self._cleanup(keep_from=seq - 1)
        return seq

    def _cleanup(self, keep_from: int) -> None:
        """Delete payloads from rounds older than ``keep_from`` (the
        round before the current manifest stays as a safety margin).
        Local filesystem prefixes only — URI stores without listing
        keep their garbage (document in FAULT_TOLERANCE.md)."""
        import os
        from urllib.parse import urlparse
        parsed = urlparse(self._prefix)
        if parsed.scheme not in ("", "file"):
            return
        root = (parsed.netloc + parsed.path) if parsed.scheme == "file" \
            else self._prefix
        try:
            names = os.listdir(root)
        except OSError:
            return
        for name in names:
            if not name.endswith(".snap") or name in self._protected:
                continue
            try:
                seq = int(name.rsplit(".seq", 1)[1][:-len(".snap")])
            except (IndexError, ValueError):
                continue
            if seq < keep_from:
                try:
                    os.unlink(os.path.join(root, name))
                except OSError:
                    pass


def _read_uri(uri: str) -> Optional[bytes]:
    """Read a whole object; None when it definitively does not exist
    (any scheme's read failure counts as absent — the caller treats
    'no snapshot' as a fresh start, and a PRESENT-but-torn local file
    still surfaces through size/crc validation)."""
    from ..io.stream import read_bytes_or_none
    return read_bytes_or_none(uri)


def _write_uri_atomic(uri: str, data: bytes, fsync: bool = False) -> None:
    from ..io.stream import write_bytes_atomic
    write_bytes_atomic(uri, data, fsync=fsync)
