"""Cross-process TCP message-stream transport.

TPU-native replacement for the reference's point-to-point backends — the
functional equivalent of its ZeroMQ DEALER mesh
(ref: include/multiverso/net/zmq_net.h:23-270) and of the MPI wrapper's
serialized send/recv (ref: include/multiverso/net/mpi_net.h:195-344),
implemented over plain TCP sockets so a multi-rank cluster needs no MPI
and no libzmq:

- every rank binds one listening socket and lazily opens one outbound
  connection per peer (full mesh, like the reference's per-peer DEALER
  sockets, ref: zmq_net.h:25-61);
- messages travel as length-prefixed frames: ``[total u64][header 10xi32]
  [nblobs u32][blob sizes u64 x n][blob bytes ...]`` — the same frame
  LAYOUT as the reference's MPI path (ref: mpi_net.h:289-317), but built
  zero-copy: the send side never joins the frame into one flat buffer
  (``serialize_views`` emits a small header buffer plus one view per
  blob payload, drained by ``socket.sendmsg`` vectored writes straight
  out of the Blobs' own memory), and the receive side leases a pooled
  buffer (``util/buffer_pool.py``), fills it with ``recv_into``, and
  cuts read-only Blob views directly from the frame. Device blobs still
  materialize to host bytes at the wire boundary. ``-zero_copy=0``
  falls back to the flat join/copy path (byte-identical frames — the
  bench baseline and the mixed-build escape hatch);
- bootstrap is machine-file driven (one ``host[:port]`` per line, own rank
  found by local-address match or the ``-rank`` flag,
  ref: zmq_net.h:20-28,25-61) or app-driven via ``net_bind``/
  ``net_connect`` (``MV_NetBind``/``MV_NetConnect`` parity,
  ref: include/multiverso/multiverso.h:55-64, zmq_net.h:63-109).

On TPU this is the *control/table plane* across hosts (DCN); tensor traffic
inside a jitted step rides XLA collectives and never sees this layer.
"""

from __future__ import annotations

import collections
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.blob import Blob
from ..core.message import HEADER_SIZE, Message, trace_of
from ..util import chaos, log, tracing
from ..util.buffer_pool import BufferPool
from ..util.configure import (define_bool, define_double, define_int,
                              define_string, get_flag)
from ..util.dashboard import count, monitor
from ..util.lock_witness import (acquire_timeout, named_condition,
                                 named_lock)
from ..util.mt_queue import MtQueue
from ..util.net_util import local_addresses
from . import thread_roles
from .net import NetInterface, PeerLostError

define_string("machine_file", "", "path: one host[:port] per rank line")
define_int("port", 55555, "default TCP port when a machine-file line has none")
define_int("rank", -1, "explicit rank override for machine-file bootstrap")
define_int("send_queue_mb", 32,
           "per-peer async send queue cap (MB): send_async blocks "
           "(backpressure) once this many serialized bytes are in flight "
           "to one destination — the transport twin of the worker "
           "coalescer's 4MB flush cap")
define_double("connect_timeout_s", 30.0,
              "seconds to keep retrying an outbound connection to a "
              "peer that is not (yet) listening — covers both bootstrap "
              "races and, with the fault-tolerance retry path, the "
              "restart window of a crashed peer (a send toward a dead "
              "rank blocks in connect-retry until the replacement "
              "process binds, then delivers)")
define_bool("zero_copy", True,
            "scatter-gather wire path: serialize outbound frames as "
            "view lists drained by sendmsg vectored writes (no flat "
            "join), and deserialize inbound frames as read-only Blob "
            "views into pooled receive buffers (-buffer_pool_mb). "
            "Frames are byte-identical either way (golden-tested) — "
            "0 restores the legacy join/copy path as the bench "
            "baseline and a diagnostics escape hatch")
define_double("net_pace_mbps", 0.0,
              "emulate a constrained wire: pace outbound frames to this "
              "many megabits/s. The sleep happens BEFORE each write "
              "while holding the destination's send lock, so a frame "
              "occupies the emulated wire for its transmission time and "
              "its ARRIVAL is delayed accordingly — on the writer "
              "thread for async sends (the caller keeps computing), on "
              "the caller for blocking sends. Bench/test knob for "
              "reproducing DCN-speed behavior on localhost; 0 = off")

_HDR = struct.Struct(f"<{HEADER_SIZE}i")
_LEN = struct.Struct("<Q")
_NBLOBS = struct.Struct("<I")

_RECV_INTERRUPT = object()


def _parse_endpoint(line: str, default_port: int) -> Tuple[str, int]:
    line = line.strip()
    if ":" in line and not line.startswith("["):  # host:port (IPv4/name)
        host, port = line.rsplit(":", 1)
        return host, int(port)
    return line, default_port


def _read_exact(sock: socket.socket, n: int) -> Optional[bytearray]:
    """Read exactly n bytes; None on orderly EOF. Returns the filled
    ``bytearray`` itself — a ``bytes(buf)`` copy here used to tax every
    inbound frame once for nothing (struct unpacks and numpy views read
    a bytearray directly)."""
    buf = bytearray(n)
    return buf if _recv_into_exact(sock, memoryview(buf)) else None


def _recv_into_exact(sock: socket.socket, view: memoryview) -> bool:
    """Fill ``view`` completely from the socket; False on orderly EOF.
    The zero-copy twin of ``_read_exact``: the caller owns the buffer
    (a pooled frame lease), so nothing is allocated here."""
    n = view.nbytes
    got = 0
    while got < n:
        k = sock.recv_into(view[got:], n - got)
        if k == 0:
            return False
        got += k
    return True


def serialize_views(msg: Message) -> Tuple[List[memoryview], int]:
    """Scatter-gather framer: the wire frame as ``(views, nbytes)``
    where ``views[0]`` is the length-prefix + header + blob-size table
    (the only bytes this function builds) and every following view
    reads straight through ``Blob.wire_views()`` into the payload's own
    memory — no per-blob ``tobytes``, no ``b"".join``, no prefix
    concat. Drained by ``sendmsg`` vectored writes; joining the views
    reproduces ``_serialize``'s frame byte for byte (golden-tested),
    so the wire format is unchanged and mixed -zero_copy builds
    interoperate."""
    views: List[memoryview] = [memoryview(b"")]  # head placeholder
    sizes: List[int] = []
    payload = 0
    for blob in msg.data:
        # Device payloads cross the wire as host bytes (the reference's
        # serialize step; ref: mpi_net.h:289-317). Codec-filtered blobs
        # (header slot CODEC_SLOT set by the communicator) are already
        # uint8 frames — possibly in scatter-gather parts — and pass
        # through unchanged.
        nbytes = 0
        for view in blob.wire_views():
            nbytes += view.nbytes
            if view.nbytes:  # zero-length views would stall sendmsg
                views.append(view)
        sizes.append(nbytes)
        payload += nbytes
    body = _HDR.size + _NBLOBS.size + _LEN.size * len(sizes) + payload
    head = bytearray(_LEN.size + _HDR.size + _NBLOBS.size
                     + _LEN.size * len(sizes))
    _LEN.pack_into(head, 0, body)
    _HDR.pack_into(head, _LEN.size, *[int(v) for v in msg.header])
    off = _LEN.size + _HDR.size
    _NBLOBS.pack_into(head, off, len(sizes))
    off += _NBLOBS.size
    for sz in sizes:
        _LEN.pack_into(head, off, sz)
        off += _LEN.size
    views[0] = memoryview(head)
    # Copy accounting (docs/MEMORY.md): only the framing bytes are
    # built here; payload bytes go to the wire without a host copy.
    count("WIRE_BYTES_COPIED", len(head))
    count("WIRE_PAYLOAD_BYTES", payload)
    return views, _LEN.size + body


#: Buffers per sendmsg call — conservatively under IOV_MAX (1024 on
#: Linux); a frame with more views loops.
_IOV_CAP = 64


def _sendmsg_all(sock: socket.socket, views: List[memoryview]) -> None:
    """Drain ``views`` through vectored writes, handling partial sends
    (sendmsg may stop mid-view under backpressure). Views must be
    non-empty (``serialize_views`` filters zero-length ones)."""
    i = 0
    off = 0
    n = len(views)
    while i < n:
        if off:
            batch = [views[i][off:]]
            batch.extend(views[i + 1:i + _IOV_CAP])
        else:
            batch = views[i:i + _IOV_CAP]
        sent = sock.sendmsg(batch)
        while i < n and sent:
            remaining = views[i].nbytes - off
            if sent >= remaining:
                sent -= remaining
                i += 1
                off = 0
            else:
                off += sent
                sent = 0


def _frame_views(msg: Message) -> Tuple[List[memoryview], int]:
    """The outbound frame as vectored-write views: scatter-gather by
    default, a single view of the legacy flat frame under
    ``-zero_copy=0`` (identical bytes either way)."""
    if bool(get_flag("zero_copy")):
        return serialize_views(msg)
    frame = _serialize(msg)
    return [memoryview(frame)], len(frame)


def _serialize(msg: Message) -> bytes:
    """Flat-buffer serializer — the LEGACY path (``-zero_copy=0``), the
    golden reference the scatter-gather framer is byte-compared
    against, and the bench baseline whose copy count the zero-copy path
    is measured by. Each payload byte is copied ~3x here (per-blob
    tobytes, the join, the length-prefix concat)."""
    parts: List[bytes] = []
    blobs: List[bytes] = []
    payload = 0
    for blob in msg.data:
        blobs.append(blob.wire_bytes().tobytes())  # mvlint: ignore[copy-lint]
        payload += len(blobs[-1])
    header = _HDR.pack(*[int(v) for v in msg.header])
    parts.append(header)
    parts.append(_NBLOBS.pack(len(blobs)))
    for b in blobs:
        parts.append(_LEN.pack(len(b)))
    parts.extend(blobs)
    body = b"".join(parts)  # mvlint: ignore[copy-lint]
    frame = _LEN.pack(len(body)) + body
    count("WIRE_BYTES_COPIED", payload + len(body) + len(frame))
    count("WIRE_PAYLOAD_BYTES", payload)
    return frame


def _deserialize(body) -> Message:
    """Flat-buffer parser — the LEGACY path (``-zero_copy=0``): every
    payload byte is copied out of the frame into a private Blob
    array."""
    header = _HDR.unpack_from(body, 0)
    msg = Message()
    msg.header = list(header)
    off = _HDR.size
    (nblobs,) = _NBLOBS.unpack_from(body, off)
    off += _NBLOBS.size
    sizes = []
    payload = 0
    for _ in range(nblobs):
        (sz,) = _LEN.unpack_from(body, off)
        sizes.append(sz)
        off += _LEN.size
    for sz in sizes:
        msg.data.append(Blob(np.frombuffer(body, np.uint8, sz, off).copy()))
        off += sz
        payload += sz
    count("WIRE_BYTES_COPIED", payload)
    count("WIRE_PAYLOAD_BYTES", payload)
    return msg


def _deserialize_frame(body: memoryview, lease) -> Message:
    """Zero-copy parser: Blobs are READ-ONLY numpy views straight into
    the leased receive frame; ``lease`` rides every Blob and returns
    the buffer to the pool when the last one dies
    (util/buffer_pool.py). Mutating consumers must
    ``Blob.materialize()`` first — the copy-on-write contract
    (docs/MEMORY.md)."""
    header = _HDR.unpack_from(body, 0)
    msg = Message()
    msg.header = list(header)
    off = _HDR.size
    (nblobs,) = _NBLOBS.unpack_from(body, off)
    off += _NBLOBS.size
    sizes = []
    payload = 0
    for _ in range(nblobs):
        (sz,) = _LEN.unpack_from(body, off)
        sizes.append(sz)
        off += _LEN.size
    for sz in sizes:
        arr = np.frombuffer(body, np.uint8, sz, off)
        arr.flags.writeable = False
        msg.data.append(Blob.from_lease(arr, lease))
        off += sz
        payload += sz
    count("WIRE_PAYLOAD_BYTES", payload)
    return msg


class _PeerWriter:
    """Per-destination writer thread + bounded frame queue.

    ``send_async`` enqueues frames here as ``(views, nbytes)`` pairs —
    the scatter-gather view lists ``serialize_views`` built, drained by
    vectored ``sendmsg`` writes through the shared per-destination
    socket (under the same ``_out_locks[dst]`` the blocking path takes,
    so async and sync frames never interleave mid-write). The views
    alias the payload's own buffers until the write completes, which is
    exactly the ``send_async`` contract (NetInterface: the caller must
    not mutate a queued payload before ``flush_sends``). Backpressure:
    ``submit`` blocks once ``-send_queue_mb`` of frame bytes — summed
    view lengths — are queued, so a runaway producer degrades to the
    blocking-send behavior instead of buffering without bound. A wire
    error parks in ``error`` and is re-raised to the next submit/flush
    (the writer thread has no caller to raise into)."""

    def __init__(self, net: "TcpNet", dst: int):
        self._net = net
        self._dst = dst
        self._cond = named_condition(f"tcp[r{net.rank}].writer[d{dst}]")
        self._frames: collections.deque = collections.deque()  # guarded_by: _cond
        self._queued_bytes = 0  # guarded_by: _cond
        self._writing = False  # guarded_by: _cond
        self._closed = False  # guarded_by: _cond
        self.error: Optional[BaseException] = None  # guarded_by: _cond
        self._thread = thread_roles.spawn(
            thread_roles.WRITER, target=self._main,
            name=f"mv-tcp-write-r{net.rank}-d{dst}")

    def submit(self, views: List[memoryview], nbytes: int) -> None:
        cap = max(1, int(get_flag("send_queue_mb"))) << 20
        with self._cond:
            while (self._queued_bytes >= cap and self.error is None
                   and not self._closed):
                self._cond.wait(timeout=1.0)
            if self.error is not None:
                # The endpoint is DEAD (the writer thread died on it):
                # typed so callers can tell a lost peer — retryable
                # after a rejoin — from a local programming error.
                raise PeerLostError(
                    f"send to rank {self._dst} failed: peer connection "
                    f"is dead ({self.error})") from self.error
            if self._closed:
                raise RuntimeError("TcpNet finalized")
            self._frames.append((views, nbytes))
            self._queued_bytes += nbytes
            self._cond.notify_all()

    def flush(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while (self._frames or self._writing) and self.error is None:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise RuntimeError(
                        f"flush_sends: {self._queued_bytes} bytes to rank "
                        f"{self._dst} not drained within {timeout}s")
                self._cond.wait(timeout=1.0 if remaining is None
                                else min(remaining, 1.0))
            if self.error is not None:
                raise PeerLostError(
                    f"send to rank {self._dst} failed: peer connection "
                    f"is dead ({self.error})") from self.error

    @property
    def queued_bytes(self) -> int:
        with self._cond:
            return self._queued_bytes

    def close(self, timeout: float = 2.0) -> None:
        """Stop accepting frames, drain what is queued, join the thread."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not threading.current_thread():
            # The dying writer itself retires its endpoint through
            # drop_connection — it cannot join itself.
            self._thread.join(timeout=timeout)

    def _main(self) -> None:
        while True:
            with self._cond:
                while not self._frames and not self._closed:
                    self._cond.wait()
                if not self._frames:  # closed and drained
                    return
                views, nbytes = self._frames.popleft()
                self._writing = True
            try:
                # Same lock order as the blocking path (lock, then
                # lazy-connect, then pace, then write the whole frame).
                with self._net._out_locks[self._dst]:
                    sock = self._net._connect(self._dst)
                    self._net._pace(nbytes)
                    with monitor("tcp_send"):
                        _sendmsg_all(sock, views)
                self._net._count_sent(nbytes)
            except BaseException as exc:  # noqa: BLE001 - the writer
                # has no caller to raise into; ANY death (OSError,
                # MemoryError, ...) must park in self.error and wake
                # waiters — submit()/flush() then raise PeerLostError
                # instead of enqueueing into a dead thread.
                with self._cond:
                    self.error = exc
                    self._frames.clear()
                    self._queued_bytes = 0
                    self._writing = False
                    self._cond.notify_all()
                # Mark the ENDPOINT dead too (outside our lock): drop
                # the broken cached socket so a later retry reconnects,
                # and report the peer so the zoo can fail blocked
                # waiters instead of letting them hang. Quiet during
                # finalize — a teardown race is not a peer death.
                if isinstance(exc, OSError) and not self._net._closed:
                    self._net._peer_connection_died(self._dst, exc)
                return
            # Drop the view list BEFORE parking in the next wait: the
            # views alias payload buffers (possibly a pooled receive
            # frame being forwarded), and an idle writer holding its
            # last frame's views would pin that memory until the next
            # send to this peer.
            views = None
            with self._cond:
                self._queued_bytes -= nbytes
                self._writing = False
                self._cond.notify_all()


class TcpNet(NetInterface):
    """One endpoint of a full-mesh TCP cluster."""

    #: Optional callback fired when a peer connection dies while the
    #: mesh is still supposed to be up (set by Zoo.start -> Zoo.abort).
    on_peer_lost = None

    def __init__(self, rank: int, endpoints: List[str],
                 default_port: Optional[int] = None):
        if not 0 <= rank < len(endpoints):
            raise ValueError(f"rank {rank} not in endpoint list "
                             f"of size {len(endpoints)}")
        port = default_port if default_port is not None \
            else int(get_flag("port"))
        self._rank = rank
        self._peers = [_parse_endpoint(e, port) for e in endpoints]
        self._inbox: MtQueue = MtQueue()
        self._out_locks = [named_lock(f"tcp[r{rank}].out[{d}]")
                           for d in range(len(endpoints))]
        self._lifecycle = named_lock(f"tcp[r{rank}].lifecycle")
        self._out: Dict[int, socket.socket] = {}  # guarded_by: _lifecycle
        self._writers: Dict[int, _PeerWriter] = {}  # guarded_by: _lifecycle
        self._closed = False  # guarded_by: _lifecycle
        self._readers: List[threading.Thread] = []
        self._stats_lock = named_lock(f"tcp[r{rank}].stats")
        self._bytes_sent = 0  # guarded_by: _stats_lock
        self._wire_free_at = 0.0  # guarded_by: _stats_lock
        # Receive-frame pool, shared by every reader thread of this
        # endpoint (the leases are what recycle the buffers; the pool
        # itself only caps what is RETAINED, so readers never block).
        self._pool = BufferPool()

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("", self._peers[rank][1]))
        self._listener.listen(len(endpoints) + 4)
        self._accept_thread = thread_roles.spawn(
            thread_roles.BACKGROUND, target=self._accept_main,
            name=f"mv-tcp-accept-r{rank}")
        log.debug("TcpNet rank %d listening on %s:%d", rank,
                  self._peers[rank][0], self._peers[rank][1])

    # -- NetInterface --
    @property
    def rank(self) -> int:
        return self._rank

    @property
    def size(self) -> int:
        return len(self._peers)

    def send(self, msg: Message) -> int:
        """Serialize + send, each under a Dashboard monitor (the
        reference instruments exactly these wire phases,
        ref: mpi_net.h:292-342 MVA_NET_SERIALIZE/SEND sites)."""
        dst = msg.dst
        if not 0 <= dst < self.size:
            raise ValueError(f"bad dst rank {dst}")
        # Lock-free probe: a miss only skips the pre-send flush for a
        # writer created concurrently — which then has no queued frames
        # to reorder with this sync frame.
        writer = self._writers.get(dst)  # mvlint: ignore[guarded-by]
        if writer is not None:
            # FIFO with earlier async frames: a sync frame overtaking
            # queued async ones would reorder the peer's stream.
            writer.flush(timeout=60.0)
        tid = trace_of(msg)
        with monitor("tcp_serialize"), \
                tracing.span(tid, "tcp_serialize", self._rank):
            views, nbytes = _frame_views(msg)
        try:
            with monitor("tcp_send"), \
                    tracing.span(tid, "tcp_send", self._rank,
                                 args={"dst": dst,
                                       "bytes": nbytes}
                                 if tid else None):
                with self._out_locks[dst]:
                    sock = self._connect(dst)
                    self._pace(nbytes)
                    _sendmsg_all(sock, views)
        except OSError as exc:
            # Broken connection mid-send: drop the cached socket (a
            # retry must reconnect, not re-use the corpse), report the
            # peer, and surface a typed retryable error.
            self._peer_connection_died(dst, exc)
            raise PeerLostError(
                f"send to rank {dst} failed: {exc}") from exc
        self._count_sent(nbytes)
        return nbytes

    def send_async(self, msg: Message) -> int:
        """Queue one serialized frame on the destination's writer thread
        and return immediately (the non-blocking half of the chunked
        allreduce pipeline: multiple frames in flight per peer)."""
        dst = msg.dst
        if not 0 <= dst < self.size:
            raise ValueError(f"bad dst rank {dst}")
        # Chaos harness (-chaos_frames, util/chaos.py): direct async
        # senders (liveness/metrics frames) bypass the communicator's
        # choke point, so the fault filter hooks here too (one flag
        # probe when disarmed).
        faulted = chaos.filter_frames(msg)
        if faulted is not None:
            total = 0
            for m in faulted:
                total += self._send_async_real(m)
            return total
        return self._send_async_real(msg)

    def _send_async_real(self, msg: Message) -> int:
        dst = msg.dst
        tid = trace_of(msg)
        with monitor("tcp_serialize"), \
                tracing.span(tid, "tcp_serialize", self._rank):
            views, nbytes = _frame_views(msg)
        if tid:
            # The actual socket write happens on the writer thread,
            # which only sees bytes — the submit marker is the async
            # path's wire hop for sampled traces.
            tracing.event(tid, "tcp_send_async_submit", self._rank,
                          args={"dst": dst, "bytes": nbytes})
        self._writer(dst).submit(views, nbytes)
        return nbytes

    def flush_sends(self, dst: Optional[int] = None,
                    timeout: Optional[float] = None) -> None:
        # Snapshot under the lock (a concurrent drop_connection must
        # not mutate the dict mid-iteration); flush OUTSIDE it — flush
        # blocks, and _writer() needs the lock to register new peers.
        with self._lifecycle:
            writers = [self._writers[dst]] if dst is not None \
                and dst in self._writers else \
                (list(self._writers.values()) if dst is None else [])
        for writer in writers:
            writer.flush(timeout)

    @property
    def bytes_sent(self) -> int:
        with self._stats_lock:
            return self._bytes_sent

    def _writer(self, dst: int) -> _PeerWriter:
        # Double-checked probe: the hot async-send path skips the
        # lifecycle lock; the slow path below re-reads under it.
        writer = self._writers.get(dst)  # mvlint: ignore[guarded-by]
        if writer is None:
            with self._lifecycle:
                if self._closed:
                    raise RuntimeError("TcpNet finalized")
                writer = self._writers.get(dst)
                if writer is None:
                    writer = self._writers[dst] = _PeerWriter(self, dst)
        return writer

    # -- peer-death bookkeeping --
    def drop_connection(self, dst: int) -> None:
        """Forget the outbound connection state for ``dst``: close the
        cached socket and retire a (possibly dead) writer thread. The
        next send toward ``dst`` reconnects from scratch — the
        fault-tolerance retry path calls this when a peer is declared
        dead so a restarted replacement process is actually reachable
        instead of every retry hitting the broken socket."""
        with self._lifecycle:
            sock = self._out.pop(dst, None)
            writer = self._writers.pop(dst, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if writer is not None:
            writer.close(timeout=0.5)

    def _peer_connection_died(self, dst: int, exc: BaseException) -> None:
        """A connection toward ``dst`` broke while the mesh is live:
        drop it and report the peer (readers report via their own dirty
        -close path; this covers the SEND side, where the rank is
        known)."""
        # Racy loop-guard read by design: a teardown racing a peer
        # death at worst reports a peer that finalize already forgot.
        if self._closed:  # mvlint: ignore[guarded-by]
            return
        log.error("TcpNet rank %d: connection to rank %d died: %s",
                  self._rank, dst, exc)
        self.drop_connection(dst)
        hook = self.on_peer_lost
        if hook is not None:
            try:
                hook(dst)
            except Exception:  # noqa: BLE001 - failure handling must
                # not take the transport down with it
                pass

    def _count_sent(self, nbytes: int) -> None:
        with self._stats_lock:
            self._bytes_sent += nbytes

    def _pace(self, nbytes: int) -> None:
        """Emulated-wire pacing: one shared outbound link per endpoint,
        modeled as an absolute busy-until deadline. Each frame reserves
        its transmission slot and sleeps toward the deadline, so an
        OVERSLEEP on one frame (common when compute threads load the
        core) credits the next frame instead of accumulating — without
        this, many-small-frame paths pay per-sleep scheduler jitter
        that a few-big-frame path does not, skewing comparisons."""
        mbps = float(get_flag("net_pace_mbps"))
        if mbps <= 0:
            return
        tx = nbytes * 8.0 / (mbps * 1e6)
        with self._stats_lock:
            start = max(time.monotonic(), self._wire_free_at)
            self._wire_free_at = target = start + tx
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)

    def recv(self, timeout: Optional[float] = None) -> Optional[Message]:
        item = self._inbox.pop(timeout=timeout)
        if item is _RECV_INTERRUPT:
            return None
        return item

    def deliver(self, msg: Message) -> None:
        """Inject a locally received message into the inbox — the
        delivery port of the shm ring poller (runtime/shm.py), so
        ring-borne and socket-borne frames share one queue and recv
        keeps its blocking semantics and per-source FIFO."""
        self._inbox.push(msg)

    def finalize(self) -> None:
        with self._lifecycle:
            if self._closed:
                return
            self._closed = True
            # Steal the writer table while holding the lock: the drain
            # below iterates it OUTSIDE the lock (flush blocks), and a
            # concurrent drop_connection popping the live dict
            # mid-iteration would raise RuntimeError. self._out must
            # stay populated until the writers are drained — their
            # sends go through _connect, which needs the cached
            # sockets (and refuses to dial anew once _closed is set).
            writers, self._writers = dict(self._writers), {}
        try:
            self._listener.close()
        except OSError:
            pass
        # Drain + stop the async writers BEFORE the goodbye frames: a
        # goodbye racing past queued frames would truncate the peer's
        # stream mid-payload — a ring allreduce returns once it has
        # RECEIVED everything, so its final-step sends may still be
        # queued when the caller shuts down, and a peer's collective
        # depends on them. The drain bound scales with what is queued
        # (wire-rate paced frames can legitimately take many seconds);
        # a truly wedged writer is abandoned after that (daemon thread;
        # the socket close below unblocks any sendall it is stuck in).
        pace = float(get_flag("net_pace_mbps"))
        for writer in writers.values():
            pending = writer.queued_bytes
            drain = 2.0 + pending / (4 << 20)  # ≥4 MB/s of real wire
            if pace > 0:
                drain += pending * 8.0 / (pace * 1e6)
            try:
                writer.flush(timeout=drain)
            except RuntimeError:
                pass
            writer.close(timeout=2.0)
        # Only now steal the socket table: every writer has drained (or
        # been abandoned), so nothing sends through _out anymore.
        with self._lifecycle:
            out, self._out = dict(self._out), {}
        for dst, sock in out.items():
            # Goodbye frame (length 0): tells the peer's reader this
            # close is GRACEFUL, so peer-death detection stays quiet.
            # Take the per-destination send lock (with a bound — a
            # wedged sender must not hang shutdown) so the goodbye
            # cannot interleave into a frame a sender is mid-writing,
            # and bound the send itself: a peer that is alive but not
            # reading (full receive buffer) would otherwise block
            # sendall indefinitely.
            with acquire_timeout(self._out_locks[dst], 2.0) as locked:
                if locked:
                    # Without the lock, a goodbye could interleave into a
                    # frame a sender is mid-writing and corrupt the
                    # peer's stream; skipping it merely degrades to the
                    # dirty-close signal the goodbye would have avoided.
                    try:
                        sock.settimeout(2.0)
                        sock.sendall(_LEN.pack(0))
                    except OSError:
                        pass
                try:
                    sock.close()
                except OSError:
                    pass
        self._inbox.exit()

    def interrupt_recv(self) -> None:
        self._inbox.push(_RECV_INTERRUPT)

    # -- outbound mesh --
    def _connect(self, dst: int) -> socket.socket:
        """Connection to dst, established lazily with retry (a peer may not
        have bound yet during bootstrap — the reference's ZMQ connect is
        similarly fire-and-wait, ref: zmq_net.h:50-59)."""
        # Lock-free fast path: callers already serialize per
        # destination via _out_locks[dst], so the probe cannot race
        # another connect to the SAME dst; the insert re-checks under
        # _lifecycle.
        sock = self._out.get(dst)  # mvlint: ignore[guarded-by]
        if sock is not None:
            return sock
        host, port = self._peers[dst]
        connect_timeout = float(get_flag("connect_timeout_s"))
        deadline = time.monotonic() + connect_timeout
        delay = 0.02
        while True:
            # Racy abort check by design: the insert below re-checks
            # _closed under _lifecycle before publishing the socket.
            if self._closed:  # mvlint: ignore[guarded-by]
                raise RuntimeError("TcpNet finalized")
            try:
                sock = socket.create_connection((host, port), timeout=10)
                break
            except OSError as exc:
                if time.monotonic() >= deadline:
                    # Typed as a lost peer: unreachable-within-timeout is
                    # exactly the retryable condition (bootstrap race or
                    # a crashed rank whose replacement has not bound yet).
                    raise PeerLostError(
                        f"rank {self._rank}: cannot reach rank {dst} "
                        f"at {host}:{port} within {connect_timeout}s"
                    ) from exc
                time.sleep(delay)
                delay = min(delay * 2, 0.5)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
        with self._lifecycle:
            if self._closed:
                # finalize() ran while we were connecting; don't leak the
                # socket or let a send slip out after teardown.
                sock.close()
                raise RuntimeError("TcpNet finalized")
            self._out[dst] = sock
        return sock

    # -- inbound mesh --
    def _accept_main(self) -> None:
        # Racy loop guard by design: finalize closing the listener is
        # what actually stops this thread (accept raises OSError).
        while not self._closed:  # mvlint: ignore[guarded-by]
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            reader = thread_roles.spawn(
                thread_roles.BACKGROUND, target=self._reader_main,
                args=(conn,), name=f"mv-tcp-read-r{self._rank}")
            self._readers.append(reader)

    def _read_frame(self, conn: socket.socket,
                    total: int) -> Optional[Message]:
        """Read + parse one frame body. Zero-copy path: lease a pooled
        buffer, ``recv_into`` it, and cut read-only Blob views straight
        from the frame (the lease rides the Blobs and recycles the
        buffer when the last one dies). ``-zero_copy=0`` restores the
        legacy read-then-copy parse. None on EOF mid-frame."""
        if bool(get_flag("zero_copy")):
            lease = self._pool.lease(total)
            with monitor("tcp_recv"):
                if not _recv_into_exact(conn, lease.view(total)):
                    lease.release()
                    return None
            with monitor("tcp_deserialize"):
                return _deserialize_frame(lease.view(total), lease)
        with monitor("tcp_recv"):
            body = _read_exact(conn, total)
        if body is None:
            return None
        with monitor("tcp_deserialize"):
            return _deserialize(body)

    def _reader_main(self, conn: socket.socket) -> None:
        clean = False
        peer = None  # rank learned from the frames this conn carries
        try:
            # Racy loop guard by design: the conn close in finalize is
            # what actually unblocks a parked reader.
            while not self._closed:  # mvlint: ignore[guarded-by]
                head = _read_exact(conn, _LEN.size)
                if head is None:
                    return
                (total,) = _LEN.unpack(head)
                if total == 0:  # goodbye frame: graceful peer close
                    clean = True
                    return
                t0_ns = tracing.now_ns()
                msg = self._read_frame(conn, total)
                if msg is None:
                    return
                tid = trace_of(msg)
                if tid:
                    # The trace id is only known after the parse; the
                    # span still covers the read+deserialize window.
                    tracing.add_span(tid, "tcp_recv", self._rank,
                                     t0_ns, tracing.now_ns() - t0_ns,
                                     args={"bytes": total})
                # Every inbound frame names its sender; remembering it
                # lets a dirty close report WHICH peer died (the zoo's
                # rejoin path fails only that rank's in-flight requests
                # instead of aborting the whole cluster).
                if 0 <= msg.src < self.size and msg.src != self._rank:
                    peer = msg.src
                self._inbox.push(msg)
            clean = True
        except OSError:
            return  # torn down mid-read
        finally:
            try:
                conn.close()
            except OSError:
                pass
            # Racy teardown check by design: worst case is one spurious
            # peer-lost report during finalize, which abort ignores.
            if not clean and not self._closed:  # mvlint: ignore[guarded-by]
                # A peer hung up while the mesh is live: report it so the
                # zoo can abort blocked waits (the reference has no such
                # detection — a dead MPI rank hangs the cluster). The
                # send side toward that peer is stale too — drop it so
                # retries reconnect rather than write into the corpse.
                if peer is not None:
                    self.drop_connection(peer)
                hook = self.on_peer_lost
                if hook is not None:
                    try:
                        hook(peer)
                    except Exception:  # noqa: BLE001 - abort must not die
                        pass

    # -- bootstrap --
    @classmethod
    def from_flags(cls) -> "TcpNet":
        """Machine-file bootstrap (ref: zmq_net.h:25-61): one host[:port]
        per line; own rank from -rank or by unique local-address match."""
        path = get_flag("machine_file")
        if not path:
            raise RuntimeError("machine_file flag not set")
        with open(path) as f:
            endpoints = [ln.strip() for ln in f if ln.strip()
                         and not ln.lstrip().startswith("#")]
        if not endpoints:
            raise RuntimeError(f"machine file {path!r} is empty")
        rank = int(get_flag("rank"))
        if rank < 0:
            port = int(get_flag("port"))
            local = local_addresses()
            matches = [i for i, e in enumerate(endpoints)
                       if _parse_endpoint(e, port)[0] in local]
            if len(matches) != 1:
                raise RuntimeError(
                    f"cannot determine own rank from {path!r}: "
                    f"{len(matches)} lines match local addresses; "
                    "pass -rank=N (required when ranks share a host)")
            rank = matches[0]
        return cls(rank, endpoints)


# -- app-driven deployment (MV_NetBind / MV_NetConnect parity) --

_pending_bind: Optional[Tuple[int, str]] = None
_pending_net: Optional[TcpNet] = None


def net_bind(rank: int, endpoint: str) -> None:
    """MV_NetBind (ref: multiverso.h:55-59, zmq_net.h:63-80): declare this
    process's rank and listening endpoint before ``mv.init``."""
    global _pending_bind
    _pending_bind = (rank, endpoint)


def net_connect(ranks: List[int], endpoints: List[str]) -> None:
    """MV_NetConnect (ref: multiverso.h:60-64, zmq_net.h:82-109): supply
    the full rank -> endpoint table and build the transport; ``mv.init``
    consumes it."""
    global _pending_net, _pending_bind
    if _pending_bind is None:
        raise RuntimeError("call net_bind(rank, endpoint) before "
                           "net_connect")
    if len(ranks) != len(endpoints):
        raise ValueError(f"net_connect: {len(ranks)} ranks but "
                         f"{len(endpoints)} endpoints")
    my_rank, my_endpoint = _pending_bind
    table = dict(zip(ranks, endpoints))
    table[my_rank] = my_endpoint
    if sorted(table) != list(range(len(table))):
        raise RuntimeError(f"net_connect needs a dense rank set, got "
                           f"{sorted(table)}")
    ordered = [table[r] for r in range(len(table))]
    _pending_net = TcpNet(my_rank, ordered)
    _pending_bind = None


def take_pending_net() -> Optional[TcpNet]:
    """Consume the transport prepared by net_bind/net_connect (called by
    Zoo.start)."""
    global _pending_net
    net, _pending_net = _pending_net, None
    return net
