"""Cross-process TCP message-stream transport.

TPU-native replacement for the reference's point-to-point backends — the
functional equivalent of its ZeroMQ DEALER mesh
(ref: include/multiverso/net/zmq_net.h:23-270) and of the MPI wrapper's
serialized send/recv (ref: include/multiverso/net/mpi_net.h:195-344),
implemented over plain TCP sockets so a multi-rank cluster needs no MPI
and no libzmq:

- every rank binds one listening socket and lazily opens one outbound
  connection per peer (full mesh, like the reference's per-peer DEALER
  sockets, ref: zmq_net.h:25-61);
- messages travel as length-prefixed frames: ``[total u64][header 10xi32]
  [nblobs u32][blob sizes u64 x n][blob bytes ...]`` — the same frame
  LAYOUT as the reference's MPI path (ref: mpi_net.h:289-317), but built
  zero-copy: the send side never joins the frame into one flat buffer
  (``serialize_views`` emits a small header buffer plus one view per
  blob payload, drained by ``socket.sendmsg`` vectored writes straight
  out of the Blobs' own memory), and the receive side leases a pooled
  buffer (``util/buffer_pool.py``), fills it with ``recv_into``, and
  cuts read-only Blob views directly from the frame. Device blobs still
  materialize to host bytes at the wire boundary. ``-zero_copy=0``
  falls back to the flat join/copy path (byte-identical frames — the
  bench baseline and the mixed-build escape hatch);
- all socket I/O — accepts, nonblocking connects, frame reads, frame
  writes — multiplexes onto ONE ``selectors`` event-loop thread per
  endpoint (``_EventLoop``). Each destination is a ``_Peer`` state
  machine (CONNECTING → HANDSHAKE → READY → DRAINING → DEAD) with a
  bounded outbound frame queue (``-send_queue_mb`` backpressure, same
  contract the per-peer writer threads used to enforce); each inbound
  connection is a ``_Conn`` read state machine filling the same pooled
  lease buffers the old reader threads did. Transport thread count is
  O(1) in peer count, a dead peer costs retry timers instead of a
  blocked thread, and dead-peer detection unifies onto
  selector-observed EOF/ECONNRESET plus the heartbeat path;
- bootstrap is machine-file driven (one ``host[:port]`` per line, own rank
  found by local-address match or the ``-rank`` flag,
  ref: zmq_net.h:20-28,25-61) or app-driven via ``net_bind``/
  ``net_connect`` (``MV_NetBind``/``MV_NetConnect`` parity,
  ref: include/multiverso/multiverso.h:55-64, zmq_net.h:63-109).

On TPU this is the *control/table plane* across hosts (DCN); tensor traffic
inside a jitted step rides XLA collectives and never sees this layer.
"""

from __future__ import annotations

import collections
import errno
import heapq
import os
import selectors
import socket
import struct
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.blob import Blob
from ..core.message import HEADER_SIZE, Message, trace_of
from ..util import chaos, log, tracing
from ..util.buffer_pool import BufferPool
from ..util.configure import (define_bool, define_double, define_int,
                              define_string, get_flag)
from ..util.dashboard import count, monitor, samples
from ..util.lock_witness import named_condition, named_lock
from ..util.mt_queue import MtQueue
from ..util.net_util import local_addresses
from . import thread_roles
from .net import NetInterface, PeerLostError

define_string("machine_file", "", "path: one host[:port] per rank line")
define_int("port", 55555, "default TCP port when a machine-file line has none")
define_int("rank", -1, "explicit rank override for machine-file bootstrap")
define_int("send_queue_mb", 32,
           "per-peer async send queue cap (MB): send_async blocks "
           "(backpressure) once this many serialized bytes are in flight "
           "to one destination — the transport twin of the worker "
           "coalescer's 4MB flush cap")
define_double("connect_timeout_s", 30.0,
              "seconds to keep retrying an outbound connection to a "
              "peer that is not (yet) listening — covers both bootstrap "
              "races and, with the fault-tolerance retry path, the "
              "restart window of a crashed peer (a send toward a dead "
              "rank waits in connect-retry until the replacement "
              "process binds, then delivers). The retries are "
              "nonblocking timers on the event loop: an unreachable "
              "peer costs zero blocked threads")
define_bool("zero_copy", True,
            "scatter-gather wire path: serialize outbound frames as "
            "view lists drained by sendmsg vectored writes (no flat "
            "join), and deserialize inbound frames as read-only Blob "
            "views into pooled receive buffers (-buffer_pool_mb). "
            "Frames are byte-identical either way (golden-tested) — "
            "0 restores the legacy join/copy path as the bench "
            "baseline and a diagnostics escape hatch")
define_double("net_pace_mbps", 0.0,
              "emulate a constrained wire: pace outbound frames to this "
              "many megabits/s. Each frame reserves its transmission "
              "slot on a shared busy-until deadline and is held on an "
              "event-loop timer until the slot opens, so a frame "
              "occupies the emulated wire for its transmission time and "
              "its ARRIVAL is delayed accordingly — no thread sleeps. "
              "Bench/test knob for reproducing DCN-speed behavior on "
              "localhost; 0 = off")

_HDR = struct.Struct(f"<{HEADER_SIZE}i")
_LEN = struct.Struct("<Q")
_NBLOBS = struct.Struct("<I")

_RECV_INTERRUPT = object()

#: _Peer connection states (peer.state; NET_PEER_STATE[*] counts every
#: transition). CONNECTING covers both "not dialed yet" and the timer
#: wait between nonblocking connect retries; HANDSHAKE is a connect_ex
#: in flight (EINPROGRESS, waiting for writability); DRAINING is READY
#: with a goodbye frame queued behind the remaining traffic (finalize);
#: DEAD peers are retired from the peer table — the next send toward
#: that rank starts a fresh state machine.
_ST_CONNECTING = "CONNECTING"
_ST_HANDSHAKE = "HANDSHAKE"
_ST_READY = "READY"
_ST_DRAINING = "DRAINING"
_ST_DEAD = "DEAD"

#: connect_ex return codes that mean "in progress, wait for the
#: selector" rather than "failed".
_EX_PENDING = frozenset(
    {errno.EINPROGRESS, errno.EWOULDBLOCK, errno.EAGAIN, errno.EALREADY})


def _parse_endpoint(line: str, default_port: int) -> Tuple[str, int]:
    line = line.strip()
    if ":" in line and not line.startswith("["):  # host:port (IPv4/name)
        host, port = line.rsplit(":", 1)
        return host, int(port)
    return line, default_port


def _read_exact(sock: socket.socket, n: int) -> Optional[bytearray]:
    """Read exactly n bytes; None on orderly EOF. Returns the filled
    ``bytearray`` itself — a ``bytes(buf)`` copy here used to tax every
    inbound frame once for nothing (struct unpacks and numpy views read
    a bytearray directly)."""
    buf = bytearray(n)
    return buf if _recv_into_exact(sock, memoryview(buf)) else None


def _recv_into_exact(sock: socket.socket, view: memoryview) -> bool:
    """Fill ``view`` completely from the socket; False on orderly EOF.
    The zero-copy twin of ``_read_exact``: the caller owns the buffer
    (a pooled frame lease), so nothing is allocated here."""
    n = view.nbytes
    got = 0
    while got < n:
        k = sock.recv_into(view[got:], n - got)
        if k == 0:
            return False
        got += k
    return True


def serialize_views(msg: Message) -> Tuple[List[memoryview], int]:
    """Scatter-gather framer: the wire frame as ``(views, nbytes)``
    where ``views[0]`` is the length-prefix + header + blob-size table
    (the only bytes this function builds) and every following view
    reads straight through ``Blob.wire_views()`` into the payload's own
    memory — no per-blob ``tobytes``, no ``b"".join``, no prefix
    concat. Drained by ``sendmsg`` vectored writes; joining the views
    reproduces ``_serialize``'s frame byte for byte (golden-tested),
    so the wire format is unchanged and mixed -zero_copy builds
    interoperate."""
    views: List[memoryview] = [memoryview(b"")]  # head placeholder
    sizes: List[int] = []
    payload = 0
    for blob in msg.data:
        # Device payloads cross the wire as host bytes (the reference's
        # serialize step; ref: mpi_net.h:289-317). Codec-filtered blobs
        # (header slot CODEC_SLOT set by the communicator) are already
        # uint8 frames — possibly in scatter-gather parts — and pass
        # through unchanged.
        nbytes = 0
        for view in blob.wire_views():
            nbytes += view.nbytes
            if view.nbytes:  # zero-length views would stall sendmsg
                views.append(view)
        sizes.append(nbytes)
        payload += nbytes
    body = _HDR.size + _NBLOBS.size + _LEN.size * len(sizes) + payload
    head = bytearray(_LEN.size + _HDR.size + _NBLOBS.size
                     + _LEN.size * len(sizes))
    _LEN.pack_into(head, 0, body)
    _HDR.pack_into(head, _LEN.size, *[int(v) for v in msg.header])
    off = _LEN.size + _HDR.size
    _NBLOBS.pack_into(head, off, len(sizes))
    off += _NBLOBS.size
    for sz in sizes:
        _LEN.pack_into(head, off, sz)
        off += _LEN.size
    views[0] = memoryview(head)
    # Copy accounting (docs/MEMORY.md): only the framing bytes are
    # built here; payload bytes go to the wire without a host copy.
    count("WIRE_BYTES_COPIED", len(head))
    count("WIRE_PAYLOAD_BYTES", payload)
    return views, _LEN.size + body


#: Buffers per sendmsg call — conservatively under IOV_MAX (1024 on
#: Linux); a frame with more views loops.
_IOV_CAP = 64

#: Emulated-wire catch-up window (s): how far behind its busy-until
#: timeline the pacing bucket lets a sender fall before slots anchor
#: to wall time again (``_pace_reserve``). Absorbs ms-scale wake
#: jitter without banking unbounded burst across idle gaps.
_PACE_CREDIT_S = 0.005


def _sendmsg_all(sock: socket.socket, views: List[memoryview]) -> None:
    """Drain ``views`` through vectored writes, handling partial sends
    (sendmsg may stop mid-view under backpressure). Views must be
    non-empty (``serialize_views`` filters zero-length ones). Blocking
    -socket helper for out-of-loop senders (the shm announce path and
    tests); ``_Peer._drain`` is the nonblocking event-loop twin of this
    arithmetic."""
    i = 0
    off = 0
    n = len(views)
    while i < n:
        if off:
            batch = [views[i][off:]]
            batch.extend(views[i + 1:i + _IOV_CAP])
        else:
            batch = views[i:i + _IOV_CAP]
        sent = sock.sendmsg(batch)
        while i < n and sent:
            remaining = views[i].nbytes - off
            if sent >= remaining:
                sent -= remaining
                i += 1
                off = 0
            else:
                off += sent
                sent = 0


def _frame_views(msg: Message) -> Tuple[List[memoryview], int]:
    """The outbound frame as vectored-write views: scatter-gather by
    default, a single view of the legacy flat frame under
    ``-zero_copy=0`` (identical bytes either way)."""
    if bool(get_flag("zero_copy")):
        return serialize_views(msg)
    frame = _serialize(msg)
    return [memoryview(frame)], len(frame)


def _serialize(msg: Message) -> bytes:
    """Flat-buffer serializer — the LEGACY path (``-zero_copy=0``), the
    golden reference the scatter-gather framer is byte-compared
    against, and the bench baseline whose copy count the zero-copy path
    is measured by. Each payload byte is copied ~3x here (per-blob
    tobytes, the join, the length-prefix concat)."""
    parts: List[bytes] = []
    blobs: List[bytes] = []
    payload = 0
    for blob in msg.data:
        blobs.append(blob.wire_bytes().tobytes())  # mvlint: ignore[copy-lint]
        payload += len(blobs[-1])
    header = _HDR.pack(*[int(v) for v in msg.header])
    parts.append(header)
    parts.append(_NBLOBS.pack(len(blobs)))
    for b in blobs:
        parts.append(_LEN.pack(len(b)))
    parts.extend(blobs)
    body = b"".join(parts)  # mvlint: ignore[copy-lint]
    frame = _LEN.pack(len(body)) + body
    count("WIRE_BYTES_COPIED", payload + len(body) + len(frame))
    count("WIRE_PAYLOAD_BYTES", payload)
    return frame


def _deserialize(body) -> Message:
    """Flat-buffer parser — the LEGACY path (``-zero_copy=0``): every
    payload byte is copied out of the frame into a private Blob
    array."""
    header = _HDR.unpack_from(body, 0)
    msg = Message()
    msg.header = list(header)
    off = _HDR.size
    (nblobs,) = _NBLOBS.unpack_from(body, off)
    off += _NBLOBS.size
    sizes = []
    payload = 0
    for _ in range(nblobs):
        (sz,) = _LEN.unpack_from(body, off)
        sizes.append(sz)
        off += _LEN.size
    for sz in sizes:
        msg.data.append(Blob(np.frombuffer(body, np.uint8, sz, off).copy()))
        off += sz
        payload += sz
    count("WIRE_BYTES_COPIED", payload)
    count("WIRE_PAYLOAD_BYTES", payload)
    return msg


def _deserialize_frame(body: memoryview, lease) -> Message:
    """Zero-copy parser: Blobs are READ-ONLY numpy views straight into
    the leased receive frame; ``lease`` rides every Blob and returns
    the buffer to the pool when the last one dies
    (util/buffer_pool.py). Mutating consumers must
    ``Blob.materialize()`` first — the copy-on-write contract
    (docs/MEMORY.md)."""
    header = _HDR.unpack_from(body, 0)
    msg = Message()
    msg.header = list(header)
    off = _HDR.size
    (nblobs,) = _NBLOBS.unpack_from(body, off)
    off += _NBLOBS.size
    sizes = []
    payload = 0
    for _ in range(nblobs):
        (sz,) = _LEN.unpack_from(body, off)
        sizes.append(sz)
        off += _LEN.size
    for sz in sizes:
        arr = np.frombuffer(body, np.uint8, sz, off)
        arr.flags.writeable = False
        msg.data.append(Blob.from_lease(arr, lease))
        off += sz
        payload += sz
    count("WIRE_PAYLOAD_BYTES", payload)
    return msg


class _EventLoop:
    """One ``selectors``-based I/O loop thread per endpoint.

    Everything the transport does with a socket — accepting, the
    nonblocking connect handshakes, frame reads, frame writes, retry
    and pacing timers, the shm ring doorbell — runs as handlers on this
    single EVENTLOOP thread. The pass-9 blocking-reachability proof
    (tools/mvlint/role_lint.py) pins the contract: the ONLY call that
    may park this thread is the ``selector.select(timeout)`` in
    ``_main``; every handler runs against nonblocking fds and timed
    waits, so no dead peer can ever strand the loop.

    Three thread-safe entry points exist for the rest of the process:
    ``call_soon(job)`` (enqueue a job and wake the loop), ``wake()``
    (self-pipe), and ``run_sync(fn)`` (call_soon + bounded wait —
    finalize uses it to run teardown ON the loop). ``call_later`` and
    the selector registration helpers are loop-thread-only.

    Jobs and timer payloads dispatch by object type — ``_Peer`` ticks,
    handler objects with ``on_misc_timer`` (TcpNet housekeeping, the
    shm ring service), or plain callables. The explicit isinstance
    chain is deliberate: it keeps every hot dispatch target statically
    resolvable for the blocking-reachability proof (a single dynamic
    ``job()`` would hide the transport behind an opaque call)."""

    def __init__(self, rank: int):
        self._rank = rank
        self._sel = selectors.DefaultSelector()
        self._pending: collections.deque = collections.deque()  # guarded_by: _pending_lock
        self._pending_lock = named_lock(f"tcp[r{rank}].loop.pending")
        self._timers: list = []  # heap of (when, seq, job); loop-thread only
        self._tseq = 0
        # Racy-by-design wake gate: worst case is one redundant
        # self-pipe byte; the loop resets it before draining jobs so a
        # racing call_soon can never be missed.
        self._woken = False
        self._stopped = False
        self._fds_closed = False
        rfd, wfd = os.pipe()
        os.set_blocking(rfd, False)
        os.set_blocking(wfd, False)
        self._rfd, self._wfd = rfd, wfd
        self._sel.register(rfd, selectors.EVENT_READ, _WakePipe(rfd))
        self._tick_gauge = samples("EVENTLOOP_TICK_MS")
        self._ready_gauge = samples("EVENTLOOP_READY_FDS")
        self._thread = thread_roles.spawn(
            thread_roles.EVENTLOOP, target=self._main,
            name=f"mv-net-loop-r{rank}")

    # -- thread-safe entry points --
    def on_loop(self) -> bool:
        return threading.current_thread() is self._thread

    def wake(self) -> None:
        if self._woken:
            return
        self._woken = True
        try:
            os.write(self._wfd, b"\0")
        except OSError:
            pass  # pipe full (a wake is already pending) or torn down

    def call_soon(self, job) -> None:
        """Enqueue ``job`` for the next loop iteration (any thread)."""
        with self._pending_lock:
            self._pending.append(job)
        self.wake()

    def run_sync(self, fn, timeout: float = 5.0) -> bool:
        """Run ``fn`` on the loop and wait (bounded) for it to finish.
        Runs inline when called from the loop itself or after the loop
        thread has exited (teardown stragglers must still run)."""
        if self.on_loop() or not self._thread.is_alive():
            fn()
            return True
        done = threading.Event()

        def job():
            try:
                fn()
            finally:
                done.set()

        self.call_soon(job)
        return done.wait(timeout=timeout)

    def stop(self, timeout: float = 5.0) -> None:
        self._stopped = True
        self.wake()
        if not self.on_loop():
            self._thread.join(timeout=timeout)
        if not self._thread.is_alive() and not self._fds_closed:
            self._fds_closed = True
            try:
                self._sel.close()
            except OSError:
                pass
            for fd in (self._rfd, self._wfd):
                try:
                    os.close(fd)
                except OSError:
                    pass

    # -- loop-thread-only helpers --
    def call_later(self, delay: float, job) -> None:
        self._tseq += 1
        heapq.heappush(self._timers,
                       (time.monotonic() + max(0.0, delay),
                        self._tseq, job))

    def register(self, fileobj, events: int, data) -> None:
        self._sel.register(fileobj, events, data)

    def modify(self, fileobj, events: int, data) -> None:
        self._sel.modify(fileobj, events, data)

    def unregister(self, fileobj) -> None:
        self._sel.unregister(fileobj)

    # -- the loop --
    def _dispatch_job(self, job) -> None:
        try:
            if isinstance(job, _Peer):
                job.on_peer_timer()
            elif hasattr(job, "on_misc_timer"):
                # Housekeeping handler objects (TcpNet gauge tick, the
                # shm ring service) — object dispatch, so the blocking
                # proof can resolve the targets.
                job.on_misc_timer()
            else:
                job()  # plain callable (call_soon/run_sync closures)
        except Exception:  # noqa: BLE001 - a handler bug must not take
            # the whole transport's I/O loop down with it
            log.error("event loop r%d: job %r raised:\n%s",
                      self._rank, job, traceback.format_exc())

    def _main(self) -> None:
        sel = self._sel
        select_errors = 0
        while True:
            # Re-arm the wake latch BEFORE the stop/pending checks and
            # the park. The pipe drain below swallows every byte queued
            # at drain time — including one written by a wake() racing
            # this iteration — so a latch cleared mid-iteration could
            # read True with an EMPTY pipe, suppressing every later
            # wake: a stop() landing in that state never wakes the
            # park and leaks this thread. Ordered this way, any wake
            # after the re-arm writes a real byte (select returns) and
            # any wake before it published its stop/pending state
            # before the checks below run.
            self._woken = False
            if self._stopped:
                return
            timeout = None
            if self._timers:
                timeout = max(0.0, self._timers[0][0] - time.monotonic())
                if timeout > 0.0015:
                    # epoll ceils its wait to whole milliseconds, so a
                    # timer parked for exactly `timeout` wakes up to
                    # 1 ms LATE — and the pacing bucket's busy-until
                    # arithmetic accumulates that drift per frame. Aim
                    # one quantum early; the residual re-select lands
                    # on time. (Sub-1.5 ms waits keep the ceil: a 0-
                    # timeout here would busy-spin the core instead.)
                    timeout -= 0.001
            with self._pending_lock:
                if self._pending:
                    timeout = 0.0
            try:
                # The ONLY blocking call an EVENTLOOP thread may make
                # (pass-9 pins this; the -debug_locks watchdog reads a
                # thread parked here as idle because this is the entry
                # frame).
                events = sel.select(timeout)
            except OSError:
                # An fd died under the selector (should be unreachable:
                # every close is preceded by unregister). Log and keep
                # serving; bail if it persists so a bug cannot hot-spin.
                select_errors += 1
                if select_errors > 100:
                    raise
                log.error("event loop r%d: select failed:\n%s",
                          self._rank, traceback.format_exc())
                events = []
            if self._stopped:
                return
            t0 = time.perf_counter()
            worked = bool(events)
            jobs = None
            with self._pending_lock:
                if self._pending:
                    jobs = list(self._pending)
                    self._pending.clear()
            if jobs:
                worked = True
                for job in jobs:
                    self._dispatch_job(job)
            now = time.monotonic()
            while self._timers and self._timers[0][0] <= now:
                _when, _seq, job = heapq.heappop(self._timers)
                worked = True
                self._dispatch_job(job)
            for key, mask in events:
                data = key.data
                try:
                    if isinstance(data, _Peer):
                        data.on_peer_io(mask)
                    elif isinstance(data, _Conn):
                        data.on_conn_io(mask)
                    elif isinstance(data, _Listener):
                        data.on_accept_io(mask)
                    else:
                        data.on_misc_io(mask)
                except Exception:  # noqa: BLE001 - ditto: one broken
                    # handler must not stop every other fd's service
                    log.error("event loop r%d: handler %r raised:\n%s",
                              self._rank, data, traceback.format_exc())
            if events:
                self._ready_gauge.add(len(events))
            if worked:
                self._tick_gauge.add((time.perf_counter() - t0) * 1e3)


class _WakePipe:
    """Self-pipe read end: drains wake bytes so the selector can park
    again. The payload is meaningless — the readiness edge is the
    signal."""

    def __init__(self, rfd: int):
        self._rfd = rfd

    def on_misc_io(self, mask: int) -> None:
        while True:
            try:
                chunk = os.read(self._rfd, 4096)
            except (BlockingIOError, OSError):
                return
            if not chunk:
                return


class _Listener:
    """Accept handler: the listening socket is nonblocking and
    registered on the loop; each accepted connection becomes a
    ``_Conn`` read state machine on the same selector (the old model
    spawned a blocking reader thread per connection here)."""

    def __init__(self, net: "TcpNet"):
        self._net = net

    def on_accept_io(self, mask: int) -> None:
        while True:
            try:
                conn, _addr = self._net._listener.accept()  # mvlint: ignore[thread-role] - nonblocking listener: EAGAIN ends the burst, never parks the loop
            except BlockingIOError:
                return
            except OSError:
                return  # listener closed (finalize)
            conn.setblocking(False)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._net._register_conn(_Conn(self._net, conn))


class _Conn:
    """One inbound connection's receive state machine (loop-thread
    only). Buffers and protocol are exactly the old reader thread's:
    an 8-byte length prefix, then either a pooled lease filled by
    ``recv_into`` (zero-copy) or a legacy bytearray (``-zero_copy=0``);
    a length-0 frame is the peer's goodbye (graceful close), EOF
    without one is a dirty close and reports the peer. The difference
    is shape: the fill tolerates partial reads and resumes whenever the
    selector reports readability instead of parking a thread in
    ``recv``."""

    #: Frames parsed per readiness event before yielding the loop
    #: (level-triggered epoll re-arms, so a firehose connection gets
    #: re-served next tick without starving the other fds).
    _FRAME_BUDGET = 32

    def __init__(self, net: "TcpNet", sock: socket.socket):
        self._net = net
        self._sock: Optional[socket.socket] = sock
        self._head = memoryview(bytearray(_LEN.size))
        self._head_got = 0
        self._total = 0
        self._lease = None  # pooled frame lease (zero-copy path)
        self._legacy: Optional[bytearray] = None  # -zero_copy=0 path
        self._body: Optional[memoryview] = None  # fill target
        self._body_got = 0
        self._t0_ns = 0
        self.peer: Optional[int] = None  # rank learned from frames

    def on_conn_io(self, mask: int) -> None:
        if self._sock is None:
            return  # stale event: torn down earlier in this batch
        try:
            self._read_burst()
        except BlockingIOError:
            pass  # socket drained mid-frame; resumes on next readiness
        except OSError:
            self._close(clean=False)

    def _read_burst(self) -> None:
        frames = 0
        while frames < self._FRAME_BUDGET:
            if self._body is None:
                # Header phase: accumulate the 8-byte length prefix.
                k = self._sock.recv_into(self._head[self._head_got:])  # mvlint: ignore[thread-role] - nonblocking fd: EAGAIN raises, never parks
                if k == 0:
                    self._close(clean=False)  # EOF without goodbye
                    return
                self._head_got += k
                if self._head_got < _LEN.size:
                    continue
                (total,) = _LEN.unpack(self._head)
                self._head_got = 0
                if total == 0:  # goodbye frame: graceful peer close
                    self._close(clean=True)
                    return
                self._total = total
                self._t0_ns = tracing.now_ns()
                if bool(get_flag("zero_copy")):
                    self._lease = self._net._pool.lease(total)
                    self._body = self._lease.view(total)
                else:
                    self._legacy = bytearray(total)
                    self._body = memoryview(self._legacy)
                self._body_got = 0
            # Body phase: progressive fill of the leased buffer.
            with monitor("tcp_recv"):
                k = self._sock.recv_into(self._body[self._body_got:])  # mvlint: ignore[thread-role] - nonblocking fd: EAGAIN raises, never parks
            if k == 0:
                self._close(clean=False)  # EOF mid-frame
                return
            self._body_got += k
            if self._body_got < self._total:
                continue
            self._finish_frame()
            frames += 1

    def _finish_frame(self) -> None:
        total = self._total
        lease, self._lease = self._lease, None
        legacy, self._legacy = self._legacy, None
        self._body = None
        self._total = 0
        with monitor("tcp_deserialize"):
            if legacy is None:
                msg = _deserialize_frame(lease.view(total), lease)
            else:
                msg = _deserialize(legacy)
        tid = trace_of(msg)
        if tid:
            # The trace id is only known after the parse; the span
            # still covers the read+deserialize window.
            tracing.add_span(tid, "tcp_recv", self._net.rank,
                             self._t0_ns, tracing.now_ns() - self._t0_ns,
                             args={"bytes": total})
        # Every inbound frame names its sender; remembering it lets a
        # dirty close report WHICH peer died (the zoo's rejoin path
        # fails only that rank's in-flight requests instead of aborting
        # the whole cluster).
        if 0 <= msg.src < self._net.size and msg.src != self._net.rank:
            self.peer = msg.src
        self._net._inbox.push(msg)

    def close_for_teardown(self) -> None:
        self._close(clean=True)

    def _close(self, clean: bool) -> None:
        if self._sock is None:
            return
        self._net._unregister_conn(self)
        sock, self._sock = self._sock, None
        try:
            sock.close()
        except OSError:
            pass
        lease, self._lease = self._lease, None
        self._body = None
        self._legacy = None
        if lease is not None:
            lease.release()  # mid-frame teardown: recycle the buffer
        # Racy teardown check by design: worst case is one spurious
        # peer-lost report during finalize, which abort ignores.
        if not clean and not self._net._closed:  # mvlint: ignore[guarded-by]
            # A peer hung up while the mesh is live: report it so the
            # zoo can abort blocked waits (the reference has no such
            # detection — a dead MPI rank hangs the cluster).
            self._net._conn_died(self.peer)


class _Peer:
    """Per-destination connection state machine + bounded outbound
    frame queue (CONNECTING → HANDSHAKE → READY → DRAINING → DEAD).

    Replaces the per-destination writer THREAD: ``submit`` enqueues
    ``(views, nbytes)`` scatter-gather frames under the same
    ``-send_queue_mb`` backpressure contract, and the event loop drains
    them with nonblocking ``sendmsg`` vectored writes — partial-send
    resume included — so the views alias the payload's own buffers
    until the write completes (the ``send_async`` contract: callers
    must not mutate a queued payload before ``flush_sends``). A wire
    error parks in ``error`` and re-raises from the next submit/flush
    as ``PeerLostError``; the dead machine retires itself from the peer
    table, so the next send toward this rank dials fresh.

    Locking: the queue fields are caller-shared under ``_cond``;
    everything about the socket and connection state is loop-thread
    only."""

    #: Frames written per drain pass before yielding the loop (WRITE
    #: readiness re-kicks immediately; the budget just interleaves
    #: other fds' service between bursts — and keeps the watchdog's
    #: same-line stack heuristic from mistaking a long burst for a
    #: parked thread).
    _DRAIN_FRAMES = 64

    #: Pacing burst slack (s): epoll timers have ~1 ms granularity, so
    #: parking for a sub-millisecond pace gap wakes late and the
    #: chunked pipelines bleed a timer-quantum per frame. A frame due
    #: within this window sends immediately instead — the token
    #: bucket's absolute busy-until arithmetic keeps the long-run rate
    #: exact, this only trades ms-scale smoothness (the old sleeping
    #: writer's overshoot, in the other direction).
    _PACE_SLACK = 0.002

    def __init__(self, net: "TcpNet", dst: int):
        self._net = net
        self._loop = net._loop
        self._dst = dst
        self._cond = named_condition(f"tcp[r{net.rank}].peer[d{dst}]")
        self._frames: collections.deque = collections.deque()  # guarded_by: _cond
        self._queued_bytes = 0  # guarded_by: _cond
        self._inflight = False  # guarded_by: _cond
        self._kicked = False  # guarded_by: _cond
        self.error: Optional[BaseException] = None  # guarded_by: _cond
        self.closed = False  # guarded_by: _cond
        # Loop-thread-only connection state:
        self.state = _ST_CONNECTING
        self._sock: Optional[socket.socket] = None
        self._registered = False
        self._want_write = False
        self._cur: Optional[list] = None  # [views, i, off, nbytes, t0, bye]
        self._pace_until = 0.0
        self._deadline = 0.0  # connect-epoch deadline (0 = not dialing)
        self._retry_at = 0.0
        self._retry_delay = 0.02
        self._eof_scratch = memoryview(bytearray(256))
        self._depth_gauge = samples(f"DISPATCH_QUEUE_DEPTH[d{dst}]")
        self._lat_gauge = samples(f"DISPATCH_MS[d{dst}]")
        count(f"NET_PEER_STATE[{_ST_CONNECTING}]")

    # -- caller-side API (any thread) --
    def submit(self, views: List[memoryview], nbytes: int,
               goodbye: bool = False) -> None:
        cap = max(1, int(get_flag("send_queue_mb"))) << 20
        # The loop itself must never park on backpressure (it IS the
        # drain); loop-side submits (the finalize goodbye) enqueue
        # unconditionally.
        on_loop = self._loop.on_loop()
        kick = False
        with self._cond:
            while (not on_loop and self._queued_bytes >= cap
                   and self.error is None and not self.closed):
                self._cond.wait(timeout=1.0)
            if self.error is not None:
                # The endpoint is DEAD: typed so callers can tell a
                # lost peer — retryable after a rejoin — from a local
                # programming error.
                raise PeerLostError(
                    f"send to rank {self._dst} failed: peer connection "
                    f"is dead ({self.error})") from self.error
            if self.closed and not goodbye:
                raise RuntimeError("TcpNet finalized")
            self._frames.append(
                (views, nbytes, time.perf_counter(), goodbye))
            self._queued_bytes += nbytes
            depth = len(self._frames)
            if not self._kicked:
                self._kicked = True
                kick = True
            self._cond.notify_all()
        self._depth_gauge.add(depth)
        if kick:
            self._loop.call_soon(self)

    def flush(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while (self._frames or self._inflight) and self.error is None:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise RuntimeError(
                        f"flush_sends: {self._queued_bytes} bytes to rank "
                        f"{self._dst} not drained within {timeout}s")
                self._cond.wait(timeout=1.0 if remaining is None
                                else min(remaining, 1.0))
            if self.error is not None:
                raise PeerLostError(
                    f"send to rank {self._dst} failed: peer connection "
                    f"is dead ({self.error})") from self.error

    @property
    def queued_bytes(self) -> int:
        with self._cond:
            return self._queued_bytes

    def depth(self) -> int:
        with self._cond:
            return len(self._frames) + (1 if self._inflight else 0)

    # -- loop-side state machine --
    def _set_state(self, state: str) -> None:
        self.state = state
        count(f"NET_PEER_STATE[{state}]")

    def on_peer_timer(self) -> None:
        """Loop tick: advance whatever the current state allows. Kicks
        from submit, connect-retry and pacing timers, and drain-budget
        yields all funnel here — a tick is idempotent, so over-kicking
        is harmless."""
        with self._cond:
            self._kicked = False
        if self.state in (_ST_READY, _ST_DRAINING):
            self._drain()
        elif (self.state == _ST_CONNECTING and self._sock is None
                and time.monotonic() >= self._retry_at):
            self._dial()

    def on_peer_io(self, mask: int) -> None:
        if self._sock is None or self.state == _ST_DEAD:
            return  # stale event: torn down earlier in this batch
        if self.state == _ST_HANDSHAKE:
            err = self._sock.getsockopt(socket.SOL_SOCKET,
                                        socket.SO_ERROR)
            if err:
                self._teardown_socket()
                self._connect_failed(OSError(err, os.strerror(err)))
            else:
                self._on_connected()
            return
        if mask & selectors.EVENT_READ and not self._probe_eof():
            return  # died on the read edge
        if mask & selectors.EVENT_WRITE:
            self._drain()

    def _dial(self) -> None:
        """Nonblocking connect attempt: connect_ex + selector-observed
        completion, with per-peer exponential backoff timers between
        attempts — the replacement for the old blocking dial loop that
        parked a writer thread for up to -connect_timeout_s per dead
        peer."""
        now = time.monotonic()
        if not self._deadline:
            self._deadline = now + float(get_flag("connect_timeout_s"))
        host, port = self._net._peers[self._dst]
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        try:
            err = sock.connect_ex((host, port))
        except OSError as exc:  # e.g. name resolution failure
            try:
                sock.close()
            except OSError:
                pass
            self._connect_failed(exc)
            return
        if err != 0 and err not in _EX_PENDING:
            try:
                sock.close()
            except OSError:
                pass
            self._connect_failed(OSError(err, os.strerror(err)))
            return
        # Connected-immediately (err 0, loopback) still goes through
        # HANDSHAKE: the socket is instantly writable, so the selector
        # confirms it on the next tick — one uniform path.
        self._sock = sock
        self._set_state(_ST_HANDSHAKE)
        self._register(selectors.EVENT_READ | selectors.EVENT_WRITE)

    def _on_connected(self) -> None:
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._deadline = 0.0
        self._retry_delay = 0.02
        self._want_write = True  # force the modify down to READ-only
        self._set_want_write(False)
        # A peer that finished its handshake after finalize began goes
        # straight to DRAINING: the queued frames (goodbye included)
        # still flush, but the state never reads READY.
        self._set_state(_ST_DRAINING if self.closed else _ST_READY)  # mvlint: ignore[guarded-by] - closed is loop-written after __init__; the cond only orders it for caller-side reads
        self._drain()

    def _connect_failed(self, exc: BaseException) -> None:
        now = time.monotonic()
        if now >= self._deadline:
            host, port = self._net._peers[self._dst]
            timeout_s = float(get_flag("connect_timeout_s"))
            # Typed as a lost peer: unreachable-within-timeout is
            # exactly the retryable condition (bootstrap race or a
            # crashed rank whose replacement has not bound yet). No
            # peer-lost report — parity with the old blocking dialer,
            # whose deadline raised into the sender without declaring
            # the peer dead.
            self._die(PeerLostError(
                f"rank {self._net.rank}: cannot reach rank {self._dst} "
                f"at {host}:{port} within {timeout_s}s"), report=False)
            return
        if self.state != _ST_CONNECTING:
            self._set_state(_ST_CONNECTING)
        self._retry_at = now + self._retry_delay
        self._retry_delay = min(self._retry_delay * 2, 0.5)
        self._loop.call_later(self._retry_at - now, self)

    def _probe_eof(self) -> bool:
        """READ readiness on the outbound socket. The protocol never
        sends bytes back on this direction, so readability means EOF or
        an error — the selector-observed half of dead-peer detection.
        EOF with frames queued is a mid-send death (report it); EOF on
        an idle peer is the remote side's own graceful close racing
        ours — retire quietly and let the next send dial fresh."""
        try:
            k = self._sock.recv_into(self._eof_scratch)  # mvlint: ignore[thread-role] - nonblocking fd: EAGAIN raises, never parks
        except BlockingIOError:
            return True
        except OSError as exc:
            self._die(exc)
            return False
        if k:
            return True  # stray bytes: not ours to interpret
        with self._cond:
            busy = bool(self._frames) or self._inflight
        self._die(ConnectionResetError(
            errno.ECONNRESET,
            f"rank {self._dst} closed the connection"), report=busy)
        return False

    def _drain(self) -> None:
        """Write queued frames with nonblocking vectored sends — the
        same partial-send arithmetic as ``_sendmsg_all``, suspended on
        EAGAIN (WRITE interest re-arms it) instead of blocking."""
        sock = self._sock
        if sock is None or self.state not in (_ST_READY, _ST_DRAINING):
            return
        budget = self._DRAIN_FRAMES
        while True:
            cur = self._cur
            if cur is None:
                with self._cond:
                    if not self._frames:
                        break
                    views, nbytes, t_submit, goodbye = \
                        self._frames.popleft()
                    self._inflight = True
                cur = self._cur = [views, 0, 0, nbytes, t_submit, goodbye]
                self._pace_until = self._net._pace_reserve(nbytes)
            if self._pace_until:
                now = time.monotonic()
                if now + self._PACE_SLACK < self._pace_until:
                    # Paced frame not due yet: park on a loop timer,
                    # not a sleep — every other fd keeps being served.
                    self._set_want_write(False)
                    self._loop.call_later(self._pace_until - now, self)
                    return
                self._pace_until = 0.0
            views, i, off, nbytes, t_submit, goodbye = cur
            n = len(views)
            try:
                while i < n:
                    if off:
                        batch = [views[i][off:]]
                        batch.extend(views[i + 1:i + _IOV_CAP])
                    else:
                        batch = views[i:i + _IOV_CAP]
                    with monitor("tcp_send"):
                        sent = sock.sendmsg(batch)
                    while i < n and sent:
                        remaining = views[i].nbytes - off
                        if sent >= remaining:
                            sent -= remaining
                            i += 1
                            off = 0
                        else:
                            off += sent
                            sent = 0
            except BlockingIOError:
                cur[1], cur[2] = i, off  # resume exactly here
                self._set_want_write(True)
                return
            except OSError as exc:
                self._die(exc)
                return
            # Frame complete (kernel accepted every byte). Drop the
            # view list before anything else: the views alias payload
            # buffers (possibly a pooled receive frame being
            # forwarded), and holding them would pin that memory.
            self._cur = None
            views = cur = None
            self._net._count_sent(nbytes)
            self._lat_gauge.add((time.perf_counter() - t_submit) * 1e3)
            with self._cond:
                self._queued_bytes -= nbytes
                self._inflight = False
                self._cond.notify_all()
            if goodbye:
                self._finish_close()
                return
            budget -= 1
            if budget <= 0:
                # Yield the tick: WRITE interest re-fires immediately
                # while the socket stays writable, so the remaining
                # frames interleave with other fds' service.
                self._set_want_write(True)
                return
        self._set_want_write(False)

    def _finish_close(self) -> None:
        """Goodbye frame fully written: the graceful half of DRAINING →
        DEAD. No error parks — flush() returns normally."""
        self._teardown_socket()
        self._set_state(_ST_DEAD)
        self._net._retire_peer(self)
        with self._cond:
            self._cond.notify_all()

    def _die(self, exc: BaseException, report: bool = True) -> None:
        """Peer death on the loop: close the socket, park the error for
        submit/flush waiters, clear the queue (the old writer threads
        did the same — zoo.peer_lost fails the stranded requests), and
        retire this machine from the peer table."""
        if self.state == _ST_DEAD:
            return
        self._teardown_socket()
        self._cur = None
        self._pace_until = 0.0
        self._set_state(_ST_DEAD)
        with self._cond:
            if self.error is None:
                self.error = exc
            self._frames.clear()
            self._queued_bytes = 0
            self._inflight = False
            self._cond.notify_all()
        self._net._retire_peer(self)
        if report and not self.closed:  # mvlint: ignore[guarded-by] - loop-side read; closed only transitions False->True, worst case a report during finalize that abort ignores
            self._net._report_send_death(self._dst, exc)

    def kill(self, exc: BaseException) -> None:
        """Teardown entry for drop_connection/finalize: death without a
        peer-lost report."""
        self._die(exc, report=False)

    def _teardown_socket(self) -> None:
        self._unregister()
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _register(self, mask: int) -> None:
        self._loop.register(self._sock, mask, self)
        self._registered = True
        self._want_write = bool(mask & selectors.EVENT_WRITE)

    def _unregister(self) -> None:
        if self._registered and self._sock is not None:
            try:
                self._loop.unregister(self._sock)
            except (KeyError, ValueError):
                pass
        self._registered = False

    def _set_want_write(self, want: bool) -> None:
        if want == self._want_write or not self._registered:
            return
        self._want_write = want
        mask = selectors.EVENT_READ
        if want:
            mask |= selectors.EVENT_WRITE
        self._loop.modify(self._sock, mask, self)


class TcpNet(NetInterface):
    """One endpoint of a full-mesh TCP cluster."""

    #: Optional callback fired when a peer connection dies while the
    #: mesh is still supposed to be up (set by Zoo.start -> Zoo.abort).
    on_peer_lost = None

    #: Live instances (the test-suite leak guard scopes its FD baseline
    #: check to tests that actually built an endpoint).
    instances_created = 0

    def __init__(self, rank: int, endpoints: List[str],
                 default_port: Optional[int] = None):
        if not 0 <= rank < len(endpoints):
            raise ValueError(f"rank {rank} not in endpoint list "
                             f"of size {len(endpoints)}")
        port = default_port if default_port is not None \
            else int(get_flag("port"))
        self._rank = rank
        self._peers = [_parse_endpoint(e, port) for e in endpoints]
        self._inbox: MtQueue = MtQueue()
        self._lifecycle = named_lock(f"tcp[r{rank}].lifecycle")
        self._out_peers: Dict[int, _Peer] = {}  # guarded_by: _lifecycle
        self._closed = False  # guarded_by: _lifecycle
        self._stats_lock = named_lock(f"tcp[r{rank}].stats")
        self._bytes_sent = 0  # guarded_by: _stats_lock
        self._wire_free_at = 0.0  # guarded_by: _stats_lock
        # Receive-frame pool shared by every inbound connection of this
        # endpoint (the leases are what recycle the buffers; the pool
        # itself only caps what is RETAINED, so reads never block).
        self._pool = BufferPool()
        self._conns: set = set()  # loop-thread only: live inbound conns
        self._transport_gauge = samples("TRANSPORT_THREADS")

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("", self._peers[rank][1]))
        self._listener.listen(len(endpoints) + 4)
        self._listener.setblocking(False)
        self._loop = _EventLoop(rank)
        self._loop.call_soon(self._start_on_loop)
        TcpNet.instances_created += 1
        log.debug("TcpNet rank %d listening on %s:%d", rank,
                  self._peers[rank][0], self._peers[rank][1])

    def _start_on_loop(self) -> None:
        self._loop.register(self._listener, selectors.EVENT_READ,
                            _Listener(self))
        self.on_misc_timer()

    def on_misc_timer(self) -> None:
        """Housekeeping tick (~2s on the loop): record the transport
        thread gauge — O(1) in peer count is the point of the
        event-loop core, and TRANSPORT_THREADS is how the bench's
        many-connection arm proves it."""
        alive = thread_roles.roles_alive()
        self._transport_gauge.add(
            alive.get(thread_roles.EVENTLOOP, 0)
            + alive.get(thread_roles.WRITER, 0))
        # Racy re-arm guard by design: one extra tick after finalize at
        # worst — the loop exits right after.
        if not self._closed:  # mvlint: ignore[guarded-by]
            self._loop.call_later(2.0, self)

    # -- NetInterface --
    @property
    def rank(self) -> int:
        return self._rank

    @property
    def size(self) -> int:
        return len(self._peers)

    def send(self, msg: Message) -> int:
        """Serialize + send, each under a Dashboard monitor (the
        reference instruments exactly these wire phases,
        ref: mpi_net.h:292-342 MVA_NET_SERIALIZE/SEND sites). The
        blocking path is submit + flush on the destination's queue:
        FIFO with earlier async frames for free, and the caller parks
        in a timed wait, never on a socket."""
        dst = msg.dst
        if not 0 <= dst < self.size:
            raise ValueError(f"bad dst rank {dst}")
        tid = trace_of(msg)
        with monitor("tcp_serialize"), \
                tracing.span(tid, "tcp_serialize", self._rank):
            views, nbytes = _frame_views(msg)
        with tracing.span(tid, "tcp_send", self._rank,
                          args={"dst": dst, "bytes": nbytes}
                          if tid else None):
            peer = self._peer(dst)
            peer.submit(views, nbytes)
            peer.flush(timeout=60.0)
        return nbytes

    def send_async(self, msg: Message) -> int:
        """Queue one serialized frame on the destination's peer state
        machine and return immediately (the non-blocking half of the
        chunked allreduce pipeline: multiple frames in flight per
        peer)."""
        dst = msg.dst
        if not 0 <= dst < self.size:
            raise ValueError(f"bad dst rank {dst}")
        # Chaos harness (-chaos_frames, util/chaos.py): direct async
        # senders (liveness/metrics frames) bypass the communicator's
        # choke point, so the fault filter hooks here too (one flag
        # probe when disarmed).
        faulted = chaos.filter_frames(msg)
        if faulted is not None:
            total = 0
            for m in faulted:
                total += self._send_async_real(m)
            return total
        return self._send_async_real(msg)

    def _send_async_real(self, msg: Message) -> int:
        dst = msg.dst
        tid = trace_of(msg)
        with monitor("tcp_serialize"), \
                tracing.span(tid, "tcp_serialize", self._rank):
            views, nbytes = _frame_views(msg)
        if tid:
            # The actual socket write happens on the event loop, which
            # only sees bytes — the submit marker is the async path's
            # wire hop for sampled traces.
            tracing.event(tid, "tcp_send_async_submit", self._rank,
                          args={"dst": dst, "bytes": nbytes})
        self._peer(dst).submit(views, nbytes)
        return nbytes

    def flush_sends(self, dst: Optional[int] = None,
                    timeout: Optional[float] = None) -> None:
        # Snapshot under the lock (a concurrent drop_connection must
        # not mutate the dict mid-iteration); flush OUTSIDE it — flush
        # blocks, and _peer() needs the lock to register new peers.
        with self._lifecycle:
            peers = [self._out_peers[dst]] if dst is not None \
                and dst in self._out_peers else \
                (list(self._out_peers.values()) if dst is None else [])
        for peer in peers:
            peer.flush(timeout)

    def queue_depths(self) -> Dict[int, int]:
        """Outbound frames queued (or mid-write) per destination — the
        live-introspection port autotune and the bench read."""
        with self._lifecycle:
            peers = list(self._out_peers.items())
        return {dst: peer.depth() for dst, peer in peers}

    @property
    def bytes_sent(self) -> int:
        with self._stats_lock:
            return self._bytes_sent

    def _peer(self, dst: int) -> _Peer:
        # Double-checked probe: the hot async-send path skips the
        # lifecycle lock; the slow path below re-reads under it.
        peer = self._out_peers.get(dst)  # mvlint: ignore[guarded-by]
        if peer is None:
            with self._lifecycle:
                if self._closed:
                    raise RuntimeError("TcpNet finalized")
                peer = self._out_peers.get(dst)
                if peer is None:
                    peer = self._out_peers[dst] = _Peer(self, dst)
        return peer

    # -- peer-death bookkeeping --
    def drop_connection(self, dst: int) -> None:
        """Forget the outbound connection state for ``dst``: retire the
        (possibly dead) peer state machine. The next send toward
        ``dst`` reconnects from scratch — the fault-tolerance retry
        path calls this when a peer is declared dead so a restarted
        replacement process is actually reachable instead of every
        retry hitting the broken socket."""
        with self._lifecycle:
            peer = self._out_peers.pop(dst, None)
        if peer is None:
            return
        exc = PeerLostError(f"connection to rank {dst} dropped")
        if self._loop.on_loop():
            peer.kill(exc)
        else:
            self._loop.call_soon(lambda: peer.kill(exc))

    def _retire_peer(self, peer: _Peer) -> None:
        with self._lifecycle:
            if self._out_peers.get(peer._dst) is peer:
                del self._out_peers[peer._dst]

    def _report_send_death(self, dst: int, exc: BaseException) -> None:
        """A connection toward ``dst`` broke while the mesh is live
        (inbound conns report via their own dirty-close path; this
        covers the SEND side, where the rank is known)."""
        # Racy loop-guard read by design: a teardown racing a peer
        # death at worst reports a peer that finalize already forgot.
        if self._closed:  # mvlint: ignore[guarded-by]
            return
        log.error("TcpNet rank %d: connection to rank %d died: %s",
                  self._rank, dst, exc)
        hook = self.on_peer_lost
        if hook is not None:
            try:
                hook(dst)
            except Exception:  # noqa: BLE001 - failure handling must
                # not take the transport down with it
                pass

    def _conn_died(self, peer: Optional[int]) -> None:
        """Dirty close of an inbound connection: the send side toward
        that peer is stale too — drop it so retries reconnect rather
        than write into the corpse, then report the loss."""
        if peer is not None:
            self.drop_connection(peer)
        hook = self.on_peer_lost
        if hook is not None:
            try:
                hook(peer)
            except Exception:  # noqa: BLE001 - abort must not die
                pass

    def _count_sent(self, nbytes: int) -> None:
        with self._stats_lock:
            self._bytes_sent += nbytes

    def _pace_reserve(self, nbytes: float) -> float:
        """Emulated-wire pacing (-net_pace_mbps): one shared outbound
        link per endpoint, modeled as an absolute busy-until deadline.
        Each frame reserves its transmission slot and returns the
        monotonic time before which it must not be written (0.0 when
        pacing is off); the event loop holds the frame on a timer until
        then. An overrun on one frame credits the next instead of
        accumulating — same arithmetic the sleeping version used, just
        parked on a timer instead of a thread."""
        mbps = float(get_flag("net_pace_mbps"))
        if mbps <= 0:
            return 0.0
        tx = nbytes * 8.0 / (mbps * 1e6)
        with self._stats_lock:
            # Bounded catch-up credit: the loop wakes for a paced frame
            # with ms-scale jitter (epoll granularity + GIL handoff),
            # and anchoring each slot at max(now, busy-until) would
            # compound every late wake into all later slots — the
            # emulated wire would run measurably under its configured
            # rate. Let the bucket keep its own timeline instead,
            # unless the sender falls more than the credit window
            # behind (idle links still never bank unbounded burst).
            start = max(time.monotonic() - _PACE_CREDIT_S,
                        self._wire_free_at)
            self._wire_free_at = target = start + tx
        return target

    def recv(self, timeout: Optional[float] = None) -> Optional[Message]:
        item = self._inbox.pop(timeout=timeout)
        if item is _RECV_INTERRUPT:
            return None
        return item

    def deliver(self, msg: Message) -> None:
        """Inject a locally received message into the inbox — the
        delivery port of the shm ring service (runtime/shm.py), so
        ring-borne and socket-borne frames share one queue and recv
        keeps its blocking semantics and per-source FIFO."""
        self._inbox.push(msg)

    # -- inbound-conn bookkeeping (loop thread) --
    def _register_conn(self, conn: _Conn) -> None:
        self._conns.add(conn)
        self._loop.register(conn._sock, selectors.EVENT_READ, conn)

    def _unregister_conn(self, conn: _Conn) -> None:
        self._conns.discard(conn)
        try:
            self._loop.unregister(conn._sock)
        except (KeyError, ValueError):
            pass

    def finalize(self) -> None:
        with self._lifecycle:
            if self._closed:
                return
            self._closed = True
            peers = dict(self._out_peers)
        # Stop accepting, then queue a goodbye frame (length 0 — tells
        # each peer's receive side this close is GRACEFUL) behind every
        # destination's remaining traffic. DRAINING peers flush queued
        # frames first, so a goodbye can never truncate the stream
        # mid-payload — a ring allreduce returns once it has RECEIVED
        # everything, so its final-step sends may still be queued here,
        # and a peer's collective depends on them.
        self._loop.run_sync(self._teardown_listener, timeout=2.0)
        self._loop.run_sync(
            lambda: [self._begin_drain(p) for p in peers.values()],
            timeout=5.0)
        # Bounded drain per peer, scaled by what is queued (wire-rate
        # paced frames can legitimately take many seconds); a wedged or
        # dead peer is force-killed below.
        pace = float(get_flag("net_pace_mbps"))
        for peer in peers.values():
            pending = peer.queued_bytes
            drain = 2.0 + pending / (4 << 20)  # >=4 MB/s of real wire
            if pace > 0:
                drain += pending * 8.0 / (pace * 1e6)
            try:
                peer.flush(timeout=drain)
            except (PeerLostError, RuntimeError):
                pass
        self._loop.run_sync(self._teardown_links, timeout=5.0)
        self._loop.stop(timeout=5.0)
        self._inbox.exit()

    def _teardown_listener(self) -> None:
        try:
            self._loop.unregister(self._listener)
        except (KeyError, ValueError):
            pass
        try:
            self._listener.close()
        except OSError:
            pass

    def _begin_drain(self, peer: _Peer) -> None:
        """Finalize, on the loop: refuse new frames, queue the goodbye.
        READY → DRAINING; a peer still connecting keeps its backoff
        machine (the goodbye flushes if the handshake completes within
        the drain bound, else the force-kill reaps it)."""
        with peer._cond:
            already = peer.closed
            peer.closed = True
            peer._cond.notify_all()
        if already or peer.state == _ST_DEAD:
            return
        if peer.state == _ST_READY:
            peer._set_state(_ST_DRAINING)
        try:
            peer.submit([memoryview(_LEN.pack(0))], _LEN.size,
                        goodbye=True)
        except (PeerLostError, RuntimeError):
            pass  # already dead: nothing to say goodbye on

    def _teardown_links(self) -> None:
        with self._lifecycle:
            stragglers = list(self._out_peers.values())
        for peer in stragglers:
            peer.kill(RuntimeError("TcpNet finalized"))
        for conn in list(self._conns):
            conn.close_for_teardown()

    def interrupt_recv(self) -> None:
        self._inbox.push(_RECV_INTERRUPT)

    # -- bootstrap --
    @classmethod
    def from_flags(cls) -> "TcpNet":
        """Machine-file bootstrap (ref: zmq_net.h:25-61): one host[:port]
        per line; own rank from -rank or by unique local-address match."""
        path = get_flag("machine_file")
        if not path:
            raise RuntimeError("machine_file flag not set")
        with open(path) as f:
            endpoints = [ln.strip() for ln in f if ln.strip()
                         and not ln.lstrip().startswith("#")]
        if not endpoints:
            raise RuntimeError(f"machine file {path!r} is empty")
        rank = int(get_flag("rank"))
        if rank < 0:
            port = int(get_flag("port"))
            local = local_addresses()
            matches = [i for i, e in enumerate(endpoints)
                       if _parse_endpoint(e, port)[0] in local]
            if len(matches) != 1:
                raise RuntimeError(
                    f"cannot determine own rank from {path!r}: "
                    f"{len(matches)} lines match local addresses; "
                    "pass -rank=N (required when ranks share a host)")
            rank = matches[0]
        return cls(rank, endpoints)


# -- app-driven deployment (MV_NetBind / MV_NetConnect parity) --

_pending_bind: Optional[Tuple[int, str]] = None
_pending_net: Optional[TcpNet] = None


def net_bind(rank: int, endpoint: str) -> None:
    """MV_NetBind (ref: multiverso.h:55-59, zmq_net.h:63-80): declare this
    process's rank and listening endpoint before ``mv.init``."""
    global _pending_bind
    _pending_bind = (rank, endpoint)


def net_connect(ranks: List[int], endpoints: List[str]) -> None:
    """MV_NetConnect (ref: multiverso.h:60-64, zmq_net.h:82-109): supply
    the full rank -> endpoint table and build the transport; ``mv.init``
    consumes it."""
    global _pending_net, _pending_bind
    if _pending_bind is None:
        raise RuntimeError("call net_bind(rank, endpoint) before "
                           "net_connect")
    if len(ranks) != len(endpoints):
        raise ValueError(f"net_connect: {len(ranks)} ranks but "
                         f"{len(endpoints)} endpoints")
    my_rank, my_endpoint = _pending_bind
    table = dict(zip(ranks, endpoints))
    table[my_rank] = my_endpoint
    if sorted(table) != list(range(len(table))):
        raise RuntimeError(f"net_connect needs a dense rank set, got "
                           f"{sorted(table)}")
    ordered = [table[r] for r in range(len(table))]
    _pending_net = TcpNet(my_rank, ordered)
    _pending_bind = None


def take_pending_net() -> Optional[TcpNet]:
    """Consume the transport prepared by net_bind/net_connect (called by
    Zoo.start)."""
    global _pending_net
    net, _pending_net = _pending_net, None
    return net
