"""In-process virtual cluster for tests and single-host multi-rank runs.

The reference tests distributed behavior by actually launching
``mpirun -np 4`` (ref: deploy/docker/Dockerfile:100-110) and has a
degenerate single-process mode where one rank is both worker and server
(ref: Test/unittests/multiverso_env.h:9-31). On TPU a single JAX process
already drives every local chip, so the natural multi-rank unit is a
*thread* per virtual rank over a shared ``LocalFabric`` — same actor stack,
same registration/barrier protocol, no MPI. Real multi-host deployments run
one Zoo per host over the DCN transport instead.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from . import device_lock
from . import thread_roles
from .net import LocalFabric
from .zoo import ClusterAborted, Zoo, set_thread_zoo


class LocalCluster:
    """Run ``fn(rank)`` on ``n`` virtual ranks, each with its own Zoo."""

    def __init__(self, n: int, argv: Optional[List[str]] = None,
                 roles: Optional[List[str]] = None,
                 nets: Optional[List[Any]] = None):
        """``roles`` optionally gives one -ps_role value per rank (the flag
        registry is process-global, so heterogeneous roles are passed here
        instead of via argv). ``nets`` optionally gives one pre-built
        ``NetInterface`` per rank — benches use this to run the same
        virtual cluster over real TCP/shm transports instead of the
        default in-process ``LocalFabric``."""
        self.n = n
        self.argv = list(argv or [])
        if roles is not None and len(roles) != n:
            raise ValueError("roles must have one entry per rank")
        if nets is not None and len(nets) != n:
            raise ValueError("nets must have one entry per rank")
        self.roles = roles
        self.nets = nets
        self.timeout = 120.0

    def run(self, fn: Callable[[int], Any]) -> List[Any]:
        if self.n > 1:
            # Several virtual ranks share this process's XLA CPU
            # runtime: serialize + settle every multi-device dispatch
            # for the duration (runtime/device_lock.py) — concurrent
            # sharded programs from sibling ranks can wedge the
            # execution pool on small hosts.
            device_lock.enable()
        try:
            return self._run(fn)
        finally:
            if self.n > 1:
                device_lock.disable()

    def _run(self, fn: Callable[[int], Any]) -> List[Any]:
        if self.nets is not None:
            endpoints = list(self.nets)
        else:
            fabric = LocalFabric(self.n)
            endpoints = [fabric.endpoint(r) for r in range(self.n)]
        results: List[Any] = [None] * self.n
        errors: List[Optional[BaseException]] = [None] * self.n
        zoos: List[Optional[Zoo]] = [None] * self.n

        def abort_all() -> None:
            for z in zoos:
                if z is not None:
                    z.abort()

        def rank_main(rank: int) -> None:
            zoo = Zoo()
            zoos[rank] = zoo
            set_thread_zoo(zoo)
            started = False
            try:
                zoo.start(list(self.argv), net=endpoints[rank],
                          role=self.roles[rank] if self.roles else None)
                started = True
                results[rank] = fn(rank)
            except BaseException as exc:  # noqa: BLE001 - reported to caller
                errors[rank] = exc
                # Unblock every sibling barrier/wait — a failed rank would
                # otherwise mispair barriers and hang the whole cluster.
                abort_all()
            finally:
                try:
                    if started:
                        zoo.stop()
                except BaseException as exc:  # noqa: BLE001
                    if errors[rank] is None:
                        errors[rank] = exc
                finally:
                    set_thread_zoo(None)

        threads = [thread_roles.spawn(thread_roles.BACKGROUND,
                                      target=rank_main, args=(r,),
                                      name=f"mv-rank-{r}")
                   for r in range(self.n)]
        hung = []
        for t in threads:
            t.join(timeout=self.timeout)
            if t.is_alive():
                hung.append(t.name)
        # Report a primary error over collateral ClusterAborted fallout.
        primary = [e for e in errors
                   if e is not None and not isinstance(e, ClusterAborted)]
        if primary:
            raise primary[0]
        for exc in errors:
            if exc is not None:
                raise exc
        if hung:
            abort_all()
            raise TimeoutError(f"virtual rank threads hung: {hung}")
        return results
