"""Epoch-stamped dynamic shard maps + live row migration (ISSUE 12).

Extension over the reference: Multiverso freezes the row→server layout
at table creation (``row_offsets`` in tables/matrix_table.py, ref:
matrix_table.cpp:23-45) — a production PS must absorb a new server or
drain a retiring one without a stop-the-world. This module supplies the
three coordinated pieces (full protocol spec in docs/SHARDING.md,
"Elastic resharding"):

* :class:`ShardMap` — an epoch-stamped interval map ``row →
  owner server id``. Epoch 0 reproduces the frozen ``row_offsets``
  layout bit-for-bit (so a never-resharded cluster routes exactly as
  before); every committed migration bumps the epoch and the rank-0
  controller broadcasts the whole map (``Control_Shard_Map``, the
  PR-7 ``Control_Replica_Map`` pattern — stale epochs are ignored by
  every consumer).
* :class:`MigrationOut` / :class:`MigrationIn` — the per-table source/
  destination state machines for one live range move: the source
  streams the range in seq-numbered chunks (the point-to-point
  schedule of the portable-collective redistribution formulation,
  arxiv 2112.01075) while still serving; rows an Add touches after
  their chunk left re-stream inside the FINAL chunk, whose send
  atomically flips the source into a dual-read/forwarding window
  (single actor thread — no lock needed). The destination detects
  chunk loss by seq gap at the final chunk and requests retransmits;
  only a complete range commits.
Concurrency note (mvlint pass 10): this module carries NO
``guarded_by`` annotations on purpose — the map and both migration
state machines are confined to their owning actor thread (map applies
on the worker/server actor, migrations run on the server actor,
planning on the controller actor), so the discipline here is
single-thread confinement, not locking.

* :class:`ReshardManager` — the controller-side coordinator: plans a
  minimal move list toward an even spread over the requested active
  servers (or, with ``-reshard_auto``, splits skewed ranges from the
  PR-7 ``HotTracker`` load reports), drives one move at a time,
  commits an epoch on the destination's ``Control_Shard_Done``, and
  rolls back (``Request_ShardAbort``) when either endpoint dies
  mid-handoff — the map never advances past a partial move, so every
  failure lands in a consistent epoch.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..util import log
from ..util.configure import define_bool, define_double, define_int, get_flag

define_int("reshard_chunk_rows", 4096,
           "rows per Request_ShardData chunk while a live migration "
           "streams a range between servers (smaller = finer "
           "interleaving with serving traffic, more per-chunk overhead)")
define_bool("reshard_auto", False,
            "closed-loop rebalancing: dense matrix servers report their "
            "HotTracker load windows to the controller even without "
            "replication, and the controller moves the hottest half of "
            "an overloaded server's hottest range to the coldest server "
            "whenever one server carries more than -reshard_skew times "
            "the mean load (docs/SHARDING.md)")
define_double("reshard_skew", 2.0,
              "load-skew trigger for -reshard_auto: a server whose "
              "decayed Get load exceeds this multiple of the mean "
              "across servers gets a range split off")
define_int("shard_initial_servers", 0,
           "create row/bucket-sharded tables over only the FIRST this "
           "many servers; the rest start as standbys that own no rows "
           "until a reshard migrates ranges onto them (the elastic "
           "grow story, docs/SHARDING.md). 0 (default) = all servers, "
           "the frozen reference layout")

def initial_active_servers(num_servers: int) -> int:
    """How many servers newly created elastic tables spread over
    (``-shard_initial_servers``, clamped; 0 = all)."""
    k = int(get_flag("shard_initial_servers", 0))
    if k <= 0:
        return num_servers
    return min(k, num_servers)


class ShardMap:
    """Interval map ``item id -> owner server id`` with an epoch stamp.

    ``bounds`` is a sorted int64 vector ``[0, b1, ..., num_items]``;
    ``owners[i]`` serves ``[bounds[i], bounds[i+1])``. Immutable —
    ``move`` returns a new map with the next epoch.
    """

    def __init__(self, bounds: np.ndarray, owners: np.ndarray,
                 epoch: int = 0):
        self.bounds = np.asarray(bounds, dtype=np.int64)
        self.owners = np.asarray(owners, dtype=np.int64)
        self.epoch = int(epoch)
        assert self.bounds.size == self.owners.size + 1

    @property
    def num_items(self) -> int:
        return int(self.bounds[-1])

    @classmethod
    def initial(cls, num_items: int, num_servers: int,
                active: Optional[int] = None) -> "ShardMap":
        """Epoch-0 map reproducing the frozen ``row_offsets`` layout
        over the first ``active`` servers (default: all) — a
        never-resharded cluster routes bit-identically to the
        reference's static split."""
        from ..tables.matrix_table import row_offsets
        n = int(num_servers) if active is None \
            else min(int(active), int(num_servers))
        offsets = row_offsets(int(num_items), max(n, 1))
        bounds = np.asarray(offsets, dtype=np.int64)
        owners = np.arange(bounds.size - 1, dtype=np.int64)
        return cls(bounds, owners, epoch=0)

    def owner_of(self, keys: np.ndarray) -> np.ndarray:
        """Vectorized item ids -> owner server ids."""
        keys = np.asarray(keys)
        idx = np.searchsorted(self.bounds, keys, side="right") - 1
        idx = np.clip(idx, 0, self.owners.size - 1)
        return self.owners[idx]

    def intervals_of(self, sid: int) -> List[Tuple[int, int]]:
        return [(int(self.bounds[i]), int(self.bounds[i + 1]))
                for i in range(self.owners.size)
                if int(self.owners[i]) == int(sid)]

    def owner_sids(self) -> List[int]:
        return sorted({int(s) for s in self.owners})

    def move(self, lo: int, hi: int, dst: int) -> "ShardMap":
        """New map (epoch+1) with ``[lo, hi)`` owned by ``dst``;
        adjacent same-owner intervals coalesce so the map stays small
        over many migrations."""
        lo, hi = int(lo), int(hi)
        assert 0 <= lo < hi <= self.num_items
        cuts = np.unique(np.concatenate(
            [self.bounds, np.asarray([lo, hi], dtype=np.int64)]))
        owners = self.owner_of(cuts[:-1]).copy()
        owners[(cuts[:-1] >= lo) & (cuts[:-1] < hi)] = int(dst)
        keep = np.concatenate(
            [[True], owners[1:] != owners[:-1]])
        bounds = np.concatenate([cuts[:-1][keep], cuts[-1:]])
        return ShardMap(bounds, owners[keep], epoch=self.epoch + 1)

    def diff_moved(self, newer: "ShardMap") -> List[Tuple[int, int, int, int]]:
        """Intervals whose owner changed between self and ``newer``:
        ``[(lo, hi, old_sid, new_sid), ...]`` (consumers invalidate
        caches / prune replicas for exactly these)."""
        cuts = np.unique(np.concatenate([self.bounds, newer.bounds]))
        old = self.owner_of(cuts[:-1])
        new = newer.owner_of(cuts[:-1])
        out: List[Tuple[int, int, int, int]] = []
        for i in range(cuts.size - 1):
            if old[i] != new[i]:
                lo, hi = int(cuts[i]), int(cuts[i + 1])
                if out and out[-1][1] == lo \
                        and out[-1][2] == int(old[i]) \
                        and out[-1][3] == int(new[i]):
                    out[-1] = (out[-1][0], hi, int(old[i]), int(new[i]))
                else:
                    out.append((lo, hi, int(old[i]), int(new[i])))
        return out

    # -- wire payload (Control_Shard_Map; docs/WIRE_FORMAT.md) --
    def pack(self, table_id: int, alive_sids: List[int]) -> List[np.ndarray]:
        """``[desc, bounds, owners, alive]`` int64 blobs; desc =
        [table_id, epoch, n_intervals, num_items, n_alive]. The alive
        vector is the controller's authoritative live-server view —
        workers reconcile their replica routers' dead marks against it
        on every broadcast (docs/SHARDING.md)."""
        alive = np.asarray(sorted(alive_sids), dtype=np.int64)
        desc = np.asarray([int(table_id), self.epoch, self.owners.size,
                           self.num_items, alive.size], dtype=np.int64)
        return [desc, self.bounds, self.owners, alive]

    @classmethod
    def unpack(cls, blobs) -> Tuple[int, "ShardMap", np.ndarray]:
        desc = np.asarray(blobs[0], dtype=np.int64)
        table_id, epoch = int(desc[0]), int(desc[1])
        bounds = np.asarray(blobs[1], dtype=np.int64)
        owners = np.asarray(blobs[2], dtype=np.int64)
        alive = np.asarray(blobs[3], dtype=np.int64) \
            if len(blobs) >= 4 else np.empty(0, np.int64)
        return table_id, cls(bounds, owners, epoch=epoch), alive


def plan_moves(current: ShardMap,
               active_sids: List[int]) -> List[Tuple[int, int, int, int]]:
    """Minimal move list ``[(lo, hi, src_sid, dst_sid)]`` carrying
    ``current`` to an even contiguous spread over ``active_sids`` (in
    sid order — the target layout is ``row_offsets`` over the active
    set, so growing back to the full fleet restores the frozen
    reference layout exactly)."""
    from ..tables.matrix_table import row_offsets
    sids = sorted({int(s) for s in active_sids})
    if not sids:
        return []
    offsets = row_offsets(current.num_items, len(sids))
    target = ShardMap(np.asarray(offsets, dtype=np.int64),
                      np.asarray([sids[i] for i in range(len(offsets) - 1)],
                                 dtype=np.int64))
    return [(lo, hi, src, dst)
            for lo, hi, src, dst in current.diff_moved(target)]


# ---------------------------------------------------------------------------
# migration state machines (server actor thread only — no locking)
# ---------------------------------------------------------------------------

class MigrationOut:
    """Source-side state for one outbound range move.

    The source keeps serving while chunks stream; Adds landing on rows
    whose chunk already left go into ``dirty`` and ride the FINAL
    chunk, so the handoff instant (final chunk composed and sent on
    the actor thread) hands the destination a value set that includes
    every Add the source ever applied to the range."""

    def __init__(self, table_id: int, lo: int, hi: int, src_sid: int,
                 dst_sid: int, dst_rank: int, epoch: int):
        self.table_id = int(table_id)
        self.lo, self.hi = int(lo), int(hi)
        self.src_sid, self.dst_sid = int(src_sid), int(dst_sid)
        self.dst_rank = int(dst_rank)
        self.epoch = int(epoch)
        chunk = max(int(get_flag("reshard_chunk_rows")), 1)
        #: seq -> (chunk_lo, chunk_hi); the final dirty-drain chunk is
        #: appended at handoff (row list, not a range).
        self.chunks: List[Tuple[int, int]] = [
            (c_lo, min(c_lo + chunk, self.hi))
            for c_lo in range(self.lo, self.hi, chunk)]
        self.next_seq = 0
        self.sent_hi = self.lo      # rows < sent_hi have left
        self.dirty: set = set()     # re-dirtied already-sent rows
        self.final_sent = False
        self.final_rows: Optional[np.ndarray] = None  # retransmit rows
        #: Set when the controller re-sends Begin AFTER the handoff —
        #: its view of the move is stalled (a lost Control_Shard_Done,
        #: with no destination traffic to ride the re-announce on):
        #: the next pump re-sends the FINAL chunk from the frozen
        #: snapshot, which re-triggers the destination's Done.
        self.resend_final = False
        #: Handoff-time value snapshot of the WHOLE range, captured in
        #: the same actor step that composes the final chunk:
        #: retransmits must re-send exactly what the destination's
        #: ledger expects — the source's live copy keeps moving after
        #: the handoff (forwarded Adds both-apply there), and a
        #: re-gather from it would double-apply every Add the
        #: destination already ledgered against the lost chunk. Keyed
        #: storage is table-specific; the table sets it at handoff and
        #: serves chunk values from it in ``shard_ack``.
        self.frozen = None

    @property
    def streaming(self) -> bool:
        return not self.final_sent

    def note_add(self, keys: np.ndarray) -> None:
        """Rows in the moving range that an Add touched after their
        chunk left must re-stream in the final chunk."""
        if self.final_sent:
            return
        sent = keys[(keys >= self.lo) & (keys < self.sent_hi)]
        if sent.size:
            self.dirty.update(int(k) for k in sent.tolist())

    def next_chunk(self) -> Optional[Tuple[int, np.ndarray, bool]]:
        """``(seq, rows, is_final)`` for the next chunk to send, or
        None when the final already left. The final chunk drains the
        dirty set — the caller flips into forwarding the moment it is
        handed out (same actor-thread step)."""
        if self.final_sent:
            return None
        if self.next_seq < len(self.chunks):
            c_lo, c_hi = self.chunks[self.next_seq]
            seq = self.next_seq
            self.next_seq += 1
            self.sent_hi = c_hi
            return seq, np.arange(c_lo, c_hi, dtype=np.int64), False
        rows = np.asarray(sorted(self.dirty), dtype=np.int64)
        self.dirty.clear()
        self.final_sent = True
        self.final_rows = rows
        return len(self.chunks), rows, True

    def rows_of_seq(self, seq: int) -> Optional[np.ndarray]:
        """Row set of a chunk, for retransmission (the source's values
        are frozen once the final left, so a regather is exact)."""
        if 0 <= seq < len(self.chunks):
            c_lo, c_hi = self.chunks[seq]
            return np.arange(c_lo, c_hi, dtype=np.int64)
        if seq == len(self.chunks) and self.final_rows is not None:
            return self.final_rows
        return None


class MigrationIn:
    """Destination-side state for one inbound range move: seq
    bookkeeping (loss detection by gap at the final chunk), and the
    pending-commit resend loop (the ``Control_Shard_Done`` toward the
    controller re-announces on traffic until the committed map
    broadcast confirms it landed — a chaos-dropped commit must not
    strand a completed migration)."""

    def __init__(self, epoch: int, src_sid: int, src_rank: int,
                 lo: int, hi: int):
        self.epoch = int(epoch)
        self.src_sid, self.src_rank = int(src_sid), int(src_rank)
        self.lo, self.hi = int(lo), int(hi)
        self.applied: set = set()
        self.n_chunks: Optional[int] = None  # known at the final chunk
        #: Items the FINAL chunk delivered: they carry the handoff-time
        #: values of every dirty row/bucket, which are NEWER than any
        #: base chunk's copy — a reorder-delayed base chunk arriving
        #: after the final must not overwrite them (seq dedup only
        #: protects exact retransmits, not this overlap).
        self.final_items: Optional[set] = None
        self.src_version = -1
        self.complete = False
        self.last_announce = 0.0

    def note_applied(self, seq: int) -> bool:
        """True when this seq is new (duplicates/retransmits of an
        already-applied chunk are dropped — a late copy must not
        overwrite forwarded Adds applied since)."""
        if seq in self.applied:
            return False
        self.applied.add(seq)
        return True

    def missing_seqs(self) -> List[int]:
        if self.n_chunks is None:
            return []
        return [s for s in range(self.n_chunks + 1)
                if s not in self.applied]

    def check_complete(self) -> bool:
        self.complete = (self.n_chunks is not None
                         and not self.missing_seqs())
        return self.complete


class ElasticServerMixin:
    """The table-type-independent half of the server-side migration
    protocol, shared by MatrixServer and KVServer (the item space —
    rows vs hash buckets — and the storage moves are table-specific;
    everything that is pure protocol lives here exactly once, so a
    protocol fix cannot drift between the two).

    Expects on self: ``_zoo``, ``table_id``, ``server_id``, ``_fwd``
    (list of ``(lo, hi, dst_sid, dst_rank)`` windows), ``_mig_out``,
    ``_mig_in`` and ``_fwd_inflight`` (initialized by the table), plus
    a ``_shard_data_message(mig, seq, items, is_final)`` builder."""

    def _fwd_route(self, items: np.ndarray):
        """Per-item dual-read window lookup: (mask, dst_sid, dst_rank)
        with -1 where an item is not inside any forwarding window."""
        mask = np.zeros(items.size, dtype=bool)
        dst_sid = np.full(items.size, -1, dtype=np.int64)
        dst_rank = np.full(items.size, -1, dtype=np.int64)
        for lo, hi, sid, rank in self._fwd:
            m = (items >= lo) & (items < hi)
            mask |= m
            dst_sid[m] = sid
            dst_rank[m] = rank
        return mask, dst_sid, dst_rank

    def _note_fwd_inflight(self, src_rank: int, msg_id: int,
                           is_get: bool) -> List:
        """Returns error replies for entries EVICTED past the cap: a
        silently dropped entry whose request is still waiting when the
        window's destination dies would hang forever (the ledger's
        whole reason to exist). A spurious error reply for a request
        the destination already answered is a no-op at the requester,
        so failing evictees retryably is always safe."""
        if msg_id < 0:
            return []
        self._fwd_inflight.append((int(src_rank), int(msg_id), is_get))
        if len(self._fwd_inflight) <= 4096:
            return []
        evicted = self._fwd_inflight[:2048]
        del self._fwd_inflight[:2048]
        return self._fail_fwd_entries(evicted)

    def _drain_fwd_inflight(self) -> List:
        """Retryable error replies for every request forwarded into a
        window that just rolled back: the destination died holding
        them, and the requester's in-flight accounting keys on THIS
        rank (the impersonation contract) — without these replies its
        waiters block forever. Replies for requests the destination
        already answered are no-ops at the requester (completed
        waiters ignore late notifies)."""
        drained, self._fwd_inflight = self._fwd_inflight, []
        return self._fail_fwd_entries(drained)

    def _fail_fwd_entries(self, entries) -> List:
        from ..core.message import (Message, MsgType, PEER_LOST_MARK,
                                    mark_error)
        out: List = []
        for src_rank, msg_id, is_get in entries:
            reply = Message(src=self._zoo.rank, dst=src_rank,
                            msg_type=MsgType.Reply_Get if is_get
                            else MsgType.Reply_Add,
                            table_id=self.table_id, msg_id=msg_id)
            mark_error(reply, RuntimeError(
                f"{PEER_LOST_MARK} forwarded into a migration window "
                f"that cannot confirm delivery — re-issue"))
            out.append(reply)
        return out

    def _announce_done(self, mig) -> List:
        import time
        from ..core.blob import Blob
        from ..core.message import Message, MsgType
        from .zoo import CONTROLLER_RANK
        mig.last_announce = time.monotonic()
        msg = Message(src=self._zoo.rank, dst=CONTROLLER_RANK,
                      msg_type=MsgType.Control_Shard_Done,
                      table_id=self.table_id)
        msg.push(Blob(np.asarray([mig.epoch, 1, self.server_id],
                                 dtype=np.int64)))
        return [msg]

    def _retransmit_request(self, mig) -> List:
        import time
        from ..core.blob import Blob
        from ..core.message import Message, MsgType
        mig.last_announce = time.monotonic()
        missing = mig.missing_seqs()
        log.error("rank %d: migration epoch %d missing chunk seq(s) "
                  "%s — requesting retransmit", self._zoo.rank,
                  mig.epoch, missing)
        msg = Message(src=self._zoo.rank, dst=mig.src_rank,
                      msg_type=MsgType.Request_ShardAck,
                      table_id=self.table_id)
        msg.push(Blob(np.asarray(
            [mig.epoch, self.server_id] + missing, dtype=np.int64)))
        return [msg]

    def shard_announce(self) -> List:
        """Traffic-driven resend of a pending commit / retransmit
        request (a chaos-dropped Control_Shard_Done must not strand a
        completed migration; docs/SHARDING.md)."""
        import time
        out: List = []
        now = time.monotonic()
        for mig in self._mig_in.values():
            if now - mig.last_announce < 1.0:
                continue
            if mig.complete:
                out.extend(self._announce_done(mig))
            elif mig.n_chunks is not None:
                out.extend(self._retransmit_request(mig))
        return out

    def shard_ack(self, msg) -> List:
        """Retransmit from the HANDOFF-TIME frozen snapshot, never the
        live copy: forwarded Adds keep both-applying to the source
        after the handoff, and a live re-gather would double-apply
        every Add the destination ledgered against the lost chunk."""
        desc = msg.data[0].as_array(np.int64)
        mig = self._mig_out
        if mig is None or mig.epoch != int(desc[0]):
            return []
        out: List = []
        for seq in (int(x) for x in desc[2:]):
            items = mig.rows_of_seq(seq)
            if items is not None:
                from ..util.dashboard import count as _count
                _count("SHARD_RETRANSMIT")
                out.append(self._shard_data_message(
                    mig, seq, items, seq == len(mig.chunks)))
        return out

    def _freeze_range(self, mig):
        """Handoff-time value snapshot of the whole range (table-
        specific storage gather)."""
        raise NotImplementedError

    def shard_pump(self):
        """One streaming step: ``(outbound messages, more)``. The
        server actor re-enqueues a pump message while ``more`` so
        serving traffic interleaves between chunks. After the handoff,
        a pump only fires to re-send the final chunk when the
        controller's Begin-resend flagged the move as stalled."""
        from ..util import chaos
        mig = self._mig_out
        if mig is None:
            return [], False
        if mig.final_sent:
            if mig.resend_final:
                mig.resend_final = False
                items = mig.rows_of_seq(len(mig.chunks))
                if items is not None:
                    return [self._shard_data_message(
                        mig, len(mig.chunks), items, True)], False
            return [], False
        seq, items, is_final = mig.next_chunk()
        if is_final:
            chaos.kill_point("shard_source_final")
        else:
            chaos.kill_point("shard_source_chunk")
        if is_final:
            # Snapshot BEFORE the final chunk is built (same actor
            # step — nothing interleaves): retransmits and stalled-
            # commit re-sends serve from it, never the live copy.
            frozen = self._freeze_range(mig)
        msg = self._shard_data_message(mig, seq, items, is_final)
        if is_final:
            # HANDOFF, atomically with composing the final chunk: from
            # the next message on, Adds for the range both-apply and
            # forward, Gets forward — per-destination FIFO orders
            # everything after the final chunk at the destination.
            mig.frozen = frozen
            self._fwd.append((mig.lo, mig.hi, mig.dst_sid,
                              mig.dst_rank))
        return [msg], not is_final

    def _prune_fwd_windows(self, lo: int, hi: int) -> None:
        """Items in [lo, hi) came (back) to this shard: clip every
        forwarding window out of the range (partial overlaps split)."""
        pruned: List = []
        for flo, fhi, fsid, frank in self._fwd:
            if fhi <= lo or flo >= hi:
                pruned.append((flo, fhi, fsid, frank))
                continue
            if flo < lo:
                pruned.append((flo, lo, fsid, frank))
            if fhi > hi:
                pruned.append((hi, fhi, fsid, frank))
        self._fwd = pruned


# ---------------------------------------------------------------------------
# controller-side coordinator (controller actor thread only)
# ---------------------------------------------------------------------------

class PendingMove:
    def __init__(self, table_id: int, lo: int, hi: int, src_sid: int,
                 dst_sid: int, epoch: int):
        self.table_id = int(table_id)
        self.lo, self.hi = int(lo), int(hi)
        self.src_sid, self.dst_sid = int(src_sid), int(dst_sid)
        self.epoch = int(epoch)


class ReshardManager:
    """Controller-side elastic-resharding coordinator.

    Owns the authoritative per-table :class:`ShardMap`, a queue of
    planned moves, and at most ONE in-flight move cluster-wide (the
    dual-read window and the rollback story are per-move; serializing
    keeps every failure mode a single-migration failure). All entry
    points run on the controller ACTOR thread — the heartbeat monitor
    nudges via a local ``Control_Shard_Tick`` message, never directly
    (the ``Control_Check_Barriers`` precedent)."""

    def __init__(self, zoo):
        self._zoo = zoo
        self.maps: Dict[int, ShardMap] = {}
        self._queue: List[Tuple[int, int, int, int, int]] = []
        self._pending: Optional[PendingMove] = None
        #: decayed per-(table, sid) load + hottest row per table
        #: (-reshard_auto; fed from Control_Replica_Report windows).
        self._loads: Dict[int, Dict[int, float]] = {}
        self._hot_rows: Dict[int, Dict[int, int]] = {}
        self._report_rounds: Dict[int, int] = {}
        self._num_items: Dict[int, int] = {}
        self._last_begin = 0.0
        self._last_broadcast = 0.0

    # -- planning --
    def request(self, table_id: int, num_items: int,
                active_sids: List[int]) -> None:
        """An application asked for this table spread over
        ``active_sids`` (``Zoo.reshard_table``): plan the move list
        from the current map and start draining it."""
        if get_flag("sync", False):
            log.error("controller: reshard of table %d refused — BSP "
                      "sync mode pins the frozen shard map (the sync "
                      "server's vector clocks count requests per "
                      "server)", table_id)
            return
        current = self.maps.get(int(table_id))
        if current is None:
            current = ShardMap.initial(
                int(num_items), self._zoo.num_servers,
                active=initial_active_servers(self._zoo.num_servers))
            self.maps[int(table_id)] = current
        self._num_items[int(table_id)] = current.num_items
        # Plan from the PROJECTED map — the committed state plus every
        # move still queued or in flight for this table: a second
        # request arriving mid-plan must extend the schedule, not fight
        # it (stale-source moves would be refused and roll the whole
        # plan back).
        projected = current
        for t, lo, hi, src, dst in self._queue:
            if t == int(table_id):
                projected = projected.move(lo, hi, dst)
        p = self._pending
        if p is not None and p.table_id == int(table_id):
            projected = projected.move(p.lo, p.hi, p.dst_sid)
        n = 0
        for lo, hi, src, dst in plan_moves(projected, active_sids):
            self._queue.append((int(table_id), lo, hi, src, dst))
            n += 1
        log.info("controller: reshard table %d over %s: %d move(s) "
                 "queued", table_id, sorted(active_sids), n)
        self.kick()

    def note_report(self, table_id: int, src_sid: int,
                    rows: np.ndarray, counts: np.ndarray,
                    num_items: int = -1) -> None:
        """A server's HotTracker window (-reshard_auto): decayed
        per-server load; a skew past -reshard_skew plans a split of
        the overloaded server's hottest range toward the coldest
        server."""
        if not bool(get_flag("reshard_auto")) or get_flag("sync", False):
            return
        table_id, src_sid = int(table_id), int(src_sid)
        if num_items > 0:
            self._num_items.setdefault(table_id, int(num_items))
        loads = self._loads.setdefault(table_id, {})
        loads[src_sid] = loads.get(src_sid, 0.0) / 2.0 \
            + float(counts.sum())
        if rows.size:
            hot = self._hot_rows.setdefault(table_id, {})
            hot[src_sid] = int(rows[int(np.argmax(counts))])
        self._report_rounds[table_id] = \
            self._report_rounds.get(table_id, 0) + 1
        self._maybe_split(table_id)

    def _maybe_split(self, table_id: int) -> None:
        if self._pending is not None or self._queue:
            return
        if self._report_rounds.get(table_id, 0) < 3:
            # One early window must not trigger a migration: silent
            # servers read as zero load by design (standbys ARE
            # zero-load), so wait until a few windows establish the
            # shape before acting.
            return
        loads = self._loads.get(table_id, {})
        if len(loads) < 2:
            # One reporter so far: compare against the full fleet (a
            # silent server carries zero load by definition).
            for sid in range(self._zoo.num_servers):
                loads.setdefault(sid, 0.0)
            if len(loads) < 2:
                return
        mean = sum(loads.values()) / len(loads)
        hot_sid = max(loads, key=loads.get)
        if mean <= 0 or loads[hot_sid] < float(
                get_flag("reshard_skew")) * mean:
            return
        num_items = self._num_items.get(table_id)
        if num_items is None:
            return
        current = self.maps.get(table_id)
        if current is None:
            current = self.maps[table_id] = ShardMap.initial(
                num_items, self._zoo.num_servers,
                active=initial_active_servers(self._zoo.num_servers))
        intervals = current.intervals_of(hot_sid)
        if not intervals:
            return
        hot_row = self._hot_rows.get(table_id, {}).get(hot_sid)
        # The interval holding the hottest row (fallback: the widest).
        pick = max(intervals, key=lambda iv: iv[1] - iv[0])
        if hot_row is not None:
            for lo, hi in intervals:
                if lo <= hot_row < hi:
                    pick = (lo, hi)
                    break
        lo, hi = pick
        if hi - lo < 2:
            return
        cold_sid = min(loads, key=loads.get)
        if cold_sid == hot_sid:
            return
        mid = (lo + hi) // 2
        # Keep the half holding the hottest row AT the (tracked) hot
        # server and move the other half: ownership moves the load the
        # reports cannot attribute, the hot head stays put.
        move = (mid, hi) if (hot_row is None or hot_row < mid) \
            else (lo, mid)
        log.info("controller: auto-reshard table %d — server %d load "
                 "%.0f > %.1fx mean %.0f, moving [%d,%d) to server %d",
                 table_id, hot_sid, loads[hot_sid],
                 float(get_flag("reshard_skew")), mean,
                 move[0], move[1], cold_sid)
        self._queue.append((table_id, move[0], move[1], hot_sid,
                            cold_sid))
        self.kick()

    # -- drive --
    def kick(self) -> None:
        """Start the next queued move if none is in flight."""
        if self._pending is not None or not self._queue:
            return
        table_id, lo, hi, src, dst = self._queue.pop(0)
        current = self.maps[table_id]
        self._pending = PendingMove(table_id, lo, hi, src, dst,
                                    current.epoch + 1)
        self._send_begin()

    def _send_begin(self) -> None:
        import time
        from ..core.blob import Blob
        from ..core.message import Message, MsgType
        from . import actor as actors
        p = self._pending
        src_rank = self._zoo.server_rank(p.src_sid)
        dst_rank = self._zoo.server_rank(p.dst_sid)
        if src_rank < 0 or dst_rank < 0:
            log.error("controller: reshard move for table %d names "
                      "unknown server ids (%d -> %d) — abandoned",
                      p.table_id, p.src_sid, p.dst_sid)
            self._abandon("unknown server id")
            return
        msg = Message(src=self._zoo.rank, dst=src_rank,
                      msg_type=MsgType.Request_ShardBegin,
                      table_id=p.table_id)
        msg.push(Blob(np.asarray(
            [p.lo, p.hi, p.src_sid, p.dst_sid, dst_rank, p.epoch,
             self.maps[p.table_id].num_items], dtype=np.int64)))
        self._last_begin = time.monotonic()
        self._zoo.send_to(actors.COMMUNICATOR, msg)

    def on_done(self, table_id: int, epoch: int, ok: bool) -> None:
        """The destination committed (ok) or either endpoint refused
        (not ok): advance the map + broadcast, or roll the whole plan
        back to the current (consistent) epoch."""
        p = self._pending
        if p is None or p.table_id != int(table_id) \
                or p.epoch != int(epoch):
            return  # stale/duplicate Done (the dest re-announces)
        if not ok:
            log.error("controller: migration of table %d [%d,%d) -> "
                      "server %d refused/failed — rolled back at epoch "
                      "%d", p.table_id, p.lo, p.hi, p.dst_sid,
                      self.maps[p.table_id].epoch)
            self._abandon("endpoint refused")
            return
        self.maps[p.table_id] = self.maps[p.table_id].move(
            p.lo, p.hi, p.dst_sid)
        log.info("controller: table %d shard map epoch %d — [%d,%d) "
                 "now on server %d", p.table_id,
                 self.maps[p.table_id].epoch, p.lo, p.hi, p.dst_sid)
        self._pending = None
        self.broadcast(p.table_id)
        self.kick()

    def _abandon(self, reason: str) -> None:
        p, self._pending = self._pending, None
        if p is not None:
            self._queue = [m for m in self._queue if m[0] != p.table_id]

    def on_peer_dead(self, rank: int) -> None:
        """A rank was declared dead. If the in-flight move touches it,
        the move rolls back: the survivor gets a Request_ShardAbort
        (the source resumes ownership / the destination drops partial
        state) and the map stays at the pre-move epoch."""
        p = self._pending
        if p is None:
            return
        dead_sid = self._zoo.rank_to_server_id(rank)
        if dead_sid not in (p.src_sid, p.dst_sid):
            return
        survivor_sid = p.dst_sid if dead_sid == p.src_sid else p.src_sid
        log.error("controller: server %d died mid-migration of table "
                  "%d [%d,%d) — rolling back to epoch %d, aborting at "
                  "server %d", dead_sid, p.table_id, p.lo, p.hi,
                  self.maps[p.table_id].epoch, survivor_sid)
        self._send_abort(p, survivor_sid)
        self._abandon("endpoint died")
        # Re-broadcast the (unchanged) map: every rank re-anchors on
        # the pre-move epoch — the 'rolled back' consistent state.
        self.broadcast(p.table_id)

    def _send_abort(self, p: PendingMove, sid: int) -> None:
        from ..core.blob import Blob
        from ..core.message import Message, MsgType
        from . import actor as actors
        rank = self._zoo.server_rank(sid)
        if rank < 0:
            return
        msg = Message(src=self._zoo.rank, dst=rank,
                      msg_type=MsgType.Request_ShardAbort,
                      table_id=p.table_id)
        msg.push(Blob(np.asarray([p.epoch], dtype=np.int64)))
        self._zoo.send_to(actors.COMMUNICATOR, msg)

    def tick(self) -> None:
        """Heartbeat-driven nudge (controller actor thread): re-send a
        possibly-lost Begin, and re-broadcast current maps so workers
        partitioned away from a commit converge (broadcasts are
        idempotent — stale epochs are ignored; throttled so a chatty
        tick never floods the cluster)."""
        import time
        if self._pending is not None \
                and time.monotonic() - self._last_begin > max(
                    float(get_flag("heartbeat_interval_s", 0.0)), 1.0):
            self._send_begin()  # idempotent at the source
        if time.monotonic() - self._last_broadcast >= 2.0:
            for table_id in list(self.maps):
                self.broadcast(table_id)

    def broadcast(self, table_id: int) -> None:
        """Fan the table's current map to every live rank (the
        Control_Replica_Map pattern: cloned to worker AND server actors
        by the communicator's routing; stale epochs ignored).

        Remote copies ride ``net.send_async`` — the PR-6 liveness-frame
        lesson, now lint-enforced: a BLOCKING send toward a dead or
        restarting rank parks the sender up to ``-connect_timeout_s``,
        and broadcasts from the controller actor would wedge every
        later control message behind it. Declared-dead ranks are
        skipped outright (their rejoin re-register gets a fresh
        broadcast); the local rank delivers through the communicator's
        forward path (a mailbox push, never blocks)."""
        import time
        from ..core.blob import Blob
        from ..core.message import Message, MsgType
        from . import actor as actors
        smap = self.maps.get(int(table_id))
        if smap is None:
            return
        self._last_broadcast = time.monotonic()
        alive = self.alive_sids()
        dead_ranks = self._dead_ranks()
        blobs = smap.pack(table_id, alive)
        for dst in range(self._zoo.net_size):
            if dst in dead_ranks:
                continue
            msg = Message(src=self._zoo.rank, dst=dst,
                          msg_type=MsgType.Control_Shard_Map,
                          table_id=int(table_id))
            for arr in blobs:
                msg.push(Blob(arr.copy()))
            if dst == self._zoo.rank:
                self._zoo.send_to(actors.COMMUNICATOR, msg)
                continue
            try:
                self._zoo.net.send_async(msg)
            except Exception as exc:  # noqa: BLE001 - an unreachable
                # rank re-anchors from the next broadcast or its
                # rejoin; its failure must not kill the controller.
                log.debug("controller: shard-map broadcast to rank %d "
                          "failed: %s", dst, exc)

    def broadcast_all(self) -> None:
        for table_id in list(self.maps):
            self.broadcast(table_id)

    def _dead_ranks(self) -> set:
        from . import actor as actors
        controller = self._zoo._actors.get(actors.CONTROLLER)
        if controller is None:
            return set()
        with controller._live_lock:
            return set(controller._declared_dead)

    def alive_sids(self) -> List[int]:
        """Server ids the controller currently believes alive — the
        authoritative liveness view the broadcast carries so replica
        routers re-validate their dead marks (docs/SHARDING.md)."""
        from . import actor as actors
        controller = self._zoo._actors.get(actors.CONTROLLER)
        dead_ranks: set = set()
        if controller is not None:
            with controller._live_lock:
                dead_ranks = set(controller._declared_dead)
        return [s for s in range(self._zoo.num_servers)
                if self._zoo.server_rank(s) not in dead_ranks]
