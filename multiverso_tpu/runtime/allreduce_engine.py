"""Hand-rolled collectives over point-to-point transport (control plane).

Functional equivalent of the reference's ``AllreduceEngine``
(ref: include/multiverso/net/allreduce_engine.h:80-168,
src/net/allreduce_engine.cpp:31-172): a Bruck-style allgather and a
recursive-halving reduce-scatter composed into an allreduce, with the same
size-based algorithm choice (small payloads take the allgather path,
ref: allreduce_engine.cpp:31-54).

On TPU this engine is the *fallback* path: the data plane rides XLA
collectives over ICI (``multiverso_tpu.parallel``); this host-side engine
exists for model-average mode over the control transport where no device
mesh spans the ranks (the reference's ``-ma`` mode bypasses the PS the same
way, ref: src/zoo.cpp:49). It drives the raw endpoint directly, so it must
only run when the PS actors are down (ma mode) — exactly the reference's
usage pattern.

The algorithms are implemented from their standard formulations (Bruck
doubling allgather; recursive halving with an initial fold of surplus ranks
onto a power-of-two group), not transcribed from the reference.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ..core.blob import Blob
from ..core.message import Message, MsgType, is_wire_encoded
from ..util.configure import get_flag
from ..util.wire_codec import (CODEC_SLOT, decode_blob, encode_blob,
                               worth_encoding)
from .net import NetInterface

_SMALL_BYTES = 4096  # allgather-based path threshold (ref: engine.cpp:33)

#: Segment payloads at least this large run through the wire codec on
#: non-in-process transports (lossless tiers; sparse model-average
#: deltas shrink, dense ones ride RAW with only the header overhead).
_CODEC_MIN_BYTES = 4096


class AllreduceEngine:
    def __init__(self, net: NetInterface):
        self._net = net
        self.rank = net.rank
        self.size = net.size
        self._stash = {}  # (src, tag) -> blob, for early-arriving rounds
        # Frames are self-describing (CODEC_SLOT marks an encoded
        # payload), so decode needs no negotiation; in ma mode every
        # rank runs this same engine. In-process transports move object
        # references — encoding there only burns CPU.
        self._codec = (not net.in_process
                       and bool(get_flag("wire_codec")))

    # -- raw paired exchange over the message transport --
    def _send(self, dst: int, payload: np.ndarray, tag: int) -> None:
        msg = Message(src=self.rank, dst=dst, msg_type=MsgType.Default,
                      msg_id=tag)
        payload = np.ascontiguousarray(payload)
        # worth_encoding gates on density too: dense model-average
        # segments (the common ma workload) skip the frame-copy round
        # trip a RAW frame would cost.
        if self._codec and payload.nbytes >= _CODEC_MIN_BYTES \
                and worth_encoding(payload):
            frame, _ = encode_blob(payload)  # lossless tiers only
            msg.push(Blob(np.frombuffer(frame, np.uint8)))
            msg.header[CODEC_SLOT] = 1
        else:
            msg.push(Blob(payload))
        self._net.send(msg)

    def _recv(self, src: int, tag: int, dtype) -> np.ndarray:
        """Tag-matched receive: a fast peer's next-round message may arrive
        before the one this round is waiting on; stash and keep draining."""
        key = (src, tag)
        while key not in self._stash:
            msg = self._net.recv(timeout=120)
            if msg is None:
                raise RuntimeError("allreduce engine: transport closed")
            blob = msg.data[0]
            if is_wire_encoded(msg):
                blob = Blob(decode_blob(np.asarray(blob.data)))
            self._stash[(msg.src, msg.msg_id)] = blob
        return self._stash.pop(key).as_array(dtype)

    def _exchange(self, peer: int, payload: np.ndarray,
                  tag: int) -> np.ndarray:
        """Blocking sendrecv with one peer (ref: mpi_net.h:269-287)."""
        self._send(peer, payload, tag)
        return self._recv(peer, tag, payload.dtype)

    # -- public API (ref: allreduce_engine.h:96-118) --
    def allreduce(self, data: np.ndarray,
                  reducer: Callable = np.add) -> np.ndarray:
        data = np.asarray(data)
        if self.size == 1:
            return data.copy()
        if data.nbytes < _SMALL_BYTES or data.size < self.size:
            # Small path: allgather everyone's buffer, reduce locally
            # (ref: allreduce_engine.cpp:34-43).
            stacked = self.allgather(data)
            out = stacked[0]
            for part in stacked[1:]:
                out = reducer(out, part)
            return out
        return self._reduce_scatter_allgather(data, reducer)

    def allgather(self, data: np.ndarray) -> list:
        """Bruck doubling allgather: after round k every rank holds 2^(k+1)
        blocks; blocks are sent to rank-2^k and received from rank+2^k
        (ref: allreduce_engine.cpp:90-117, allreduce_topo.cpp:20-37)."""
        n = self.size
        blocks = [np.asarray(data)]
        tag = 1000
        distance = 1
        while distance < n:
            dst = (self.rank - distance) % n
            src = (self.rank + distance) % n
            count = min(distance, n - distance)
            payload = np.concatenate(
                [b.reshape(-1) for b in blocks[:count]])
            self._send(dst, payload, tag)
            incoming = self._recv(src, tag,
                                  blocks[0].dtype).reshape(count, -1)
            for i in range(count):
                blocks.append(incoming[i].reshape(blocks[0].shape))
            distance *= 2
            tag += 1
        # blocks[j] is the buffer of rank (self.rank + j) % n; rotate to
        # rank order.
        ordered = [None] * n
        for j, block in enumerate(blocks[:n]):
            ordered[(self.rank + j) % n] = block
        return ordered

    def _reduce_scatter_allgather(self, data: np.ndarray,
                                  reducer: Callable) -> np.ndarray:
        """Large path: recursive-halving reduce-scatter then allgather of
        the reduced segments (ref: allreduce_engine.cpp:44-54,120-172)."""
        n = self.size
        flat = np.asarray(data).reshape(-1).copy()
        # Fold surplus ranks onto the largest power-of-two group (the
        # reference pairs each surplus rank with a group leader,
        # ref: allreduce_topo.cpp:58-168).
        pow2 = 1
        while pow2 * 2 <= n:
            pow2 *= 2
        surplus = n - pow2
        tag = 2000
        if self.rank >= pow2:
            # Surplus rank: hand the whole buffer to its leader, then wait
            # for the final result.
            leader = self.rank - pow2
            self._send(leader, flat, tag)
            result = self._recv(leader, tag + 900, flat.dtype)
            return result.reshape(np.asarray(data).shape)
        if self.rank < surplus:
            incoming = self._recv(self.rank + pow2, tag, flat.dtype)
            flat = reducer(flat, incoming)

        # Recursive halving among the pow2 group: segment boundaries are
        # even splits of the flat buffer.
        bounds = np.linspace(0, flat.size, pow2 + 1).astype(np.int64)
        lo, hi = 0, pow2
        step_tag = tag + 1
        while hi - lo > 1:
            mid = (lo + hi) // 2
            half = (hi - lo) // 2
            in_low = self.rank < mid
            peer = self.rank + half if in_low else self.rank - half
            keep = (lo, mid) if in_low else (mid, hi)
            give = (mid, hi) if in_low else (lo, mid)
            give_seg = flat[bounds[give[0]]:bounds[give[1]]]
            recv_seg = self._exchange(peer, give_seg, step_tag)
            seg = slice(bounds[keep[0]], bounds[keep[1]])
            flat[seg] = reducer(flat[seg], recv_seg)
            lo, hi = keep
            step_tag += 1

        # Allgather the reduced segments back (ring of exchanges via the
        # Bruck machinery on the segment level).
        my_seg = flat[bounds[self.rank]:bounds[self.rank + 1]]
        gathered = self._gather_segments(my_seg, bounds, flat.dtype,
                                         step_tag)
        flat = np.concatenate(gathered)
        if self.rank < surplus:
            self._send(self.rank + pow2, flat, tag + 900)
        return flat.reshape(np.asarray(data).shape)

    def _gather_segments(self, my_seg, bounds, dtype, tag) -> list:
        """Bruck doubling allgather of the (unequal) reduced segments.
        Ownership after round r is deterministic — rank holds segments
        {rank+j mod p : j < 2^r} — so no ids ride the wire."""
        pow2 = len(bounds) - 1
        have = {self.rank: np.asarray(my_seg)}
        distance = 1
        while distance < pow2:
            dst = (self.rank - distance) % pow2
            src = (self.rank + distance) % pow2
            count = min(distance, pow2 - distance)
            send_ids = [(self.rank + j) % pow2 for j in range(count)]
            self._send(dst, np.concatenate([have[i] for i in send_ids]), tag)
            raw = self._recv(src, tag, dtype)
            offset = 0
            for j in range(count):
                seg_id = (src + j) % pow2
                seg_len = int(bounds[seg_id + 1] - bounds[seg_id])
                have[seg_id] = raw[offset:offset + seg_len]
                offset += seg_len
            distance *= 2
            tag += 1
        return [have[i] for i in range(pow2)]
