"""Hand-rolled collectives over point-to-point transport (control plane).

Functional equivalent of the reference's ``AllreduceEngine``
(ref: include/multiverso/net/allreduce_engine.h:80-168,
src/net/allreduce_engine.cpp:31-172), grown into a chunked, pipelined
collective stack:

- **small path**: Bruck-style doubling allgather + local reduce, same
  size threshold as the reference (ref: allreduce_engine.cpp:31-54);
- **recursive halving**: the reference's reduce-scatter + allgather with
  an initial fold of surplus ranks onto a power-of-two group — the
  *monolithic* path (one blocking sendrecv per round);
- **chunked ring** (new): ring reduce-scatter + ring allgather over
  ``-allreduce_chunk_kb`` chunks with a sliding window of in-flight
  frames riding the transport's ``send_async`` writer threads, so round
  k's wire time overlaps round k+1's receive + reduce (SparCML-style
  chunking). Works for ANY rank count (no surplus fold), which is why
  non-power-of-two worlds prefer it even at modest sizes;
- **sparse stream** (new): for sparse float32 sums (model-average
  deltas are power-law sparse — SparCML, arxiv 1802.08021 / 1312.3020)
  a direct reduce-scatter of codec sparse index+value frames — every
  rank ships only its own nonzeros straight to each segment's owner,
  so hop-by-hop fill-in never rides the wire — followed by a
  single-encode ring allgather of the reduced segments. The owner
  merges inbound index streams in rank order (union of indices, sum of
  values, fill-in tracked per hop into ``SPARSE_FILL[*]``), which
  reproduces the unchunked dense ring's fold association exactly:
  lossless sparse results are bit-identical to the dense ring's.
  ``choose_algo`` picks it from a cluster-agreed nnz probe and falls
  back to the dense ring once the union density crosses the break-even
  (``-allreduce_sparse_*``); ``sharded_average`` adds the cross-replica
  sharded model-average step (arxiv 2004.13336): reduce-scatter,
  shard-local divide, allgather — per-rank reduce-state is one segment
  instead of the full buffer.

Per-chunk segments >= 4 KB ride the wire codec; the opt-in
``-allreduce_lossy`` tier quantizes segment values (int8 / f16 via
``util/wire_codec``) *inside* the collective with per-destination
error-feedback residuals carried across calls (EQuARX-style), so
quantization noise averages out over training steps instead of
accumulating. In the allgather phase each reduced segment is encoded
ONCE at its owner and the encoded frame is forwarded verbatim around the
ring — no re-quantization per hop, and every rank (owner included)
decodes the same bytes, so lossy results are still bit-identical across
ranks.

Every message's ``msg_id`` carries a per-call generation in its high
bits: back-to-back collectives with different round counts (or a future
concurrent caller) can never cross-match stash entries.

On TPU this engine is the *fallback* path: the data plane rides XLA
collectives over ICI (``multiverso_tpu.parallel``); this host-side engine
exists for model-average mode over the control transport where no device
mesh spans the ranks (the reference's ``-ma`` mode bypasses the PS the
same way, ref: src/zoo.cpp:49). It drives the raw endpoint directly, so
it must only run when the PS actors are down (ma mode) — exactly the
reference's usage pattern. See docs/ALLREDUCE.md for the algorithm
choice table and flag semantics.
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from ..core.blob import Blob
from ..core.message import Message, MsgType, is_wire_encoded
from ..util.configure import (define_bool, define_double, define_int,
                              define_string, get_flag,
                              register_tunable_hook)
from ..util.dashboard import samples
from ..util.wire_codec import (CODEC_SLOT, break_even_density, decode_blob,
                               decode_blob_sparse, density_of,
                               encode_blob_views, worth_encoding)
from .net import NetInterface

define_string("allreduce_algo", "auto",
              "large-payload allreduce algorithm: auto (pick by payload "
              "size and rank count) | ring (chunked pipelined ring) | "
              "rhalving (monolithic recursive halving)")
define_int("allreduce_chunk_kb", 512,
           "ring path: split the flat buffer into chunks of this many "
           "KB; each chunk is an independent ring whose frames pipeline "
           "on the transport writer threads. Smaller chunks overlap "
           "more but pay more per-frame overhead (~0.3-1.5 ms each on "
           "a single-core host); 512 is the measured sweet spot for "
           "4-16 MB buffers on the bench wire")
define_int("allreduce_window", 4,
           "ring path: max in-flight (sent but not yet matched by a "
           "receive) chunks per ring step")
define_int("allreduce_ring_kb", 256,
           "auto algorithm choice: payloads at least this many KB take "
           "the chunked ring path (non-power-of-two worlds switch "
           "earlier — the recursive-halving surplus fold costs two "
           "extra full-buffer serial hops)")
define_double("allreduce_timeout_s", 120.0,
              "seconds a collective waits for one peer frame before "
              "failing loudly (tests lower this to fail fast)")
define_int("allreduce_stash_cap", 4096,
           "max early-arriving frames stashed while waiting for a "
           "specific (src, tag); exceeding it means a crashed peer or a "
           "tag-protocol bug and fails loudly instead of growing "
           "unboundedly")

# Lossy tier flag lives here (the codec's -wire_codec_lossy governs the
# PS matrix-Add filter stage; the collective gets its own opt-in).
define_bool("allreduce_lossy", False,
            "quantize allreduce segment values (int8/f16 wire-codec "
            "tiers) inside the collective, with per-destination "
            "error-feedback residuals carried across calls "
            "(EQuARX-style). Lossless when off — bit-identical to the "
            "unquantized path")
define_double("allreduce_sparse_density", 0.25,
              "auto algorithm choice: float32 sum-allreduces whose "
              "cluster-agreed union density (sum of per-rank nnz / "
              "element count, the nnz-probe upper bound on reduced "
              "fill-in) sits at or below this take the sparse-stream "
              "path; the effective cutoff is additionally clamped to "
              "the codec break-even (-wire_codec_density) — past that "
              "the reduced segments would ride RAW frames and the "
              "index merge buys nothing")
define_int("allreduce_sparse_idx_budget", 8388608,
           "auto algorithm choice: cap on the union index count "
           "(density x elements) the sparse path will carry per "
           "collective — past it the per-index Python merge cost beats "
           "the dense ring's streaming chunks even at low density")


def _chunk_kb_retuned(value) -> None:
    """``-allreduce_chunk_kb`` is read fresh per collective call
    (``_chunk_elems``), so a live retune needs no state rebind — this
    hook declares the handoff (the ``TUNABLE_FLAGS`` contract: every
    tunable names HOW its value lands) and logs the step so the knob
    trajectory is traceable in rank logs, not just controller
    gauges."""
    from ..util import log
    log.info("allreduce: -allreduce_chunk_kb retuned to %s (applies "
             "from the next collective call)", value)


register_tunable_hook("allreduce_chunk_kb", _chunk_kb_retuned)

_SMALL_BYTES = 4096  # allgather-based path threshold (ref: engine.cpp:33)

#: Segment payloads at least this large run through the wire codec on
#: non-in-process transports (lossless tiers; sparse model-average
#: deltas shrink, dense ones ride RAW with only the header overhead).
_CODEC_MIN_BYTES = 4096

# -- msg_id layout: [ 11-bit generation | 20-bit tag ] ----------------
# The generation increments once per public collective call (all ranks
# call collectives in the same order, so engine counters stay in sync);
# a stale frame from call g can never match a key from call g+1 even
# when the low tag bits collide. Tag bases partition the 20-bit space:
_TAG_BITS = 20
_GEN_MOD = 2047  # 11 bits, cycling 1..2047 (msg_id stays positive i32)
_BRUCK_BASE = 1000       # doubling allgather rounds
_RH_BASE = 2000          # recursive-halving rounds
_RH_RESULT = 2900        # surplus-rank final result
_RING_RS_BASE = 100000   # ring reduce-scatter: base + step*nchunks + chunk
_RING_AG_BASE = 550000   # ring allgather:     base + step*nchunks + chunk
_RING_TAG_SPAN = 400000  # per-phase room; bounds (size-1)*nchunks
_PROBE_BASE = 955000     # nnz-agreement allgather before an auto pick
_SPARSE_RS_BASE = 960000  # sparse direct scatter: base + segment
_SPARSE_AG_BASE = 1000000  # sparse allgather ring: base + step


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def choose_algo(nbytes: int, n_elems: int, world: int, *,
                density: Optional[float] = None,
                reducer_is_add: bool = True, is_f32: bool = True,
                forced: Optional[str] = None) -> str:
    """THE algorithm decision — one documented function replacing the
    scattered size checks (auto used to key on byte size only). Every
    input is either cluster-identical by the collective contract
    (payload shape/dtype, reducer, world, flags) or cluster-AGREED
    (``density`` comes from the nnz probe round, the same value on
    every rank), so every rank lands on the same branch — a split
    decision would mismatch the wire protocol.

    Order of precedence:

    1. payloads under 4 KB, or with fewer elements than ranks, take the
       Bruck allgather + local reduce ``small`` path regardless of any
       forced algorithm (the reference's small-path contract);
    2. a forced ``-allreduce_algo`` (ring / rhalving / sparse) wins;
       forcing ``sparse`` for a non-additive reducer or a non-float32
       payload falls back to the ring (the index-union merge is a SUM
       over float32 codec streams, nothing else);
    3. auto, sparse: float32 sum-reductions whose agreed union density
       sits at or below min(``-allreduce_sparse_density``,
       ``break_even_density()``) AND whose union index count
       (density x elements) fits ``-allreduce_sparse_idx_budget`` take
       the sparse-stream path — the measured fill-in signal, re-probed
       every call, is exactly what switches a densifying workload back
       to the dense ring;
    4. auto, dense: at or above ``-allreduce_ring_kb`` the chunked
       ring; non-power-of-two worlds switch to the ring from 16 KB (the
       recursive-halving surplus fold costs two extra full-buffer
       serial hops); everything else recursive halving.
    """
    if nbytes < _SMALL_BYTES or n_elems < world:
        return "bruck"
    algo = str(get_flag("allreduce_algo")) if forced is None else forced
    if algo == "sparse":
        return "sparse" if (reducer_is_add and is_f32) else "ring"
    if algo in ("ring", "rhalving"):
        return algo
    if reducer_is_add and is_f32 and density is not None:
        cutoff = min(float(get_flag("allreduce_sparse_density")),
                     break_even_density())
        if density <= cutoff and density * n_elems <= int(
                get_flag("allreduce_sparse_idx_budget")):
            return "sparse"
    if nbytes >= int(get_flag("allreduce_ring_kb")) * 1024:
        return "ring"
    if not _is_pow2(world) and nbytes >= 4 * _SMALL_BYTES:
        # Surplus fold pays 2 extra full-buffer serial hops; the
        # ring needs no fold, so non-pow2 worlds switch early.
        return "ring"
    return "rhalving"


class AllreduceEngine:
    def __init__(self, net: NetInterface):
        self._net = net
        self.rank = net.rank
        self.size = net.size
        # (src, msg_id) -> (blob, wire_encoded): early-arriving frames.
        # Decoding is lazy so allgather forwarding can relay the exact
        # received frame bytes.
        self._stash: Dict[Tuple[int, int], Tuple[Blob, bool]] = {}
        self._gen = 0
        # Error-feedback residuals, keyed by (phase, element count):
        # carried across calls so quantization noise from step t is
        # folded into step t+1's payload (OneBitFilter convention).
        self._ef: Dict[Tuple[str, int], np.ndarray] = {}
        # Frames are self-describing (CODEC_SLOT marks an encoded
        # payload), so decode needs no negotiation; in ma mode every
        # rank runs this same engine. In-process transports move object
        # references — lossless encoding there only burns CPU (the
        # lossy tier still engages: its point is the quantization
        # semantics, not the bytes). The SPARSE path frames regardless:
        # the index+value stream is the representation its O(nnz) merge
        # runs on, not just a wire shrink.
        self._codec = (not net.in_process
                       and bool(get_flag("wire_codec")))
        #: Algorithm the last public collective ran
        #: (bruck/ring/rhalving/sparse/sharded) — bench + tests read it.
        self.last_algo: Optional[str] = None
        #: Bytes of reduce-state this rank held during the last
        #: collective: the buffer(s) that accumulate reduced values
        #: before the allgather re-assembles the full result. The
        #: sharded paths hold one SEGMENT (~1/world of the buffer);
        #: the monolithic/ring paths hold the full flat copy; the
        #: small path stacks `world` whole blocks.
        self.last_reduce_state_bytes = 0

    # -- msg_id construction --
    def _mid(self, tag: int) -> int:
        return (self._gen << _TAG_BITS) | tag

    def _next_gen(self) -> None:
        self._gen = (self._gen % _GEN_MOD) + 1

    # -- raw paired exchange over the message transport --
    def _post(self, dst: int, blob: Blob, tag: int, encoded: bool) -> None:
        msg = Message(src=self.rank, dst=dst, msg_type=MsgType.Default,
                      msg_id=self._mid(tag))
        msg.push(blob)
        if encoded:
            msg.header[CODEC_SLOT] = 1
        self._net.send_async(msg)

    def _send(self, dst: int, payload: np.ndarray, tag: int) -> None:
        """Lossless send: codec-framed when the wire would benefit."""
        payload = np.ascontiguousarray(payload)
        if self._net.in_process and payload.base is not None:
            # In-process transports deliver references; a view of this
            # rank's working buffer must be snapshotted, or a receiver
            # still holding it (e.g. an allgather forward) would observe
            # later in-place mutations.
            payload = payload.copy()
        # worth_encoding gates on density too: dense model-average
        # segments (the common ma workload) skip the frame-copy round
        # trip a RAW frame would cost.
        if self._codec and payload.nbytes >= _CODEC_MIN_BYTES \
                and worth_encoding(payload):
            # Lossless tiers only; the (header, streams) parts ride the
            # scatter-gather framer unjoined (docs/MEMORY.md).
            parts, _ = encode_blob_views(payload)
            self._post(dst, Blob.from_parts(parts), tag, True)
        else:
            self._post(dst, Blob(payload), tag, False)

    def _send_lossy(self, dst: int, flat: np.ndarray, lo: int, hi: int,
                    tag: int, ef: np.ndarray) -> np.ndarray:
        """Quantized send of ``flat[lo:hi]`` with error feedback: the
        residual from this range's previous quantization is folded into
        the values before encoding and the fresh residual stored back.
        Segments below the codec threshold fall back to the lossless
        path (the folded correction goes out exactly, so the residual
        zeroes). Returns the values AS THE RECEIVER WILL DECODE THEM —
        allgather origins adopt these so every rank lands on identical
        bytes."""
        vals = flat[lo:hi] + ef[lo:hi]
        if vals.nbytes < _CODEC_MIN_BYTES:
            ef[lo:hi] = 0.0
            self._send(dst, vals, tag)
            return vals
        parts, residual = encode_blob_views(vals, lossy=True)
        ef[lo:hi] = residual if residual is not None else 0.0
        self._post(dst, Blob.from_parts(parts), tag, True)
        # decoded == vals - residual; reconstruct instead of re-decoding.
        return vals - ef[lo:hi]

    def _drain_until(self, src: int, tag: int) -> Tuple[Blob, bool]:
        """Tag-matched receive: a fast peer's next-round message may
        arrive before the one this round is waiting on; stash and keep
        draining. Fails loudly (with full context) on timeout, closed
        transport, or unbounded stash growth."""
        key = (src, self._mid(tag))
        timeout = float(get_flag("allreduce_timeout_s"))
        cap = int(get_flag("allreduce_stash_cap"))
        start = time.monotonic()
        while key not in self._stash:
            remaining = timeout - (time.monotonic() - start)
            msg = self._net.recv(timeout=max(remaining, 0.001)) \
                if remaining > 0 else None
            if msg is None:
                raise RuntimeError(
                    f"allreduce engine rank {self.rank}: transport closed "
                    f"or timed out after {time.monotonic() - start:.1f}s "
                    f"(timeout {timeout:.1f}s, -allreduce_timeout_s) "
                    f"waiting for peer {src} msg_id 0x{self._mid(tag):x} "
                    f"(gen {self._gen}, tag {tag}); stash holds "
                    f"{len(self._stash)} early frames "
                    f"{sorted(self._stash)[:8]}")
            self._stash[(msg.src, msg.msg_id)] = \
                (msg.data[0], is_wire_encoded(msg))
            if key in self._stash:
                # The awaited frame landed: popping it below shrinks
                # the stash again, so don't let a boundary-sitting cap
                # fail a collective at the moment it makes progress.
                break
            if len(self._stash) > cap:
                sample = sorted(self._stash)[:8]
                raise RuntimeError(
                    f"allreduce engine rank {self.rank}: stash exceeded "
                    f"{cap} unmatched frames (-allreduce_stash_cap) while "
                    f"waiting for peer {src} msg_id 0x{self._mid(tag):x} "
                    f"— a crashed peer or tag-protocol bug is flooding "
                    f"the endpoint; sample keys {sample}")
        return self._stash.pop(key)

    def _recv(self, src: int, tag: int, dtype) -> np.ndarray:
        blob, encoded = self._drain_until(src, tag)
        if encoded:
            decoded = decode_blob(np.asarray(blob.data))
            return decoded if decoded.dtype == np.dtype(dtype) \
                else np.asarray(decoded, dtype=dtype)
        return blob.as_array(dtype)

    def _exchange(self, peer: int, payload: np.ndarray,
                  tag: int) -> np.ndarray:
        """Blocking sendrecv with one peer (ref: mpi_net.h:269-287)."""
        self._send(peer, payload, tag)
        return self._recv(peer, tag, payload.dtype)

    # -- algorithm choice --
    def _probe_union_density(self, data: np.ndarray) -> float:
        """Cluster-agreed density signal for ``choose_algo``: a tiny
        Bruck allgather of each rank's nnz, reduced to
        min(1, sum nnz / n) — the union upper bound on the reduced
        result's fill-in (cancellation only shrinks it). Every rank
        computes the identical value, so the dense-vs-sparse pick can
        never split the cluster the way a LOCAL density test would
        (rank 0 at 5.1%% picking dense while rank 1 at 4.9%% picks
        sparse deadlocks the protocol)."""
        nnz = int(np.count_nonzero(data))
        parts = self._bruck_allgather(np.array([nnz], np.int64),
                                      base=_PROBE_BASE)
        total = sum(int(p[0]) for p in parts)
        return min(1.0, total / max(data.size, 1))

    def _should_probe(self, data: np.ndarray, reducer: Callable) -> bool:
        # Rank-identical by the collective contract (same payload
        # shape/dtype, same reducer, same flags everywhere): every rank
        # either joins the probe round or skips it.
        return (str(get_flag("allreduce_algo")) == "auto"
                and reducer is np.add
                and data.dtype == np.float32
                and data.nbytes >= _SMALL_BYTES
                and data.size >= self.size)

    # -- public API (ref: allreduce_engine.h:96-118) --
    def allreduce(self, data: np.ndarray,
                  reducer: Callable = np.add) -> np.ndarray:
        data = np.asarray(data)
        if self.size == 1:
            return data.copy()
        self._next_gen()
        density = self._probe_union_density(data) \
            if self._should_probe(data, reducer) else None
        algo = choose_algo(data.nbytes, data.size, self.size,
                           density=density,
                           reducer_is_add=reducer is np.add,
                           is_f32=data.dtype == np.float32)
        self.last_algo = algo
        if algo == "bruck":
            # Small path: allgather everyone's buffer, reduce locally
            # (ref: allreduce_engine.cpp:34-43).
            stacked = self._bruck_allgather(data)
            self.last_reduce_state_bytes = self.size * data.nbytes
            out = stacked[0]
            for part in stacked[1:]:
                out = reducer(out, part)
            return out
        if algo == "sparse":
            return self._sparse_allreduce(data, density)
        if algo == "ring":
            self.last_reduce_state_bytes = data.nbytes
            return self._ring_allreduce(data, reducer)
        self.last_reduce_state_bytes = data.nbytes
        return self._reduce_scatter_allgather(data, reducer)

    def allgather(self, data: np.ndarray) -> list:
        self._next_gen()
        return self._bruck_allgather(data)

    def _bruck_allgather(self, data: np.ndarray,
                         base: int = _BRUCK_BASE) -> list:
        """Bruck doubling allgather: after round k every rank holds 2^(k+1)
        blocks; blocks are sent to rank-2^k and received from rank+2^k
        (ref: allreduce_engine.cpp:90-117, allreduce_topo.cpp:20-37)."""
        n = self.size
        blocks = [np.asarray(data)]
        tag = base
        distance = 1
        while distance < n:
            dst = (self.rank - distance) % n
            src = (self.rank + distance) % n
            count = min(distance, n - distance)
            payload = np.concatenate(
                [b.reshape(-1) for b in blocks[:count]])
            self._send(dst, payload, tag)
            incoming = self._recv(src, tag,
                                  blocks[0].dtype).reshape(count, -1)
            for i in range(count):
                blocks.append(incoming[i].reshape(blocks[0].shape))
            distance *= 2
            tag += 1
        # blocks[j] is the buffer of rank (self.rank + j) % n; rotate to
        # rank order.
        ordered = [None] * n
        for j, block in enumerate(blocks[:n]):
            ordered[(self.rank + j) % n] = block
        return ordered

    # -- chunked pipelined ring --------------------------------------
    def _ring_allreduce(self, data: np.ndarray,
                        reducer: Callable) -> np.ndarray:
        """Ring reduce-scatter + ring allgather over chunks, with a
        sliding window of in-flight chunks per step. Any rank count.

        Reduce-scatter step s: send segment (rank-s) of every chunk to
        the right neighbor, receive segment (rank-s-1) from the left and
        fold it in; after n-1 steps this rank owns the fully reduced
        segment (rank+1). Allgather step s: forward segment (rank+1-s)
        right, receive (rank-s) from the left. Sends ride
        ``send_async`` writer threads, so while this rank blocks on
        chunk c's inbound frame, chunks c+1..c+window are already on
        the wire and the previous chunk's reduce ran during their
        transfer — wire time and reduce time overlap instead of
        alternating."""
        n, r = self.size, self.rank
        right, left = (r + 1) % n, (r - 1) % n
        shape = np.asarray(data).shape
        flat = np.asarray(data).reshape(-1).copy()
        N = flat.size
        chunk_elems = max(1, (int(get_flag("allreduce_chunk_kb")) * 1024)
                          // max(flat.itemsize, 1))
        nchunks = max(1, -(-N // chunk_elems))
        # Tag-space guard: (n-1)*nchunks must fit each phase's band.
        nchunks = min(nchunks, max(1, _RING_TAG_SPAN // max(n - 1, 1)))
        cb = np.linspace(0, N, nchunks + 1).astype(np.int64)
        segs = [np.linspace(cb[c], cb[c + 1], n + 1).astype(np.int64)
                for c in range(nchunks)]
        window = max(1, int(get_flag("allreduce_window")))
        # Lossy only for float32 SUMS: the error-feedback identity
        # (residual folded into the next payload cancels over
        # accumulation) only holds for additive reduction — adding a
        # carried residual before a max/min would corrupt the result.
        lossy = bool(get_flag("allreduce_lossy")) \
            and flat.dtype == np.float32 and reducer is np.add
        ef_rs = self._ef_buffer("rs", N) if lossy else None
        ef_ag = self._ef_buffer("ag", N) if lossy else None

        def bounds(c: int, seg: int) -> Tuple[int, int]:
            return int(segs[c][seg]), int(segs[c][seg + 1])

        # Phase 1: reduce-scatter.
        for step in range(n - 1):
            send_id = (r - step) % n
            recv_id = (r - step - 1) % n

            def rs_recv(c: int, step: int = step,
                        recv_id: int = recv_id) -> None:
                tag = _RING_RS_BASE + step * nchunks + c
                lo, hi = bounds(c, recv_id)
                incoming = self._recv(left, tag, flat.dtype)
                flat[lo:hi] = reducer(flat[lo:hi], incoming)

            pending = collections.deque()
            for c in range(nchunks):
                tag = _RING_RS_BASE + step * nchunks + c
                lo, hi = bounds(c, send_id)
                if lossy:
                    self._send_lossy(right, flat, lo, hi, tag, ef_rs)
                else:
                    self._send(right, flat[lo:hi], tag)
                pending.append(c)
                if len(pending) >= window:
                    rs_recv(pending.popleft())
            while pending:
                rs_recv(pending.popleft())

        # Phase 2: allgather with verbatim frame forwarding — each
        # reduced segment is encoded once at its owner; hops relay the
        # received blob untouched (no per-hop re-quantization), and the
        # owner adopts its own decoded frame, so every rank lands on
        # the same bytes even in lossy mode.
        carry: list = [None] * nchunks
        for step in range(n - 1):
            send_id = (r + 1 - step) % n
            recv_id = (r - step) % n

            def ag_recv(c: int, step: int = step,
                        recv_id: int = recv_id) -> None:
                tag = _RING_AG_BASE + step * nchunks + c
                blob, encoded = self._drain_until(left, tag)
                lo, hi = bounds(c, recv_id)
                if encoded:
                    flat[lo:hi] = decode_blob(np.asarray(blob.data))
                else:
                    flat[lo:hi] = blob.as_array(flat.dtype)
                carry[c] = (blob, encoded)

            pending = collections.deque()
            for c in range(nchunks):
                tag = _RING_AG_BASE + step * nchunks + c
                if step == 0:
                    lo, hi = bounds(c, send_id)
                    if lossy:
                        flat[lo:hi] = self._send_lossy(
                            right, flat, lo, hi, tag, ef_ag)
                    else:
                        self._send(right, flat[lo:hi], tag)
                else:
                    blob, encoded = carry[c]
                    self._post(right, blob, tag, encoded)
                pending.append(c)
                if len(pending) >= window:
                    ag_recv(pending.popleft())
            while pending:
                ag_recv(pending.popleft())
        # Queued async frames are zero-copy VIEWS of ``flat`` now
        # (scatter-gather framing): drain them before handing the
        # buffer to the caller, who is free to mutate the result. The
        # old path paid a serialize-time copy per frame instead; the
        # flush costs one wait for writes already in flight.
        self._net.flush_sends()
        return flat.reshape(shape)

    # -- sparse-stream tier (SparCML-style index+value collectives) ----
    def sharded_average(self, data: np.ndarray) -> np.ndarray:
        """Cross-rank MEAN with sharded reduce state (arxiv
        2004.13336's cross-replica sharding of the update step): direct
        sparse reduce-scatter — each rank accumulates only the segment
        it owns — then the divide applied SHARD-LOCALLY, then a
        single-encode allgather that re-assembles the full averaged
        buffer straight into the output. No rank ever holds more
        reduce-state than one segment (~1/world of the buffer, reported
        via ``last_reduce_state_bytes``), where the dense paths copy
        and accumulate the whole flat buffer; see docs/ALLREDUCE.md
        for the memory math. float32 only — this is the model-average
        parameter path, and the sparse merge is an f32 sum.

        Bit-identity: the segment fold order matches the UNCHUNKED
        dense ring's, and the divide is the same elementwise op the
        dense ``allreduce(x) / world`` path runs, so a lossless sharded
        average equals ring-then-divide bit for bit (one chunk)."""
        data = np.asarray(data)
        if data.dtype != np.float32:
            raise TypeError(
                "sharded_average is float32-only (model-average "
                f"parameters); got {data.dtype}")
        if self.size == 1:
            return data.copy()
        self._next_gen()
        self.last_algo = "sharded"
        if data.nbytes < _SMALL_BYTES or data.size < self.size:
            # Sharding a sub-4KB buffer buys nothing: small path.
            stacked = self._bruck_allgather(data)
            self.last_reduce_state_bytes = self.size * data.nbytes
            out = stacked[0].copy()
            for part in stacked[1:]:
                out += part
            out /= self.size
            return out
        samples("SPARSE_FILL[input]").add(density_of(data))
        return self._sparse_collective(data, average=True)

    def _sparse_allreduce(self, data: np.ndarray,
                          density: Optional[float]) -> np.ndarray:
        """Sum-allreduce over sparse index+value streams (same two
        phases as ``sharded_average`` minus the divide)."""
        if density is not None:
            samples("SPARSE_FILL[input]").add(density)
        return self._sparse_collective(np.asarray(data), average=False)

    def _sparse_collective(self, data: np.ndarray,
                           average: bool) -> np.ndarray:
        """The sparse-tier driver both public forms share: direct
        reduce-scatter, optional shard-local divide, single-encode
        allgather into a fresh output buffer."""
        shape = data.shape
        flat = np.ascontiguousarray(data).reshape(-1)
        bounds = np.linspace(0, flat.size,
                             self.size + 1).astype(np.int64)
        lossy = bool(get_flag("allreduce_lossy"))
        acc = self._sparse_reduce_scatter(flat, bounds, lossy)
        self.last_reduce_state_bytes = acc.nbytes
        if average:
            acc /= self.size  # the shard-local average
        out = np.empty(flat.size, np.float32)
        self._sparse_allgather(out, bounds, acc, lossy)
        return out.reshape(shape)

    def _post_segment(self, dst: int, payload: np.ndarray,
                      tag: int) -> None:
        """Sparse-tier lossless contribution send: codec-framed
        whenever the sparse tier wins — even in-process, because the
        index+value stream IS the representation the owner's O(nnz)
        merge consumes — raw otherwise (``_send`` handles the
        in-process snapshot copy)."""
        payload = np.ascontiguousarray(payload)
        if payload.nbytes >= _CODEC_MIN_BYTES and worth_encoding(payload):
            parts, _ = encode_blob_views(payload)
            self._post(dst, Blob.from_parts(parts), tag, True)
        else:
            self._send(dst, payload, tag)

    def _merge_stream(self, acc: np.ndarray, blob: Blob,
                      encoded: bool) -> None:
        """Fold one inbound contribution into the owner's segment
        accumulator: sparse frames through the index stream
        (``acc[idx] += vals`` — codec indices are strictly increasing,
        so the fancy-index add never collides with itself), raw / dense
        tiers through a dense add. Elementwise this performs the same
        additions the dense ring's fold would, so the lossless result
        is bit-identical."""
        if encoded:
            idx, vals = decode_blob_sparse(np.asarray(blob.data))
            if idx is None:
                acc += vals.astype(np.float32, copy=False)
            else:
                acc[idx] += vals
        else:
            acc += blob.as_array(np.float32)

    def _sparse_reduce_scatter(self, flat: np.ndarray,
                               bounds: np.ndarray,
                               lossy: bool) -> np.ndarray:
        """Phase 1 of the sparse tier: DIRECT scatter. Each rank sends
        its own contribution for segment s straight to s's owner as a
        codec sparse frame — partial sums never ride the wire, so the
        hop-by-hop fill-in growth a sparse RING would pay (the union
        densifies every hop) costs bytes only once, in the allgather
        of the fully-reduced segments. The owner then folds the n-1
        inbound index streams plus its own slice IN RANK ORDER,
        starting from the segment index — the same pairwise sums as
        the unchunked dense ring's fold (operand order differs only
        where IEEE-754 addition commutes), which is what makes the
        lossless sparse path bit-identical to the dense ring. Rank r
        owns segment (r+1) %% n, the dense ring's ownership map.
        Fill-in after every folded stream lands on the
        ``SPARSE_FILL[reduce]`` samples reservoir."""
        n, r = self.size, self.rank
        ef = self._ef_buffer("sprs", flat.size) if lossy else None
        for off in range(1, n):
            o = (r + off) % n  # stagger: rank 0 is not everyone's
            s = (o + 1) % n    # first target
            lo, hi = int(bounds[s]), int(bounds[s + 1])
            tag = _SPARSE_RS_BASE + s
            if lossy:
                self._send_lossy(o, flat, lo, hi, tag, ef)
            else:
                self._post_segment(o, flat[lo:hi], tag)
        own = (r + 1) % n
        lo, hi = int(bounds[own]), int(bounds[own + 1])
        seglen = hi - lo
        acc = np.zeros(seglen, np.float32)
        fill = samples("SPARSE_FILL[reduce]")
        for k in range(n):
            src = (own + k) % n
            if src == r:  # own slice folds last (r == own - 1 mod n)
                acc += flat[lo:hi]
            else:
                blob, encoded = self._drain_until(
                    src, _SPARSE_RS_BASE + own)
                self._merge_stream(acc, blob, encoded)
            fill.add(np.count_nonzero(acc) / max(seglen, 1))
        return acc

    def _sparse_allgather(self, out: np.ndarray, bounds: np.ndarray,
                          acc: np.ndarray, lossy: bool) -> None:
        """Phase 2 of the sparse tier: ring allgather of the reduced
        (or reduced-and-averaged) segments with verbatim frame
        forwarding. Each segment is encoded ONCE at its owner — as a
        sparse stream while its measured fill-in stays below the codec
        break-even, as a RAW frame past it (the automatic per-segment
        dense switchover) — and relayed untouched, so every rank lands
        on identical bytes, lossy tiers included."""
        n, r = self.size, self.rank
        right, left = (r + 1) % n, (r - 1) % n
        own = (r + 1) % n
        lo, hi = int(bounds[own]), int(bounds[own + 1])
        if lossy:
            ef = self._ef_buffer("spag", out.size)
            vals = acc + ef[lo:hi]
            if vals.nbytes >= _CODEC_MIN_BYTES:
                parts, residual = encode_blob_views(vals, lossy=True)
                ef[lo:hi] = residual if residual is not None else 0.0
            else:  # sub-threshold: exact, pending residual consumed
                parts, _ = encode_blob_views(vals)
                ef[lo:hi] = 0.0
            # decoded == vals - residual; every rank lands on this.
            own_vals = vals - ef[lo:hi]
            carry, encoded = Blob.from_parts(parts), True
        elif acc.nbytes >= _CODEC_MIN_BYTES and worth_encoding(acc):
            parts, _ = encode_blob_views(acc)
            own_vals = acc
            carry, encoded = Blob.from_parts(parts), True
        else:
            own_vals = acc
            carry, encoded = Blob(acc), False
        out[lo:hi] = own_vals
        for step in range(n - 1):
            tag = _SPARSE_AG_BASE + step
            self._post(right, carry, tag, encoded)
            blob, enc = self._drain_until(left, tag)
            seg = (r - step) % n
            slo, shi = int(bounds[seg]), int(bounds[seg + 1])
            seg_out = out[slo:shi]
            if enc:
                # Scatter the index stream straight into the output
                # slice — decode_blob would allocate a full segment
                # temp just to copy it here.
                idx, vals = decode_blob_sparse(np.asarray(blob.data))
                if idx is None:
                    seg_out[:] = vals
                else:
                    seg_out[:] = 0.0
                    seg_out[idx] = vals
            else:
                seg_out[:] = blob.as_array(np.float32)
            carry, encoded = blob, enc

    def _ef_buffer(self, phase: str, n: int) -> np.ndarray:
        buf = self._ef.get((phase, n))
        if buf is None:
            # One buffer per phase: a residual only means something for
            # the SAME flat layout, so a size change (new model shape)
            # both invalidates and evicts the old one — the engine is
            # cached for the process lifetime and must not pin two
            # float32 buffers per distinct size ever seen.
            for key in [k for k in self._ef if k[0] == phase]:
                del self._ef[key]
            buf = self._ef[(phase, n)] = np.zeros(n, np.float32)
        return buf

    # -- monolithic recursive halving ---------------------------------
    def _reduce_scatter_allgather(self, data: np.ndarray,
                                  reducer: Callable) -> np.ndarray:
        """Large path: recursive-halving reduce-scatter then allgather of
        the reduced segments (ref: allreduce_engine.cpp:44-54,120-172)."""
        n = self.size
        flat = np.asarray(data).reshape(-1).copy()
        # Fold surplus ranks onto the largest power-of-two group (the
        # reference pairs each surplus rank with a group leader,
        # ref: allreduce_topo.cpp:58-168).
        pow2 = 1
        while pow2 * 2 <= n:
            pow2 *= 2
        surplus = n - pow2
        tag = _RH_BASE
        if self.rank >= pow2:
            # Surplus rank: hand the whole buffer to its leader, then wait
            # for the final result.
            leader = self.rank - pow2
            self._send(leader, flat, tag)
            result = self._recv(leader, _RH_RESULT, flat.dtype)
            # Copy: in-process the received blob is (a view of) the
            # leader's result buffer — the caller owns its return value.
            return result.reshape(np.asarray(data).shape).copy()
        if self.rank < surplus:
            incoming = self._recv(self.rank + pow2, tag, flat.dtype)
            flat = reducer(flat, incoming)

        # Recursive halving among the pow2 group: segment boundaries are
        # even splits of the flat buffer.
        bounds = np.linspace(0, flat.size, pow2 + 1).astype(np.int64)
        lo, hi = 0, pow2
        step_tag = tag + 1
        while hi - lo > 1:
            mid = (lo + hi) // 2
            half = (hi - lo) // 2
            in_low = self.rank < mid
            peer = self.rank + half if in_low else self.rank - half
            keep = (lo, mid) if in_low else (mid, hi)
            give = (mid, hi) if in_low else (lo, mid)
            give_seg = flat[bounds[give[0]]:bounds[give[1]]]
            recv_seg = self._exchange(peer, give_seg, step_tag)
            seg = slice(bounds[keep[0]], bounds[keep[1]])
            flat[seg] = reducer(flat[seg], recv_seg)
            lo, hi = keep
            step_tag += 1

        # Allgather the reduced segments back (ring of exchanges via the
        # Bruck machinery on the segment level).
        my_seg = flat[bounds[self.rank]:bounds[self.rank + 1]]
        gathered = self._gather_segments(my_seg, bounds, flat.dtype,
                                         step_tag)
        flat = np.concatenate(gathered)
        if self.rank < surplus:
            self._send(self.rank + pow2, flat, _RH_RESULT)
        # The queued exchange/result frames view ``flat`` and the round
        # segments directly (scatter-gather framing): drain before the
        # caller may mutate the returned buffer.
        self._net.flush_sends()
        return flat.reshape(np.asarray(data).shape)

    def _gather_segments(self, my_seg, bounds, dtype, tag) -> list:
        """Bruck doubling allgather of the (unequal) reduced segments.
        Ownership after round r is deterministic — rank holds segments
        {rank+j mod p : j < 2^r} — so no ids ride the wire."""
        pow2 = len(bounds) - 1
        have = {self.rank: np.asarray(my_seg)}
        distance = 1
        while distance < pow2:
            dst = (self.rank - distance) % pow2
            src = (self.rank + distance) % pow2
            count = min(distance, pow2 - distance)
            send_ids = [(self.rank + j) % pow2 for j in range(count)]
            self._send(dst, np.concatenate([have[i] for i in send_ids]), tag)
            raw = self._recv(src, tag, dtype)
            offset = 0
            for j in range(count):
                seg_id = (src + j) % pow2
                seg_len = int(bounds[seg_id + 1] - bounds[seg_id])
                have[seg_id] = raw[offset:offset + seg_len]
                offset += seg_len
            distance *= 2
            tag += 1
        return [have[i] for i in range(pow2)]
