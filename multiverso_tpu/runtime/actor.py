"""Actor base: a named thread draining a mailbox through a handler table.

TPU-native equivalent of the reference's ``Actor``
(ref: include/multiverso/actor.h:18-58, src/actor.cpp:14-55). Same design:
each actor owns one thread whose main loop pops messages off ``mailbox`` and
dispatches on ``MsgType`` via a registered handler map; ``send_to`` routes to
sibling actors through the owning Zoo by name. Actor names match the
reference (ref: include/multiverso/actor.h:60-67) so routing rules carry
over verbatim.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ..core.message import Message
from ..util import log
from ..util.mt_queue import MtQueue
from . import thread_roles

# ref: include/multiverso/actor.h:60-67
WORKER = "worker"
SERVER = "server"
CONTROLLER = "controller"
COMMUNICATOR = "communicator"


class Actor:
    #: Thread role the run loop registers at spawn (docs/THREADS.md).
    #: Subclasses override — the Communicator's loop is DISPATCH: it
    #: must never block (mvlint pass 9 proves it can't).
    ROLE = thread_roles.ACTOR

    def __init__(self, name: str, zoo) -> None:
        self.name = name
        self._zoo = zoo
        self.mailbox: MtQueue = MtQueue()
        self._handlers: Dict[int, Callable[[Message], None]] = {}
        self._thread: Optional[threading.Thread] = None
        zoo.register_actor(self)

    # -- lifecycle --
    def start(self) -> None:
        self._thread = thread_roles.spawn(
            self.ROLE, target=self._main,
            name=f"mv-{self.name}-r{self._zoo.rank}")

    def stop(self) -> None:
        """Drain-exit: the thread finishes the current message then stops."""
        self.mailbox.exit()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=30)
        self._zoo.deregister_actor(self)

    # -- messaging --
    def receive(self, msg: Message) -> None:
        self.mailbox.push(msg)

    def send_to(self, name: str, msg: Message) -> None:
        self._zoo.send_to(name, msg)

    def register_handler(self, msg_type, fn: Callable[[Message], None]) -> None:
        self._handlers[int(msg_type)] = fn

    # -- main loop (ref: src/actor.cpp:38-50) --
    def _main(self) -> None:
        while True:
            msg = self.mailbox.pop()
            if msg is None:
                break
            self._safe_dispatch(msg)

    def _safe_dispatch(self, msg: Message) -> None:
        """Dispatch one message; an actor must not die silently."""
        try:
            self._dispatch(msg)
        except Exception:  # noqa: BLE001
            log.error("actor %s: handling message type %d raised",
                      self.name, msg.type_int)
            import traceback
            traceback.print_exc()

    def _dispatch(self, msg: Message) -> None:
        handler = self._handlers.get(int(msg.type_int))
        if handler is None:
            log.error("actor %s: unhandled message type %d",
                      self.name, msg.type_int)
            return
        handler(msg)
