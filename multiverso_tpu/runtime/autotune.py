"""Closed-loop self-tuning: ClusterMetrics drives the performance knobs.

The repo exposes dozens of load-bearing flags (staleness bound, replica
budget, coalescing flush caps, admission watermarks, serving batch
window, allreduce chunk, codec density threshold) and — since the
observability layer (docs/OBSERVABILITY.md) — the cluster-wide signals
to judge them. This module closes the loop (docs/AUTOTUNE.md): the
rank-0 controller's ``AutotuneManager`` consumes the aggregated
``ClusterMetrics`` view on a ``-autotune_interval_s`` cadence, runs one
policy per knob (hysteresis + hard min/max guardrails), and broadcasts
epoch-stamped config updates as ``Control_Config`` messages — the
``Control_Shard_Map`` pattern: below the worker band, intercepted by
name in the communicator, remote copies on non-blocking ``send_async``
(the recurring dispatch-starvation lesson). The receive side is the
dynamic-flag layer in ``util/configure.py`` (``TUNABLE_FLAGS`` +
per-flag apply hooks), so hot paths that cached a value at
construction actually pick the change up; non-tunable flags are
rejected at broadcast time.

Every decision is observable: ``mv_autotune_*`` gauges ride the
controller's ``/metrics`` scrape surface (current value, last-change
epoch, latest policy verdict, per-rank acked epoch), and the full
decision trajectory is exported for the bench JSON.

Adaptive-decision precedent: SparCML's density break-even and EQuARX's
quantization-tier selection (PAPERS.md) pick their operating point from
measured traffic rather than a pinned constant — here the same move is
applied across the whole transport/table/serving stack.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.blob import Blob
from ..core.message import Message, MsgType
from ..util import log
from ..util.configure import (CANONICAL_FLAGS, define_double,
                              define_string, get_flag)
from ..util.dashboard import count
from ..util.lock_witness import named_condition, named_lock
from . import actor as actors
from . import thread_roles

define_double("autotune_interval_s", 0.0,
              "closed-loop self-tuning cadence ON THE CONTROLLER RANK "
              "(docs/AUTOTUNE.md): every interval the AutotuneManager "
              "evaluates the aggregated ClusterMetrics view against "
              "the per-knob policies and broadcasts an epoch-stamped "
              "Control_Config update when any knob moves. 0 (default) "
              "disables the controller — every knob stays at its "
              "flag-configured value. Pair with -metrics_interval_s "
              "(the policies are blind without rank reports)")
define_double("autotune_slo_p99_ms", 50.0,
              "read-latency SLO the autotune policies steer against: "
              "the serving p99 (SERVING_LATENCY_MS, falling back to "
              "the mean blocking table-Get when no serving tier runs) "
              "inside this bound permits throughput-side widening "
              "(staleness bound); a violation drives the shrink side "
              "(docs/AUTOTUNE.md)")
define_string("autotune_pin", "",
              "comma-separated tunable flag names the autotune "
              "controller must NOT move (operator override, read "
              "live each tick): pinned knobs keep their current "
              "value and report verdict 'pinned' in the "
              "mv_autotune_* gauges")

#: POLICY REGISTRY — one entry per knob the controller actively
#: drives, with its hard guardrail bounds and the canonical metrics it
#: reads. ``tools/mvlint``'s tunable-lint pass parses this literal
#: (never imports) and fails CI when a key is not in
#: ``util/configure.py TUNABLE_FLAGS`` or a ``metrics`` entry does not
#: name a canonical metric (``util/dashboard.py METRIC_NAMES``,
#: trailing-``*`` families included) — a policy steering on a typo'd
#: signal would silently hold forever. Keep the literal plain.
#: ``TUNABLE_FLAGS`` entries WITHOUT a policy here are broadcast-able
#: (rejoin re-anchoring, tests) but never moved autonomously.
AUTOTUNE_POLICIES: Dict[str, dict] = {
    "max_get_staleness": {
        "min": 0, "max": 64,
        "metrics": ["SERVING_LATENCY_MS", "WORKER_TABLE_SYNC_GET",
                    "SERVER_PROCESS_GET", "WORKER_PROCESS_GET",
                    "CLIENT_CACHE_HIT", "CLIENT_CACHE_MISS"],
    },
    "replica_hot_rows": {
        "min": 0, "max": 4096,
        "metrics": ["REPLICA_REPAIR", "REPLICA_HIT",
                    "SERVER_PROCESS_GET"],
    },
    "coalesce_max_msgs": {
        "min": 8, "max": 64,
        "metrics": ["DISPATCH_QUEUE_DEPTH[d*]", "MAILBOX_DEPTH[*]"],
    },
    "serving_batch_window_ms": {
        "min": 0.25, "max": 2.0,
        "metrics": ["DISPATCH_QUEUE_DEPTH[d*]", "MAILBOX_DEPTH[*]",
                    "SERVING_LATENCY_MS"],
    },
    "allreduce_chunk_kb": {
        "min": 64, "max": 4096,
        "metrics": ["tcp_send"],
    },
    "wire_codec_density": {
        "min": 0.05, "max": 0.9,
        "metrics": ["SPARSE_FILL[*]"],
    },
}

#: Hysteresis: a knob moves only after this many CONSECUTIVE ticks
#: proposing the same direction — one noisy window must not flap a
#: knob the whole cluster re-applies.
HYSTERESIS_TICKS = 2
#: Cooldown: after a knob moves, it holds for this many ticks so the
#: next decision sees metrics produced UNDER the new value, not the
#: transition.
COOLDOWN_TICKS = 2
#: Below this many table Gets per tick the read-side policies hold —
#: an idle cluster teaches nothing.
MIN_READ_RATE = 32
#: Queue-depth watermarks (p90 of the dispatch/mailbox depth samples)
#: for the back-off policies.
QUEUE_DEEP = 64.0
QUEUE_SHALLOW = 8.0
#: tcp_send mean-ms thresholds for the allreduce chunk step.
SEND_SLOW_MS = 4.0
SEND_FAST_MS = 0.5
#: Decision-trajectory retention (bench JSON export).
TRAJECTORY_CAP = 512


# -- signal extraction (pure functions over a cluster_view dict) --

def merged_sample(view: Dict, name: str, field: str) -> Optional[float]:
    snap = (view.get("samples_merged") or {}).get(name)
    if not snap or field not in snap:
        return None
    return float(snap[field])


def family_sample_max(view: Dict, prefix: str,
                      field: str) -> Optional[float]:
    """Max of ``field`` across every merged sample family instance
    whose name starts with ``prefix`` (``DISPATCH_QUEUE_DEPTH[d`` →
    the deepest destination)."""
    best = None
    for name, snap in (view.get("samples_merged") or {}).items():
        if name.startswith(prefix) and field in snap:
            value = float(snap[field])
            if best is None or value > best:
                best = value
    return best


def monitor_totals(view: Dict, name: str) -> Tuple[int, float]:
    agg = (view.get("monitors_sum") or {}).get(name) or {}
    return int(agg.get("count", 0)), float(agg.get("elapsed_ms", 0.0))


class AutotuneManager:
    """Rank-0 closed-loop knob controller (docs/AUTOTUNE.md).

    Constructed unconditionally with the controller actor (cheap); the
    evaluation thread only starts when ``-autotune_interval_s > 0``.
    ``evaluate``/``tick_once`` are exposed for tests and the bench —
    they run the same code path the thread does.
    """

    def __init__(self, zoo, cluster_metrics) -> None:
        from ..util import configure
        self._zoo = zoo
        self._metrics = cluster_metrics
        self._state_lock = named_lock(f"autotune[r{zoo.rank}].state")
        # Epoch continues from whatever this process already applied:
        # a fresh manager (bench re-init) must outrank the previous
        # run's broadcasts or its first update would be ignored as a
        # replay.
        self._epoch = configure.applied_config_epoch()  # guarded_by: _state_lock
        #: Cumulative knob map (every change ever broadcast): each
        #: broadcast carries the FULL map so a rank that missed an
        #: epoch converges from any later one, and a rejoined rank
        #: re-anchors from a single re-broadcast.
        self._config: Dict[str, Any] = {}  # guarded_by: _state_lock
        # _tick/_streak/_last_change/_prev_counts are tick-thread-only
        # working state (tick_once callers serialize); not annotated.
        self._tick = 0
        self._streak: Dict[str, Tuple[str, int]] = {}
        self._last_change: Dict[str, int] = {}
        self._gauges: Dict[str, Dict] = {}  # guarded_by: _state_lock
        self._acked: Dict[int, int] = {}  # guarded_by: _state_lock
        self._trajectory: collections.deque = collections.deque(  # guarded_by: _state_lock
            maxlen=TRAJECTORY_CAP)
        # Monotonic decision count for the exported counter — the
        # trajectory deque is capped, so its len() would freeze.
        self._decisions_total = 0  # guarded_by: _state_lock
        # Previous cumulative monitor totals, for per-tick deltas.
        self._prev_counts: Dict[str, Tuple[int, float]] = {}
        self._stop_cond = named_condition(f"autotune[r{zoo.rank}].stop")
        self._stopped = False  # guarded_by: _stop_cond
        self._thread: Optional[threading.Thread] = None
        self._policies = {
            "max_get_staleness": self._policy_staleness,
            "replica_hot_rows": self._policy_replica,
            "coalesce_max_msgs": self._policy_coalesce,
            "serving_batch_window_ms": self._policy_batch_window,
            "allreduce_chunk_kb": self._policy_allreduce_chunk,
            "wire_codec_density": self._policy_codec_density,
        }

    # -- lifecycle --
    def start(self) -> None:
        interval = float(get_flag("autotune_interval_s"))
        if interval <= 0 or self._thread is not None:
            return
        self._thread = thread_roles.spawn(
            thread_roles.BACKGROUND, target=self._main,
            args=(interval,), name=f"mv-autotune-r{self._zoo.rank}")

    def stop(self) -> None:
        with self._stop_cond:
            self._stopped = True
            self._stop_cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _main(self, interval: float) -> None:
        while True:
            with self._stop_cond:
                if self._stopped:
                    return
                self._stop_cond.wait(timeout=interval)
                if self._stopped:
                    return
            try:
                self.tick_once()
            except Exception as exc:  # noqa: BLE001 - a bad tick
                # (teardown race, malformed view) loses one decision
                # window, never the controller
                import traceback
                log.error("autotune: tick failed: %s\n%s", exc,
                          traceback.format_exc())

    # -- one evaluation round --
    def tick_once(self) -> Dict[str, Any]:
        """Evaluate every policy against the current cluster view and
        broadcast the changes (if any). Returns the changed-knob map —
        tests and the bench call this directly for determinism."""
        view = self._metrics.cluster_view()
        changes = self.evaluate(view)
        if changes:
            self._broadcast(changes)
        return changes

    def evaluate(self, view: Dict) -> Dict[str, Any]:
        """Policy pass over one cluster view: per-knob verdicts with
        hysteresis, cooldown and guardrail clamping. Updates the
        gauge/trajectory state; returns {knob: new_value} for knobs
        that should change NOW."""
        self._tick += 1
        sig = self._signals(view)
        pinned = {p.strip() for p in
                  str(get_flag("autotune_pin")).split(",") if p.strip()}
        changes: Dict[str, Any] = {}
        for knob, policy in self._policies.items():
            # Canonical-default fallback: a knob whose defining module
            # is not imported in this process (e.g. the allreduce
            # engine in a serving-only deployment) still evaluates.
            cur = get_flag(knob, CANONICAL_FLAGS[knob])
            if knob in pinned:
                # Reset the hysteresis streak too: a pre-pin verdict
                # must not survive the pin as a stale first vote that
                # lets one fresh observation move the knob on unpin.
                self._streak[knob] = ("pinned", 0)
                self._note(knob, cur, "pinned", "operator pin "
                           "(-autotune_pin)")
                continue
            bounds = AUTOTUNE_POLICIES[knob]
            if not bounds["min"] <= cur <= bounds["max"]:
                # The operator configured a value OUTSIDE the policy's
                # band (e.g. -serving_batch_window_ms=0 = batching
                # disabled): clamping it back in would let a "down"
                # verdict RAISE the knob and re-enable what was
                # explicitly turned off. Out-of-band means
                # operator-managed — hands off, like a pin.
                self._streak[knob] = ("unmanaged", 0)
                self._note(knob, cur, "unmanaged",
                           "value outside the policy band "
                           f"[{bounds['min']}, {bounds['max']}] — "
                           "operator-set, not touched")
                continue
            proposed, verdict, reason = policy(cur, sig)
            proposed = self._clamp(knob, proposed, bounds)
            if proposed == cur and verdict in ("up", "down"):
                # Clamped back onto the current value: the knob sits
                # at its guardrail in the proposed direction.
                verdict, reason = "hold", reason + " (at guardrail)"
            if self._gate(knob, verdict):
                changes[knob] = proposed
                self._last_change[knob] = self._tick
                with self._state_lock:
                    self._trajectory.append({
                        "tick": self._tick,
                        "time": round(time.time(), 3),
                        "epoch": self._epoch + 1,
                        "knob": knob, "from": cur, "to": proposed,
                        "verdict": verdict, "reason": reason})
                self._note(knob, proposed, verdict, reason,
                           changed=True)
            else:
                self._note(knob, cur, verdict, reason)
        return changes

    def _clamp(self, knob: str, value: Any, bounds: dict) -> Any:
        lo, hi = bounds["min"], bounds["max"]
        value = min(max(value, lo), hi)
        if isinstance(CANONICAL_FLAGS[knob], int):
            value = int(round(value))
        return value

    def _gate(self, knob: str, verdict: str) -> bool:
        """Hysteresis + cooldown: act only after HYSTERESIS_TICKS
        consecutive same-direction verdicts, and never within
        COOLDOWN_TICKS of the knob's last change."""
        if verdict not in ("up", "down"):
            self._streak[knob] = (verdict, 0)
            return False
        prev, n = self._streak.get(knob, ("", 0))
        n = n + 1 if prev == verdict else 1
        self._streak[knob] = (verdict, n)
        if n < HYSTERESIS_TICKS:
            return False
        if self._tick - self._last_change.get(knob, -10**9) \
                < COOLDOWN_TICKS:
            return False
        return True

    def _note(self, knob: str, value: Any, verdict: str, reason: str,
              changed: bool = False) -> None:
        with self._state_lock:
            ent = self._gauges.setdefault(knob, {"last_epoch": 0})
            ent.update(value=value, verdict=verdict, reason=reason)
            if changed:
                ent["last_epoch"] = self._epoch + 1

    # -- signals --
    def _signals(self, view: Dict) -> Dict[str, Any]:
        """Extract every policy input from one cluster view; monitor
        counters are converted to per-tick deltas against the previous
        view (first tick: all deltas None → every policy holds)."""
        deltas: Dict[str, Optional[Tuple[int, float]]] = {}
        for name in ("WORKER_PROCESS_GET", "WORKER_TABLE_SYNC_GET",
                     "CLIENT_CACHE_HIT", "CLIENT_CACHE_MISS",
                     "REPLICA_REPAIR", "REPLICA_HIT",
                     "SERVER_PROCESS_GET", "tcp_send"):
            total = monitor_totals(view, name)
            prev = self._prev_counts.get(name)
            self._prev_counts[name] = total
            if prev is None or total[0] < prev[0]:
                # First tick, or a counter regression (rank restarted
                # and re-reported from zero): no trustworthy delta.
                deltas[name] = None
            else:
                deltas[name] = (total[0] - prev[0],
                                total[1] - prev[1])

        def delta_count(name: str) -> Optional[int]:
            d = deltas[name]
            return None if d is None else d[0]

        def delta_mean_ms(name: str) -> Optional[float]:
            d = deltas[name]
            if d is None or d[0] <= 0:
                return None
            return d[1] / d[0]

        queue_p90 = max(
            family_sample_max(view, "DISPATCH_QUEUE_DEPTH[", "p90")
            or 0.0,
            family_sample_max(view, "MAILBOX_DEPTH[", "p90") or 0.0)
        return {
            "slo_ms": float(get_flag("autotune_slo_p99_ms")),
            "serving_p99_ms": merged_sample(
                view, "SERVING_LATENCY_MS", "p99"),
            "get_mean_ms": delta_mean_ms("WORKER_TABLE_SYNC_GET"),
            "server_get_mean_ms": delta_mean_ms("SERVER_PROCESS_GET"),
            "get_rate": delta_count("WORKER_PROCESS_GET"),
            "hit_delta": delta_count("CLIENT_CACHE_HIT"),
            "miss_delta": delta_count("CLIENT_CACHE_MISS"),
            "repair_delta": delta_count("REPLICA_REPAIR"),
            "replica_hit_delta": delta_count("REPLICA_HIT"),
            "server_get_delta": delta_count("SERVER_PROCESS_GET"),
            "send_mean_ms": delta_mean_ms("tcp_send"),
            "send_delta": delta_count("tcp_send"),
            "queue_p90": queue_p90,
            "input_density_p50": merged_sample(
                view, "SPARSE_FILL[input]", "p50"),
        }

    # -- per-knob policies --
    def _policy_staleness(self, cur, sig):
        """Widen the client-cache staleness bound while the read p99
        is inside the SLO (trading bounded staleness for locally
        served reads); shrink on violation. Serving p99 when a
        frontend reports; else the mean blocking-Get; else the
        server-side get handling mean (a training-only cluster's
        nearest read-latency signal)."""
        p99 = sig["serving_p99_ms"]
        if p99 is None:
            p99 = sig["get_mean_ms"]
        if p99 is None:
            p99 = sig["server_get_mean_ms"]
        rate = sig["get_rate"]
        if p99 is None or rate is None or rate < MIN_READ_RATE:
            # "idle", not "hold": hold means "judged at its operating
            # point" (consumers like the bench convergence gate key on
            # it); a quiet window judges nothing.
            return cur, "idle", "no read traffic to judge"
        if p99 > sig["slo_ms"]:
            return cur // 2, "down", (
                f"read p99 {p99:.1f}ms over the "
                f"{sig['slo_ms']:.0f}ms SLO")
        hits = sig["hit_delta"] or 0
        misses = sig["miss_delta"] or 0
        if cur > 0 and hits + misses >= MIN_READ_RATE \
                and misses <= 0.05 * (hits + misses):
            return cur, "hold", "cache already absorbing the reads"
        return (cur * 2 if cur else 4), "up", (
            f"read p99 {p99:.1f}ms inside the "
            f"{sig['slo_ms']:.0f}ms SLO with uncached read traffic")

    def _policy_replica(self, cur, sig):
        """Grow the hot-row replica budget when owners are fielding
        repair traffic (hot reads missing their replica floor);
        shrink it back once replica traffic goes quiet."""
        repairs = sig["repair_delta"]
        gets = sig["server_get_delta"]
        if repairs is None or gets is None:
            return cur, "hold", "no report delta yet"
        if repairs >= 8 and repairs > 0.01 * max(gets, 1):
            return max(cur * 2, 64), "up", (
                f"{repairs} repairs against {gets} server gets this "
                f"window")
        if cur > 0 and repairs == 0 \
                and (sig["replica_hit_delta"] or 0) == 0:
            return cur // 2, "down", "replica tier idle this window"
        return cur, "hold", "repair rate nominal"

    def _policy_coalesce(self, cur, sig):
        """Back off the coalescing flush caps while outbound send
        queues sit deep (staged adds behind a deep queue only add
        latency); restore toward the canonical default when
        shallow."""
        depth = sig["queue_p90"]
        default = CANONICAL_FLAGS["coalesce_max_msgs"]
        if depth > QUEUE_DEEP and cur > 8:
            return cur // 2, "down", (
                f"dispatch/mailbox depth p90 {depth:.0f} over "
                f"{QUEUE_DEEP:.0f}")
        if depth < QUEUE_SHALLOW and cur < default:
            return min(cur * 2, default), "up", (
                f"queues shallow (p90 {depth:.0f}); restoring toward "
                f"the default")
        return cur, "hold", f"depth p90 {depth:.0f} in band"

    def _policy_batch_window(self, cur, sig):
        """Back off the serving batch window when the queues behind
        the reads sit deep or the serving p99 violates the SLO (the
        window is pure added latency then); restore toward the
        canonical default when healthy."""
        depth = sig["queue_p90"]
        p99 = sig["serving_p99_ms"]
        default = CANONICAL_FLAGS["serving_batch_window_ms"]
        if depth > QUEUE_DEEP or (p99 is not None
                                  and p99 > sig["slo_ms"]):
            return cur / 2, "down", (
                f"depth p90 {depth:.0f} / serving p99 "
                f"{p99 if p99 is not None else float('nan'):.1f}ms")
        if cur < default and depth < QUEUE_SHALLOW \
                and (p99 is None or p99 < sig["slo_ms"] / 2):
            return min(cur * 2, default), "up", (
                "healthy; restoring toward the default window")
        return cur, "hold", "window at its operating point"

    def _policy_allreduce_chunk(self, cur, sig):
        """Step the allreduce chunk toward the wire's measured
        break-even: long per-frame sends mean the chunk serializes too
        much behind one socket write; very short ones mean per-frame
        overhead dominates."""
        mean = sig["send_mean_ms"]
        if mean is None or (sig["send_delta"] or 0) < 16:
            return cur, "hold", "too few wire sends to judge"
        if mean > SEND_SLOW_MS:
            return cur // 2, "down", (
                f"mean wire send {mean:.2f}ms over "
                f"{SEND_SLOW_MS:.1f}ms")
        if mean < SEND_FAST_MS:
            return cur * 2, "up", (
                f"mean wire send {mean:.2f}ms under "
                f"{SEND_FAST_MS:.1f}ms")
        return cur, "hold", f"mean wire send {mean:.2f}ms in band"

    def _policy_codec_density(self, cur, sig):
        """Track the sparse/dense break-even the collectives actually
        observe: keep the codec's dense-switchover threshold a margin
        above the measured input density, so genuinely sparse traffic
        stays sparse and fill-in switches dense (SparCML's density
        break-even, PAPERS.md)."""
        density = sig["input_density_p50"]
        if density is None:
            return cur, "hold", "no sparse-traffic density samples"
        target = density + 0.15
        if abs(target - cur) <= 0.1:
            return cur, "hold", (
                f"threshold within 0.1 of measured density "
                f"{density:.2f}+margin")
        step = cur + (target - cur) / 2
        return round(step, 3), ("up" if target > cur else "down"), (
            f"measured input density p50 {density:.2f}; stepping "
            f"toward {target:.2f}")

    # -- broadcast (the Control_Shard_Map pattern) --
    def _broadcast(self, changes: Dict[str, Any]) -> None:
        with self._state_lock:
            self._config.update(changes)
            self._epoch += 1
            self._decisions_total += len(changes)
            epoch = self._epoch
            flags = dict(self._config)
        count("AUTOTUNE_DECISION", len(changes))
        log.info("autotune: epoch %d — %s", epoch,
                 {k: changes[k] for k in sorted(changes)})
        self._send_config(epoch, flags)

    def broadcast_current(self) -> None:
        """Re-send the cumulative config at the current epoch — the
        rejoin path: a late-joining (restarted) rank registered with
        construction-time flag values and must re-anchor on the live
        config without waiting for the next knob move. Idempotent
        everywhere else (epoch regression is ignored on apply)."""
        with self._state_lock:
            epoch = self._epoch
            flags = dict(self._config)
        if not flags:
            return
        self._send_config(epoch, flags)

    def _send_config(self, epoch: int, flags: Dict[str, Any]) -> None:
        from ..util.configure import TUNABLE_FLAGS
        bad = sorted(n for n in flags if n not in TUNABLE_FLAGS)
        if bad:  # the broadcast-time rejection, controller side
            raise KeyError(
                f"autotune: refusing to broadcast non-tunable "
                f"flag(s) {bad}")
        payload = json.dumps({"epoch": int(epoch), "flags": flags})
        blob = np.frombuffer(payload.encode(), dtype=np.uint8).copy()
        dead = self._dead_ranks()
        for dst in range(self._zoo.net_size):
            if dst in dead:
                continue  # its rejoin re-register gets a re-broadcast
            msg = Message(src=self._zoo.rank, dst=dst,
                          msg_type=MsgType.Control_Config)
            msg.push(Blob(blob.copy()))
            if dst == self._zoo.rank:
                # Local delivery through the communicator's forward
                # path (a mailbox push, never blocks) — the same
                # routing remote ranks take, so one code path applies
                # configs everywhere.
                self._zoo.send_to(actors.COMMUNICATOR, msg)
                continue
            try:
                self._zoo.net.send_async(msg)
            except Exception as exc:  # noqa: BLE001 - an unreachable
                # rank re-anchors from the next broadcast or its
                # rejoin; its failure must not kill the controller.
                log.debug("autotune: config broadcast to rank %d "
                          "failed: %s", dst, exc)

    def _dead_ranks(self) -> set:
        controller = self._zoo._actors.get(actors.CONTROLLER)
        if controller is None:
            return set()
        with controller._live_lock:
            return set(controller._declared_dead)

    # -- acks / observability --
    def note_ack(self, rank: int, epoch: int) -> None:
        with self._state_lock:
            if epoch >= self._acked.get(rank, -1):
                self._acked[rank] = int(epoch)

    def acked_epochs(self) -> Dict[int, int]:
        with self._state_lock:
            return dict(self._acked)

    @property
    def epoch(self) -> int:
        with self._state_lock:
            return self._epoch

    def trajectory(self) -> List[Dict]:
        """Every applied decision, oldest first (bench JSON export)."""
        with self._state_lock:
            return list(self._trajectory)

    def gauges(self) -> Dict[str, Dict]:
        with self._state_lock:
            return {k: dict(v) for k, v in self._gauges.items()}

    def prometheus_text(self) -> str:
        """The ``mv_autotune_*`` gauge block appended to the
        controller's ``/metrics`` exposition (docs/AUTOTUNE.md):
        config epoch, per-knob current value / last-change epoch /
        verdict, per-rank acked epoch, total decisions."""
        from .metrics import _escape_label, _fmt
        with self._state_lock:
            epoch = self._epoch
            gauges = {k: dict(v) for k, v in self._gauges.items()}
            acked = dict(self._acked)
            decisions = self._decisions_total
        lines = [
            "# HELP mv_autotune_config_epoch latest epoch-stamped "
            "config broadcast by the autotune controller",
            "# TYPE mv_autotune_config_epoch gauge",
            f"mv_autotune_config_epoch {epoch}",
            "# HELP mv_autotune_decisions_total knob changes the "
            "autotune controller has broadcast (monotonic)",
            "# TYPE mv_autotune_decisions_total counter",
            f"mv_autotune_decisions_total {decisions}",
            "# HELP mv_autotune_value current value of an autotuned "
            "knob as the controller last evaluated it",
            "# TYPE mv_autotune_value gauge",
        ]
        for knob in sorted(gauges):
            lines.append(
                f'mv_autotune_value{{knob="{_escape_label(knob)}"}} '
                f'{_fmt(float(gauges[knob].get("value", 0)))}')
        lines += [
            "# HELP mv_autotune_last_epoch config epoch of a knob's "
            "most recent change (0 = never moved)",
            "# TYPE mv_autotune_last_epoch gauge",
        ]
        for knob in sorted(gauges):
            lines.append(
                f'mv_autotune_last_epoch{{knob='
                f'"{_escape_label(knob)}"}} '
                f'{int(gauges[knob].get("last_epoch", 0))}')
        lines += [
            "# HELP mv_autotune_verdict latest policy verdict per "
            "knob (1 on the active verdict label)",
            "# TYPE mv_autotune_verdict gauge",
        ]
        for knob in sorted(gauges):
            verdict = str(gauges[knob].get("verdict", "hold"))
            lines.append(
                f'mv_autotune_verdict{{knob="{_escape_label(knob)}",'
                f'verdict="{_escape_label(verdict)}"}} 1')
        lines += [
            "# HELP mv_autotune_rank_epoch config epoch each rank "
            "last acked (config convergence per rank)",
            "# TYPE mv_autotune_rank_epoch gauge",
        ]
        for rank in sorted(acked):
            lines.append(
                f'mv_autotune_rank_epoch{{rank="{rank}"}} '
                f'{acked[rank]}')
        return "\n".join(lines) + "\n"
