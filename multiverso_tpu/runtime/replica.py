"""Hot-shard read replication (worker/server/controller shared pieces).

Extension over the reference: the paper's row-sharded tables pay a
coordination cost per additional server, yet word2vec Get traffic is
Zipf-skewed ("Sparse Allreduce for Power-Law Data", arxiv 1312.3020, and
SparCML, arxiv 1802.08021 — PAPERS.md), so a handful of HEAD rows
dominate load. This module implements the standard fix: replicate the
head rows for reads.

Protocol (full spec in docs/SHARDING.md):

* every dense matrix server tracks per-row Get rates (``HotTracker``)
  and reports its top rows to the rank-0 controller every
  ``-replica_report_gets`` row-Get requests (``Control_Replica_Report``);
* the controller aggregates the reports with exponential decay,
  promotes the globally hottest ``-replica_hot_rows`` rows (per table)
  and broadcasts a versioned promoted-row map to every rank
  (``Control_Replica_Map``) whenever the set changes — rows that cool
  below the threshold fall out of the map (demotion);
* OWNER servers push value refreshes for their promoted rows to every
  other server (``Request_ReplicaSync``, write-through: Adds apply at
  the owner as always, and the touched promoted rows fan out on the
  next flush), stamped with the owner shard's version;
* holder servers keep the pushed rows in a HOST-side ``ReplicaStore`` —
  serving a replica hit is a numpy gather, no device program and no
  device lock, which is what makes scale-out win on read-heavy
  traffic;
Concurrency note (mvlint pass 10): this module carries NO
``guarded_by`` annotations on purpose — every mutable structure here
is confined to exactly one actor thread (tracker + store on the server
actor, router map on the worker actor, aggregator on the controller
actor; per-class notes below), so there is no lock to annotate
against.

* workers route the replicated subset of a row Get to holders
  (``ReplicaRouter``): a worker co-located with a server prefers its
  LOCAL shard, a pure worker stripes per-row across all servers —
  merged into each holder's own shard request; rows a holder cannot
  serve (sync not yet landed, demotion race) or serves below the
  caller's read-your-writes floor come back short and the worker
  REPAIRS them with a follow-up request to the owner — the protocol is
  self-healing, never wrong.

Staleness is bounded and observable: every replica-served group carries
the owner-version floor of its rows (``REPLICA_SLOT`` + the reply's
replica descriptor, core/message.py), which feeds the same
``VersionTracker``/client-cache machinery as direct replies
(docs/CLIENT_CACHE.md).

BSP sync mode force-disables replication: the sync server's vector
clocks count one request per worker per step PER SERVER, and replica
routing changes which servers observe a Get.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..util.configure import (define_int, get_flag,
                              register_tunable_hook)

define_int("replica_hot_rows", 0,
           "hot-shard read replication budget: the controller promotes "
           "up to this many of the hottest rows PER TABLE to read "
           "replicas on every server (docs/SHARDING.md). 0 (default) "
           "disables replication entirely; BSP sync mode force-disables "
           "it (replica routing would desync the vector clocks)")
define_int("replica_report_gets", 256,
           "a server table reports its hot-row counters to the "
           "controller every this many row-Get requests (smaller = "
           "faster promotion, more control traffic)")
define_int("replica_min_gets", 8,
           "a row must log at least this many Gets (decayed) to be "
           "promotable — keeps one-off rows out of the replica map")
define_int("replica_sync_rows", 8192,
           "max rows per Request_ReplicaSync refresh message (larger "
           "refreshes split)")
define_int("replica_sync_every", 8,
           "write-through flush cadence: an owner fans refreshed values "
           "of its dirty promoted rows to the replica holders every "
           "this many served requests (bounds replica staleness in "
           "requests; the version floors make the actual staleness "
           "observable)")
def replication_enabled() -> bool:
    """Hot-row replication active for this process (read at table
    construction time, like -sparse_compress)."""
    if bool(get_flag("sync", False)):
        return False
    try:
        return int(get_flag("replica_hot_rows", 0)) > 0
    except (TypeError, ValueError):
        return False


#: Dashboard counter/sample names (util/dashboard.py).
REPLICA_HIT = "REPLICA_HIT"          # rows served from a replica store
REPLICA_MISS = "REPLICA_MISS"        # rows a holder could not serve
REPLICA_REPAIR = "REPLICA_REPAIR"    # repair requests issued
REPLICA_STALE = "REPLICA_STALE"      # groups rejected below a RYW floor
REPLICA_SYNC = "REPLICA_SYNC"        # write-through refreshes fanned out


class HotTracker:
    """Per-row Get-rate tracking on a server table.

    ``note`` is O(1) on the serving hot path — it only appends the
    request's id vector to the current window; the per-row counting is
    deferred to ``take_report`` (one vectorized ``np.unique`` per
    cadence), which drains the window, folds it into the decayed
    running counts (halving — exponential decay, so a row that stops
    being read ages out) and returns the hottest rows."""

    def __init__(self, cadence: Optional[int] = None):
        self._counts: Dict[int, float] = {}
        self._window: list = []
        self._gets = 0
        self._cadence = int(cadence if cadence is not None
                            else get_flag("replica_report_gets"))

    def note(self, rows: np.ndarray) -> None:
        self._gets += 1
        # Reference append only — request key vectors are never
        # mutated downstream. A request counts each row once (dedup at
        # fold time would cost here; duplicate ids inside one request
        # are rare and only overweight a row that is hot anyway).
        self._window.append(rows)

    @property
    def due(self) -> bool:
        return self._gets >= max(self._cadence, 1)

    def take_report(self, top_k: int) -> Tuple[np.ndarray, np.ndarray]:
        """(rows, counts) of the hottest ``top_k`` rows this window;
        decays the counters and re-arms the cadence."""
        self._gets = 0
        if self._window:
            uniq, cnt = np.unique(np.concatenate(self._window),
                                  return_counts=True)
            self._window = []
            counts = self._counts
            for r, c in zip(uniq.tolist(), cnt.tolist()):
                counts[r] = counts.get(r, 0.0) + float(c)
        items = sorted(self._counts.items(), key=lambda kv: -kv[1])[:top_k]
        rows = np.array([r for r, _ in items], dtype=np.int32)
        counts_arr = np.array([c for _, c in items], dtype=np.int32)
        # Exponential decay; fully cooled rows leave the dict so the
        # tracker's memory follows the working set, not history.
        self._counts = {r: c / 2.0 for r, c in self._counts.items()
                        if c >= 1.0}
        return rows, counts_arr


class ReplicaStore:
    """Holder-side host store of replicated rows: row id ->
    (value row, owner version, owner sid). Served rows carry per-owner
    version FLOORS (the oldest version among the group's rows) so the
    client's staleness machinery sees replica reads exactly like direct
    reads."""

    def __init__(self):
        self._values: Dict[int, np.ndarray] = {}
        self._version: Dict[int, int] = {}
        self._owner: Dict[int, int] = {}
        #: Last applied sync sequence per owner sid (gap detection).
        self._seq: Dict[int, int] = {}
        #: Lazily rebuilt packed view for ``serve`` — the per-request
        #: hot path must be numpy gathers, not per-row dict loops; the
        #: mutation paths (sync apply, prune, drop) just invalidate and
        #: the rebuild amortizes over the flush cadence.
        self._packed = None

    def __len__(self) -> int:
        return len(self._values)

    def _pack(self, num_col: int, dtype) -> tuple:
        ids = np.asarray(sorted(self._values), dtype=np.int64)
        if ids.size:
            id_list = ids.tolist()
            vals = np.stack([self._values[i] for i in id_list]) \
                .astype(dtype, copy=False)
            ver = np.asarray([self._version[i] for i in id_list],
                             np.int64)
            own = np.asarray([self._owner[i] for i in id_list],
                             np.int64)
        else:
            vals = np.empty((0, num_col), dtype)
            ver = own = np.empty(0, np.int64)
        self._packed = (ids, vals, ver, own)
        return self._packed

    def apply_sync(self, rows: np.ndarray, values: np.ndarray,
                   owner_sid: int, version: int,
                   watermark: bool = False, seq: int = -1) -> None:
        """An owner's refresh push. ``values`` is [len(rows), num_col].
        A refresh must never move a row BACKWARD in version (the owner
        serializes sends per holder). ``watermark=True`` rides the LAST
        chunk of a flush that drained EVERY row the owner dirtied since
        its previous flush: applying it makes every entry of this owner
        current as of ``version`` — without it, a row the adds never
        touch would keep its push-time version forever and read as
        stale against any later read-your-writes floor, even though its
        value is exact.

        ``seq`` is the owner's per-holder send counter. A GAP means a
        chunk toward this holder was lost (dead writer, restart): every
        entry of that owner is dropped BEFORE applying, because a later
        watermark must never certify values a lost chunk should have
        refreshed — dropped rows simply miss and repair to the owner
        (never wrong, at worst repaired). The owner also re-dirties the
        lost chunk's rows (communicator failure path), so the next
        flush restores the entries."""
        self._packed = None
        owner_sid = int(owner_sid)
        if seq >= 0:
            expected = self._seq.get(owner_sid, -1) + 1
            if seq != expected:
                self.drop_owner(owner_sid)
            self._seq[owner_sid] = int(seq)
        for i, r in enumerate(rows.tolist()):
            if self._version.get(r, -1) <= version:
                self._values[r] = np.array(values[i], copy=True)
                self._version[r] = int(version)
                self._owner[r] = owner_sid
        if watermark:
            for r, owner in self._owner.items():
                if owner == owner_sid and self._version[r] < version:
                    self._version[r] = int(version)

    def drop_owner(self, owner_sid: int) -> None:
        self._packed = None
        for r in [r for r, o in self._owner.items() if o == owner_sid]:
            del self._values[r], self._version[r], self._owner[r]

    def prune_to(self, promoted: np.ndarray) -> None:
        """Demotion: drop rows no longer in the map (the worker stops
        routing them on the same map epoch; a racing in-flight Get just
        repairs to the owner)."""
        self._packed = None
        keep = set(promoted.tolist())
        for r in [r for r in self._values if r not in keep]:
            del self._values[r], self._version[r], self._owner[r]

    def serve(self, rows: np.ndarray, num_col: int, dtype
              ) -> Tuple[List[Tuple[int, int, np.ndarray]], np.ndarray,
                         np.ndarray]:
        """Serve ``rows`` (unique ids) from the store.

        Returns ``(groups, served_keys, served_values)`` where groups is
        ``[(owner_sid, floor_version, n_rows), ...]`` (owners ascending)
        and the keys / [n, num_col] values are ordered group-by-group;
        ids not present are simply absent (the worker repairs them to
        the owner). Pure numpy on the packed view — this runs once per
        replica-routed request on the server actor thread."""
        empty = ([], np.empty(0, np.int32), np.empty((0, num_col), dtype))
        packed = self._packed
        if packed is None:
            packed = self._pack(num_col, dtype)
        ids, vals, ver, own = packed
        if ids.size == 0 or rows.size == 0:
            return empty
        pos = np.minimum(np.searchsorted(ids, rows), ids.size - 1)
        hit = ids[pos] == rows
        if not bool(hit.any()):
            return empty
        pos = pos[hit]
        keys = np.asarray(rows[hit], dtype=np.int32)
        owners, versions = own[pos], ver[pos]
        order = np.argsort(owners, kind="stable")  # input order kept
        owners, versions = owners[order], versions[order]
        uniq, starts = np.unique(owners, return_index=True)
        floors = np.minimum.reduceat(versions, starts)
        counts = np.diff(np.append(starts, owners.size))
        groups = [(int(o), int(f), int(c))
                  for o, f, c in zip(uniq, floors, counts)]
        return groups, keys[order], vals[pos[order]]


class ReplicaRouter:
    """Worker-side promoted-row map + holder choice.

    Applied on the worker actor thread (``Control_Replica_Map``
    handler) and read on the same thread (``partition``) — no locking.

    Holder choice (``route``): a worker CO-LOCATED with a server sends
    every replicated row to its local shard — the head then never
    touches the wire at all. A pure worker STRIPES the replicated rows
    across all servers by row id (every server holds every promoted
    row), which balances the Zipf head's bytes across the servers'
    links WITHIN each request — the per-request latency is the slowest
    shard's paced link, so an all-to-one-holder choice would leave the
    request gated by whichever server got the whole head. The chosen
    server's own rows ride the same shard message, so replica routing
    adds at most the messages a uniform tail already required."""

    def __init__(self, num_servers: int, salt: int = 0,
                 preferred: Optional[int] = None):
        self.epoch = -1
        self._rows: Optional[np.ndarray] = None  # sorted promoted rows
        self._num_servers = max(int(num_servers), 1)
        self._salt = int(salt)
        self._preferred = preferred if preferred is not None \
            and 0 <= int(preferred) < self._num_servers else None
        # Holders declared dead (Control_Dead_Peer): ``route`` returns
        # -1 for rows striped to them and the partition falls back to
        # the rows' OWNERS — a dead holder must not turn replicated
        # reads into retry loops against a corpse while the owner is
        # alive. A server is re-included when any reply from it lands
        # (``mark_alive`` via the reply context), and — the
        # authoritative path — whenever an epoch-stamped map broadcast
        # carries the controller's live-server view (``reconcile``):
        # before that, a rejoined server that got no organic reply
        # traffic stayed dead-marked indefinitely and its replicas
        # went unserved. After a rejoin its replica store is empty, so
        # resumed routing just misses and repairs until the owner's
        # pushes rebuild it — self-healing.
        self._dead: set = set()
        #: Resharding supersedes replication for a table: once its
        #: shard map goes dynamic, ownership moves absorb the skew and
        #: the static row->owner arithmetic the replica protocol
        #: assumes is gone (docs/SHARDING.md). A deactivated router
        #: ignores later promoted-row broadcasts.
        self._disabled = False

    @property
    def active(self) -> bool:
        return self._rows is not None and self._rows.size > 0

    @property
    def rows(self) -> Optional[np.ndarray]:
        return self._rows

    def apply(self, epoch: int, rows: np.ndarray) -> bool:
        """Adopt a broadcast map; stale epochs (reordered delivery) are
        ignored."""
        if self._disabled or epoch <= self.epoch:
            return False
        self.epoch = int(epoch)
        rows = np.asarray(rows, dtype=np.int32).reshape(-1)
        self._rows = np.sort(rows) if rows.size else None
        return True

    def replicated_mask(self, keys: np.ndarray) -> np.ndarray:
        if not self.active:
            return np.zeros(keys.shape, dtype=bool)
        idx = np.searchsorted(self._rows, keys)
        idx = np.minimum(idx, self._rows.size - 1)
        return self._rows[idx] == keys

    def mark_dead(self, sid: int) -> None:
        if 0 <= int(sid) < self._num_servers:
            self._dead.add(int(sid))

    def mark_alive(self, sid: int) -> None:
        self._dead.discard(int(sid))

    def deactivate(self) -> None:
        """Permanently retire this router (the table's shard map went
        dynamic — ownership moves supersede read replicas)."""
        self._disabled = True
        self._rows = None

    def reconcile(self, alive_sids) -> None:
        """Re-validate the dead marks against the controller's
        authoritative live-server view (carried on every epoch-stamped
        map broadcast): servers the controller considers alive resume
        receiving striped reads WITHOUT waiting for organic reply
        traffic, and servers it declared dead are marked even if no
        local send ever failed toward them."""
        alive = {int(s) for s in alive_sids}
        if not alive:
            return  # pre-liveness broadcast: keep local knowledge
        self._dead = {s for s in range(self._num_servers)
                      if s not in alive}

    def route(self, rows: np.ndarray) -> np.ndarray:
        """Holder server id per (replicated) row, or -1 where the
        chosen holder is declared dead (the caller falls back to the
        row's owner): the co-located shard when this rank hosts one,
        else a per-row stripe (salted so sibling workers shift
        phase)."""
        if self._preferred is not None:
            # The preferred holder is this rank's own shard — it cannot
            # be dead while this worker runs.
            return np.full(rows.shape, self._preferred, dtype=np.int64)
        out = (rows.astype(np.int64) + self._salt) % self._num_servers
        if self._dead:
            out[np.isin(out, np.asarray(sorted(self._dead)))] = -1
        return out


class ServerReplicaState:
    """Per-server-table replica bookkeeping (server actor thread only;
    built by dense matrix shards when ``replication_enabled()``).

    Combines the three server roles of the protocol: every server
    TRACKS the Get rate of the rows it serves (owned or replica-held —
    each request for a row lands on exactly one server, so the
    controller's aggregation over all reports preserves global counts
    and promotion cannot flap when routing moves the head to holders);
    a HOLDER keeps the pushed rows in ``store``; an OWNER remembers
    which of its rows are promoted and which of those an Add dirtied
    since the last write-through flush."""

    def __init__(self, row_offset: int, my_rows: int):
        self._row_offset = int(row_offset)
        self._my_rows = int(my_rows)
        self.tracker = HotTracker()
        self.store = ReplicaStore()
        self.epoch = -1
        self._own_promoted = np.empty(0, np.int32)  # sorted global ids
        self._dirty: set = set()  # dirty own promoted rows (global ids)
        self._served = 0
        self._sync_every = max(int(get_flag("replica_sync_every")), 1)
        self._report_top = max(2 * int(get_flag("replica_hot_rows")), 16)
        #: Owner shard version as of the last watermark-carrying sync
        #: (the table compares against its live version to decide
        #: whether a watermark-only refresh is worth a message).
        self.last_sync_version = -1
        #: Per-holder Request_ReplicaSync send counters (gap detection
        #: on the holder side; see ``next_sync_seq``).
        self._sync_seq: Dict[int, int] = {}
        # Live retuning (docs/AUTOTUNE.md): the controller-side budget
        # (ReplicaCoordinator) reads -replica_hot_rows fresh per
        # report, but this reporter cached its window size here — the
        # hook re-sizes it so a grown budget sees enough candidates.
        register_tunable_hook("replica_hot_rows",
                              self._retune_budget)

    def _retune_budget(self, value) -> None:
        self._report_top = max(2 * int(value), 16)

    def note_get(self, rows: np.ndarray) -> None:
        if rows.size:
            self.tracker.note(rows)

    def note_add(self, rows: np.ndarray) -> None:
        """Host row Add applied at this owner: promoted rows among them
        go dirty (refreshed to the holders on the next flush)."""
        if not self._own_promoted.size or not rows.size:
            return
        idx = np.searchsorted(self._own_promoted, rows)
        idx = np.minimum(idx, self._own_promoted.size - 1)
        self._dirty.update(
            rows[self._own_promoted[idx] == rows].tolist())

    def note_add_all(self) -> None:
        """Whole-table or device-key Add (ids unenumerable on the
        host): conservatively dirty every own promoted row."""
        self._dirty.update(self._own_promoted.tolist())

    def redirty(self, rows: np.ndarray) -> None:
        """A sync chunk toward some holder was lost (communicator
        failure echo, server actor thread): its rows go back in the
        dirty set so the next flush re-pushes them toward EVERY holder
        (redundant for healthy ones, restorative for the one that
        missed). Rows demoted since the send stay out."""
        keep = set(self._own_promoted.tolist())
        self._dirty.update(r for r in rows.tolist() if r in keep)

    def next_sync_seq(self, holder_sid: int) -> int:
        """Per-holder send counter for Request_ReplicaSync (the holder
        drops this owner's entries on a gap — a lost chunk must not be
        papered over by a later watermark)."""
        seq = self._sync_seq.get(int(holder_sid), 0)
        self._sync_seq[int(holder_sid)] = seq + 1
        return seq

    def apply_map(self, epoch: int, rows: np.ndarray) -> np.ndarray:
        """Adopt a promoted-row map broadcast. Returns the rows the
        owner must push NOW: the newly promoted own rows UNION the
        drained dirty set — the push carries a version watermark, which
        is only sound when no dirtied row is left out of it. Prunes
        holder entries for demoted rows."""
        rows = np.asarray(rows, dtype=np.int32).reshape(-1)
        if epoch <= self.epoch:
            return np.empty(0, np.int32)
        self.epoch = int(epoch)
        lo = self._row_offset
        own = np.sort(rows[(rows >= lo) & (rows < lo + self._my_rows)])
        new = np.setdiff1d(own, self._own_promoted)
        self._own_promoted = own
        keep = set(own.tolist())
        pending = np.asarray(sorted(r for r in self._dirty if r in keep),
                             dtype=np.int32)
        self._dirty.clear()
        self.store.prune_to(rows)
        return np.union1d(new, pending)

    def take_due_sync(self) -> Optional[np.ndarray]:
        """Every ``-replica_sync_every`` served requests: the dirty own
        promoted rows to refresh (drained; possibly EMPTY — the caller
        still sends a watermark-only refresh when its shard version
        advanced past ``last_sync_version``), else None."""
        self._served += 1
        if self._served % self._sync_every or not self._own_promoted.size:
            return None
        rows = np.asarray(sorted(self._dirty), dtype=np.int32)
        self._dirty.clear()
        return rows

    def take_due_report(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        if not self.tracker.due:
            return None
        rows, counts = self.tracker.take_report(self._report_top)
        if rows.size == 0:
            return None
        return rows, counts


# -- Control_Replica_Report / Control_Replica_Map payload helpers --
#
# Report: msg.table_id names the table; blob 0 = int32 rows, blob 1 =
# int32 counts (same length). Map: blob 0 = int32
# [epoch, n_tables, (table_id, n_rows) * n]; blobs 1..n = one int32 row
# vector per table, in descriptor order.

def pack_replica_map(epoch: int, promoted: Dict[int, np.ndarray],
                     alive_sids=None) -> List[np.ndarray]:
    """``alive_sids`` (trailing blob, absent on older payloads) is the
    controller's authoritative live-server view: routers reconcile
    their dead marks against it on every broadcast, so a rejoined
    server resumes serving replicas without waiting for organic
    traffic (docs/SHARDING.md)."""
    desc = [int(epoch), len(promoted)]
    rows_blobs: List[np.ndarray] = []
    for table_id in sorted(promoted):
        rows = np.asarray(promoted[table_id], dtype=np.int32).reshape(-1)
        desc.extend((int(table_id), int(rows.size)))
        rows_blobs.append(rows)
    blobs = [np.asarray(desc, dtype=np.int32)] + rows_blobs
    if alive_sids is not None:
        blobs.append(np.asarray(sorted(int(s) for s in alive_sids),
                                dtype=np.int32))
    return blobs


def unpack_replica_map(blobs) -> Tuple[int, Dict[int, np.ndarray]]:
    epoch, promoted, _alive = unpack_replica_map_alive(blobs)
    return epoch, promoted


def unpack_replica_map_alive(blobs):
    """(epoch, promoted, alive_sids-or-None) — the alive vector is the
    trailing blob when the sender packed one."""
    desc = blobs[0]
    epoch, n_tables = int(desc[0]), int(desc[1])
    promoted: Dict[int, np.ndarray] = {}
    for i in range(n_tables):
        table_id = int(desc[2 + 2 * i])
        promoted[table_id] = np.asarray(blobs[1 + i],
                                        dtype=np.int32).reshape(-1)
    alive = None
    if len(blobs) > 1 + n_tables:
        alive = np.asarray(blobs[1 + n_tables],
                           dtype=np.int32).reshape(-1)
    return epoch, promoted, alive


class ReplicaCoordinator:
    """Controller-side aggregation of hot-row reports into the
    promoted-row map (runs on the rank-0 controller actor thread).

    Per table the coordinator keeps decayed global counts; every
    ingested report decays the table's counts and merges the server's
    window. The promoted set is the hottest ``-replica_hot_rows`` rows
    with a decayed count of at least ``-replica_min_gets``; any CHANGE
    to any table's set bumps the epoch and triggers a fresh broadcast
    (the caller sends it)."""

    def __init__(self):
        self._counts: Dict[int, Dict[int, float]] = {}
        self._promoted: Dict[int, np.ndarray] = {}
        self._reporters: Dict[int, set] = {}
        self.epoch = 0

    def ingest(self, table_id: int, rows: np.ndarray,
               counts: np.ndarray, reporter: int = -1) -> bool:
        """Returns True when the promoted map changed (re-broadcast)."""
        budget = int(get_flag("replica_hot_rows"))
        if budget <= 0:
            return False
        table = self._counts.setdefault(int(table_id), {})
        # Decay once per report ROUND, not per report: each server
        # reports independently, so a per-report decay would halve a
        # row's count num_servers times between consecutive reports
        # from its serving server — the effective decay rate would
        # scale with the server count, crushing every row toward the
        # promotion threshold exactly when there are many servers (a
        # repeat reporter marks the next round).
        seen = self._reporters.setdefault(int(table_id), set())
        if reporter in seen:
            seen.clear()
            for r in list(table):
                table[r] /= 2.0
                if table[r] < 0.5:
                    del table[r]
        seen.add(reporter)
        for r, c in zip(rows.tolist(), counts.tolist()):
            table[r] = table.get(r, 0.0) + float(c)
        threshold = float(get_flag("replica_min_gets"))
        old_set = set(self._promoted.get(int(table_id),
                                         np.empty(0, np.int32)).tolist())
        # Promotion is deliberately STICKY, two ways: an incumbent stays
        # promotable at HALF the admission threshold, and when the
        # budget is full a hotter challenger does NOT evict — rows leave
        # only by cooling below the retention threshold. Without both,
        # boundary rows swap in and out on per-report count noise, and
        # every swap costs a map broadcast plus the owner's initial
        # value push to every holder — measured at ~20% of the hot
        # owner's paced link in the N-server bench before this policy.
        incumbents = sorted(
            (r for r, c in table.items()
             if r in old_set and c >= threshold / 2.0),
            key=lambda r: -table[r])[:budget]
        challengers = sorted(
            (r for r, c in table.items()
             if r not in old_set and c >= threshold),
            key=lambda r: -table[r])[:max(budget - len(incumbents), 0)]
        new = np.sort(np.asarray(incumbents + challengers,
                                 dtype=np.int32))
        old = self._promoted.get(int(table_id))
        if old is not None and np.array_equal(old, new):
            return False
        if old is None and new.size == 0:
            return False
        self._promoted[int(table_id)] = new
        self.epoch += 1
        return True

    @property
    def promoted(self) -> Dict[int, np.ndarray]:
        return self._promoted
