"""Process-wide serialization of multi-device dispatch (multi-zoo mode).

XLA's CPU runtime executes dispatched computations on a small shared
thread pool (sized to the host's cores — ONE on the bench container).
A multi-device program (8 virtual CPU shards) can partially occupy the
pool; two such programs in flight from different threads can each hold
resources the other needs and wedge forever. One zoo per process (the
real deployment) serializes naturally through the actor mailboxes and
never hits this; a LocalFabric process hosting SEVERAL virtual ranks
(tests, single-host multi-rank runs) does — observed as a server-side
jitted gather parked forever while a sibling rank's trainer program was
still in flight (test_ps_device_pipeline_two_workers, and the
server-vs-server variant PR 1 fixed with ``Server._table_lock``).

The fix generalizes PR 1's lock: while ``enable()`` is active (entered
by ``LocalCluster.run`` for n > 1), EVERY multi-device dispatch site —
server table jits, worker partition slicing, trainer step programs —
takes the ONE process lock and ``settle``s its outputs before releasing
it, so at most one device program is in flight at any moment and none
escapes its critical section still executing. With no multi-zoo process
active, ``guard()`` is a no-op context and ``settle`` returns its
argument untouched — the real deployment keeps full async pipelining.
"""

from __future__ import annotations

import contextlib

from ..util.lock_witness import named_lock, named_rlock

#: The one process-wide device-dispatch lock. ``Server._table_lock`` is
#: this object (kept as a class attribute for its existing callers).
#: RLock: the sync server's drain paths re-enter through Server._process_*.
#: Witnessed only when -debug_locks is set before this module first
#: imports (module-level singleton; see util/lock_witness.py).
TABLE_LOCK = named_rlock("device_lock.TABLE_LOCK")

_NULL = contextlib.nullcontext()
_serialized = 0  # nesting count of active multi-zoo contexts
_state_lock = named_lock("device_lock.state")


def _single_device() -> bool:
    """The wedge class this lock exists for is CONCURRENT MULTI-DEVICE
    programs: each such program partially occupies XLA's shared CPU
    execution pool waiting on inter-device rendezvous, and two in
    flight can each hold resources the other needs. A process whose
    platform exposes exactly ONE device never builds those programs —
    its dispatches are ordinary single-device executions, which JAX
    supports from concurrent threads — so serializing (and settling,
    which kills async pipelining) would only cost throughput. Tests run
    under the 8-virtual-device conftest mesh and therefore KEEP the
    lock; a plain CPU/one-chip bench process drops it. Computed lazily
    (jax import cost) and cached: the device count never changes
    mid-process."""
    global _single_device_cached
    if _single_device_cached is None:
        import jax
        _single_device_cached = len(jax.devices()) == 1
    return _single_device_cached


_single_device_cached = None


def enable() -> None:
    """Enter multi-zoo mode: serialize + settle all device dispatch
    (no-op on single-device processes — see ``_single_device``)."""
    global _serialized
    if _single_device():
        return
    with _state_lock:
        _serialized += 1


def disable() -> None:
    global _serialized
    if _single_device():
        return
    with _state_lock:
        _serialized -= 1


def active() -> bool:
    return _serialized > 0


def guard():
    """Context manager for a device-dispatch site: the process lock in
    multi-zoo mode, a no-op otherwise."""
    return TABLE_LOCK if _serialized else _NULL


def settle(tree):
    """Block until every device array in ``tree`` has materialized
    (multi-zoo mode only; identity otherwise). Call INSIDE the guarded
    region, on its outputs, so no execution escapes the lock."""
    if _serialized:
        import jax
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()
    return tree
