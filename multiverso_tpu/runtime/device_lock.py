"""Process-wide serialization of multi-device dispatch (multi-zoo mode).

XLA's CPU runtime executes dispatched computations on a small shared
thread pool (sized to the host's cores — ONE on the bench container).
A multi-device program (8 virtual CPU shards) can partially occupy the
pool; two such programs in flight from different threads can each hold
resources the other needs and wedge forever. One zoo per process (the
real deployment) serializes naturally through the actor mailboxes and
never hits this; a LocalFabric process hosting SEVERAL virtual ranks
(tests, single-host multi-rank runs) does — observed as a server-side
jitted gather parked forever while a sibling rank's trainer program was
still in flight (test_ps_device_pipeline_two_workers, and the
server-vs-server variant PR 1 fixed with ``Server._table_lock``).

The fix generalizes PR 1's lock: while ``enable()`` is active (entered
by ``LocalCluster.run`` for n > 1), EVERY multi-device dispatch site —
server table jits, worker partition slicing, trainer step programs —
takes the ONE process lock and ``settle``s its outputs before releasing
it, so at most one device program is in flight at any moment and none
escapes its critical section still executing. With no multi-zoo process
active, ``guard()`` is a no-op context and ``settle`` returns its
argument untouched — the real deployment keeps full async pipelining.
"""

from __future__ import annotations

import contextlib

from ..util.lock_witness import named_lock, named_rlock

#: The one process-wide device-dispatch lock. ``Server._table_lock`` is
#: this object (kept as a class attribute for its existing callers).
#: RLock: the sync server's drain paths re-enter through Server._process_*.
#: Witnessed only when -debug_locks is set before this module first
#: imports (module-level singleton; see util/lock_witness.py).
TABLE_LOCK = named_rlock("device_lock.TABLE_LOCK")

_NULL = contextlib.nullcontext()
_serialized = 0  # nesting count of active multi-zoo contexts
_state_lock = named_lock("device_lock.state")


def enable() -> None:
    """Enter multi-zoo mode: serialize + settle all device dispatch."""
    global _serialized
    with _state_lock:
        _serialized += 1


def disable() -> None:
    global _serialized
    with _state_lock:
        _serialized -= 1


def active() -> bool:
    return _serialized > 0


def guard():
    """Context manager for a device-dispatch site: the process lock in
    multi-zoo mode, a no-op otherwise."""
    return TABLE_LOCK if _serialized else _NULL


def settle(tree):
    """Block until every device array in ``tree`` has materialized
    (multi-zoo mode only; identity otherwise). Call INSIDE the guarded
    region, on its outputs, so no execution escapes the lock."""
    if _serialized:
        import jax
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()
    return tree
