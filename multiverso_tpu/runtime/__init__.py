"""Runtime: actors, transport, zoo, virtual clusters.

TPU-native re-design of the reference's actor system
(ref: src/zoo.cpp, src/actor.cpp, src/communicator.cpp, src/controller.cpp,
src/worker.cpp, src/server.cpp).
"""

from .actor import Actor  # noqa: F401
from .cluster import LocalCluster  # noqa: F401
from .net import LocalFabric, LocalNet, NetInterface  # noqa: F401
from .zoo import Zoo, current_zoo, set_default_zoo, set_thread_zoo  # noqa: F401
