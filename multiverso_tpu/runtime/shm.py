"""Shared-memory transport for co-located ranks: the zero-copy story
*below* the socket.

PR 15 removed every Python-level copy from the wire path, but same-host
peers still pushed each frame through kernel loopback — two syscalls
and two kernel copies per frame. This module finishes the job: frames
between co-located ranks travel through a per-directed-pair ring of
fixed slots in POSIX shared memory (``multiprocessing.shared_memory``),
written by the sender's writer thread and consumed in place by the
receiver's event loop — **one** ``memoryview`` copy total (producer
side, into the slot) and **zero** syscalls on the data path. A doorbell
FIFO per receiver (one nonblocking byte after each frame, drained on
the selector) replaces the old busy-polling consumer thread: an idle
pair costs a parked ``selector.select``, not CPU.

Architecture (see docs/MEMORY.md "Below the socket"):

- :class:`ShmNet` wraps a :class:`~.tcp.TcpNet`. TCP stays fully live:
  bootstrap, the ``Control_Register`` handshake, frames to remote or
  non-shm peers, and — critically — peer-death detection (the TCP
  reader's dirty-close path is the doorbell that retires rings).
- Transport selection is negotiated at registration exactly like the
  PR-1 codec-capability bit: each rank advertises :data:`CAP_SHM` plus
  a host fingerprint in its register blob; the controller broadcasts
  the per-rank host ids and a cluster-wide random *token*, and the zoo
  calls :meth:`ShmNet.enable_shm` with the set of same-host capable
  peers. A ``-shm=0`` rank advertises nothing and is simply never
  ring-addressed — mixed clusters interoperate frame for frame.
- The **send side** is negotiated; the **receive side** is
  announce-driven and needs no negotiation state at all. The sender
  creates its outbound segment lazily on its writer thread at first
  ring send, then sends a ``Control_Shm_Announce`` frame *over TCP*
  carrying ``[nonce, token]``. ``TcpNet.send`` flushes the
  destination's TCP writer first, so the announce orders after every
  frame already queued — the receiver attaches the segment when the
  announce arrives and nothing can overtake the transport switch.
  This asymmetry matters: a later-registering rank must be able to
  consume the controller's ring-borne ``Control_Reply_Register``
  *before* its own negotiation completes.

Ring layout (one segment per directed pair, name
``mvshm-{token:08x}-{src}-{dst}``)::

    [ring header 64B: magic, version, nslots, slot_bytes, nonce]
    [slot control x nslots, 64B stride: state | flags, nbytes, total]
    [slot payloads x nslots, 64-byte aligned, slot_bytes each]

A slot's control word is ``state`` (0=FREE, 1=READY) packed *last* on
write and read *first* on consume; the metadata (flags/nbytes/total/
seq) lands before the state flips. CPython's eval loop plus x86-TSO
store ordering make the plain packs sufficient — there is no torn-read
window a peer can observe. Slots do NOT recycle in FIFO order: the
writer claims any FREE slot and the consumer locates the next frame by
its ``seq`` stamp, so a slot pinned by a consumer-held frame is walked
around instead of waited on (without this, one long-held frame would
stall the whole ring at wraparound).

Ownership reuses the PR-15 ``BufferPool`` lease discipline unchanged:
a frame that fits one slot is parsed in place —
``tcp._deserialize_frame`` cuts read-only Blob views straight into the
shared slot, with a :class:`_SlotLease` riding the Blobs. When the
last Blob dies the lease checks its *weak references* to the frame's
backing numpy arrays; a survivor (a user-held view pins its base
array) makes the slot *park* instead of freeing (the ring
service re-probes), so a blob outliving everything can never alias a recycled
slot. A blob outliving the whole segment is safe too: ``shm.close()``
with live exports raises ``BufferError`` and the mapping moves to a
module graveyard instead of unmapping.

Ring exhaustion degrades, never deadlocks: the writer blocks with the
same ``-send_queue_mb`` bounded backpressure as the TCP writer, spins
with escalating sleeps on a full ring, logs once a second, and raises
:class:`~.net.PeerLostError` the moment the ring is closed under it.
Frames larger than one slot stream as chunked slot sequences (CONT
flag) and are reassembled into a pooled lease on the receive side —
one extra copy, counted in ``SHM_BYTES_COPIED``, never a stall. And a
consumer that sits on delivered frames (an out-of-order stash, a slow
actor) can pin at most HALF the ring: past that, ``consume`` copies
frames out through the pool (``SHM_PIN_COPIES``) so the writer always
progresses.
"""

from __future__ import annotations

import atexit
import collections
import os
import selectors
import socket as _socket
import struct
import threading
import time
import weakref
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.blob import Blob
from ..core.message import Message, MsgType
from ..util import chaos, log
from ..util.configure import define_bool, define_int, get_flag
from ..util.dashboard import count, monitor
from ..util.lock_witness import named_condition, named_lock
from . import thread_roles
from .net import NetInterface, PeerLostError
from .tcp import _LEN, TcpNet, _deserialize_frame, _frame_views

try:  # POSIX shared memory; absent on exotic builds — gate, don't crash
    from multiprocessing import resource_tracker, shared_memory
except ImportError:  # pragma: no cover - non-POSIX fallback
    resource_tracker = None
    shared_memory = None

try:
    import _posixshmem  # the raw unlink syscall, without tracker side effects
except ImportError:  # pragma: no cover - non-POSIX fallback
    _posixshmem = None

define_bool("shm", True,
            "shared-memory transport for co-located ranks: frames "
            "between same-host peers travel through per-pair shm rings "
            "(one memoryview copy, zero syscalls) instead of kernel "
            "loopback; negotiated per peer at registration like the "
            "wire-codec capability bit, TCP kept for remote peers. "
            "0 = advertise nothing and stay on TCP everywhere")
define_int("shm_ring_slots", 16,
           "slots per outbound shm ring (per directed peer pair); a "
           "full ring blocks the writer thread with bounded "
           "backpressure, it never deadlocks or drops")
define_int("shm_slot_kb", 512,
           "payload bytes per shm ring slot (KB); a frame that fits "
           "one slot is consumed zero-copy in place, a larger frame "
           "streams across slots and is reassembled through the "
           "receive pool (one extra copy, counted in SHM_BYTES_COPIED)")

#: Capability bit advertised in the Control_Register blob (PR-1 codec
#: negotiation precedent: util/wire_codec.py CAP_WIRE_CODEC = 1).
CAP_SHM = 2

_RING_MAGIC = 0x4D565348  # "MVSH"
_RING_VERSION = 1
#: Segment header: magic, version, nslots, slot_bytes, nonce.
_RING_HDR = struct.Struct("<IIIIQ")
#: Per-slot control, split on purpose: the metadata struct (flags,
#: nbytes, total, seq — at control offset +4) is packed BEFORE the
#: state word (at +0) flips to READY, and consumers read state first.
#: ``seq`` is the writer's absolute slot counter: a slot can sit READY
#: long after the consumer moved past it (an in-place Blob view holds
#: it until the lease dies), so on wraparound READY alone is
#: ambiguous — the consumer only takes a slot whose seq matches its
#: own absolute position.
_SLOT_STATE = struct.Struct("<I")
_SLOT_META = struct.Struct("<IQQQ")
_SLOT_STRIDE = 64
_ALIGN = 64
_CTRL_OFF = 64  # header rounded up to one cache line

_FREE = 0
_READY = 1
_F_CONT = 1  # more chunks of this frame follow in later slots


def supported() -> bool:
    """POSIX shared memory available on this build?"""
    return shared_memory is not None and _posixshmem is not None


def host_fingerprint() -> int:
    """Same-host detector for the register handshake: hostname plus the
    kernel boot id (two containers sharing a hostname but not /dev/shm
    differ in boot id on distinct kernels; same-kernel containers with
    private shm namespaces are out of scope — ``-shm=0`` is the
    escape hatch). Fits an int32 register slot."""
    ident = _socket.gethostname()
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            ident += f.read().strip()
    except OSError:  # pragma: no cover - no procfs
        pass
    return zlib.crc32(ident.encode()) & 0x7FFFFFFF


def _seg_name(token: int, src: int, dst: int) -> str:
    return f"mvshm-{token & 0xFFFFFFFF:08x}-{src}-{dst}"


def _bell_name(token: int, rank: int) -> str:
    """Doorbell FIFO name for ``rank``'s receive side. The mvshm-
    prefix keeps it inside the lifecycle-hygiene sweep (tests scan
    /dev/shm for leftovers by that prefix), and ``_unlink_name``'s raw
    shm_unlink removes /dev/shm entries regardless of file type."""
    return f"mvshm-bell-{token & 0xFFFFFFFF:08x}-{rank}"


def _untrack(shm) -> None:
    """Opt this mapping out of the multiprocessing resource tracker.
    The tracker would unlink every registered segment at interpreter
    exit *and* print leak warnings — but segment lifetime is OURS
    (creator unlinks on retire/finalize; survivors reap a dead peer's
    names), and the tracker registers on attach too, so a reader
    exiting first would unlink a ring its peer still writes. Exactly
    one unregister per create/attach — a second one trips tracker
    KeyError noise on stderr."""
    if resource_tracker is not None:
        try:
            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:  # noqa: BLE001 - tracker noise must not kill IO
            pass


def _unlink_name(name: str) -> None:
    """Unlink a segment by name without touching the tracker (the
    ``SharedMemory.unlink`` method would double-unregister)."""
    _created_names.discard(name)
    if _posixshmem is None:  # pragma: no cover - non-POSIX fallback
        return
    try:
        _posixshmem.shm_unlink("/" + name)
    except (FileNotFoundError, OSError):
        pass


#: Segment names THIS process created and has not yet unlinked. The
#: atexit reap below is the last line of the lifecycle-hygiene defence:
#: a process that dies by unhandled exception never reaches
#: ``ShmNet.finalize``, and with the resource tracker opted out
#: (:func:`_untrack`) nothing else would unlink its rings. atexit does
#: not run under ``os._exit``/SIGKILL — those cases are covered by the
#: survivor/rejoin reaps (``drop_connection``/``finalize``/
#: ``_OutRing.create``'s FileExistsError path). GIL-atomic set ops;
#: no lock needed for add/discard of interned names.
_created_names: set = set()


def _atexit_reap() -> None:  # pragma: no cover - exercised in tests
    for name in list(_created_names):
        _unlink_name(name)


atexit.register(_atexit_reap)


#: Mappings that could not unmap because a Blob still views them (a
#: consumer kept a zero-copy view past transport teardown). Parking
#: the SharedMemory object keeps the pages mapped, so the view stays
#: valid forever instead of faulting — the memory-safety half of the
#: "blob outlives the segment" contract. Bounded in practice by how
#: many rings a process tears down while holding live views.
_graveyard: List = []


def _pay_off(nslots: int) -> int:
    off = _CTRL_OFF + nslots * _SLOT_STRIDE
    return (off + _ALIGN - 1) & ~(_ALIGN - 1)


class _SlotLease:
    """Slot ownership token riding the Blobs cut from one in-place
    frame — the shared-segment twin of ``buffer_pool.FrameLease``.

    A ``memoryview.release()`` probe cannot prove liveness here: numpy
    acquires the buffer through its *own* internal memoryview, so
    releasing the parsed body never raises even while Blob arrays are
    alive. Instead the lease weak-tracks the numpy arrays backing the
    frame's Blobs (:meth:`watch`, armed by ``consume`` right after
    ``_deserialize_frame``). Every user-held view derives from one of
    those arrays and pins it through its ``base`` chain, so a dead
    weakref set proves no export survives. Release with a survivor
    parks the slot (the ring service re-probes) instead of freeing it, so a
    long-lived Blob never aliases a recycled slot."""

    __slots__ = ("_ring", "_slot", "_watch")

    def __init__(self, ring: "_InRing", slot: int):
        self._ring = ring
        self._slot = slot
        self._watch: Tuple = ()

    def watch(self, arrays) -> None:
        """Arm the liveness probe over the frame's backing arrays."""
        self._watch = tuple(weakref.ref(a) for a in arrays)

    def exports_alive(self) -> bool:
        return any(r() is not None for r in self._watch)

    def release(self) -> None:
        ring, self._ring = self._ring, None
        if ring is None:
            return  # idempotent
        if self.exports_alive():
            # A Blob array (or a user view pinning it) is still alive:
            # the slot must not recycle under it. Park; the ring service
            # frees it once the last weakref clears.
            ring._park(self._slot, self)
            return
        self._watch = ()
        ring._free_inplace(self._slot)

    def __del__(self):
        self.release()


class _OutRing:
    """The sender's half of one directed ring: created on the writer
    thread at first ring send, unlinked by the creator on retire."""

    def __init__(self, name: str, shm, nslots: int, slot_bytes: int,
                 nonce: int):
        self.name = name
        self.nonce = nonce
        self._shm = shm
        self._nslots = nslots
        self._slot_bytes = slot_bytes
        pay = _pay_off(nslots)
        self._pay = [shm.buf[pay + i * slot_bytes:
                             pay + (i + 1) * slot_bytes]
                     for i in range(nslots)]
        self._head = 0  # absolute frame/chunk seq (writer-thread only)
        self._scan = 0  # round-robin slot-scan start (writer-thread only)
        # Closed flag: flipped by retire/finalize (any thread), polled
        # by the writer inside _acquire_slot. A plain bool — one racy
        # read at worst delays the PeerLostError by one spin iteration.
        self._closed = False

    @classmethod
    def create(cls, token: int, src: int, dst: int) -> "_OutRing":
        nslots = max(2, int(get_flag("shm_ring_slots")))
        slot_bytes = max(4096, int(get_flag("shm_slot_kb")) << 10)
        name = _seg_name(token, src, dst)
        size = _pay_off(nslots) + nslots * slot_bytes
        try:
            shm = shared_memory.SharedMemory(name=name, create=True,
                                             size=size)
        except FileExistsError:
            # Stale leftover from a SIGKILL'd predecessor of this rank:
            # reap it and claim the name (the rejoin path). Receivers
            # match segments by announced nonce, never by name alone.
            _unlink_name(name)
            shm = shared_memory.SharedMemory(name=name, create=True,
                                             size=size)
        _untrack(shm)
        _created_names.add(name)  # atexit reap if we die before destroy
        # A fresh POSIX segment is zero-filled: every slot starts FREE.
        nonce = int.from_bytes(os.urandom(8), "little") >> 1
        _RING_HDR.pack_into(shm.buf, 0, _RING_MAGIC, _RING_VERSION,
                            nslots, slot_bytes, nonce)
        return cls(name, shm, nslots, slot_bytes, nonce)

    def _acquire_slot(self) -> int:
        """Claim ANY free slot, round-robin preferred — slots do NOT
        recycle in FIFO order: a slot pinned by a consumer-held frame
        is skipped, not waited on (the ``seq`` stamp in the metadata
        carries delivery order, and the consumer's pin valve bounds
        pins to half the ring, so a FREE slot always reappears). When
        every slot is busy this blocks — the bounded-backpressure half
        of the no-deadlock contract: a slow reader stalls this writer
        thread (never a caller; callers are already capped by
        -send_queue_mb in submit), with a once-a-second log and a
        typed PeerLostError if the ring closes under the wait (peer
        declared dead)."""
        buf = self._shm.buf
        spins = 0
        waited = False
        next_warn = 0.0
        while True:
            if self._closed:
                raise PeerLostError(
                    f"shm ring {self.name}: peer ring closed while "
                    f"waiting for a free slot")
            for probe in range(self._nslots):
                slot = (self._scan + probe) % self._nslots
                off = _CTRL_OFF + slot * _SLOT_STRIDE
                (state,) = _SLOT_STATE.unpack_from(buf, off)
                if state == _FREE:
                    self._scan = (slot + 1) % self._nslots
                    return slot
            if not waited:
                waited = True
                count("SHM_RING_FULL_WAITS")
                next_warn = time.monotonic() + 1.0
            elif time.monotonic() >= next_warn:
                next_warn = time.monotonic() + 1.0
                log.info("shm ring %s full: backpressure on a slow "
                         "reader (%d slots x %d KB)", self.name,
                         self._nslots, self._slot_bytes >> 10)
            spins += 1
            if spins < 20:
                time.sleep(0)  # reader is usually one GIL slice away
            else:
                time.sleep(min(0.00005 * spins, 0.001))

    def write_frame(self, views: List[memoryview], nbytes: int) -> None:
        """Copy one serialized frame into ring slots — THE one copy of
        the shm data path. ``views`` is the ``_frame_views`` list;
        the wire length prefix is dropped (slot metadata carries
        sizes), so the slot body is exactly the TCP frame body and
        ``tcp._deserialize_frame`` parses it unchanged. Frames larger
        than one slot stream as CONT-chained chunks; the reader frees
        chunk slots as it copies them out, so even a frame larger than
        the whole ring flows."""
        total = nbytes - _LEN.size
        slot_bytes = self._slot_bytes
        nchunks = max(1, -(-total // slot_bytes))
        flat: List[memoryview] = []
        head = views[0][_LEN.size:]
        if head.nbytes:
            flat.append(head)
        for v in views[1:]:
            if not (v.format == "B" and v.ndim == 1):
                v = v.cast("B")
            flat.append(v)
        buf = self._shm.buf
        vi = 0
        vo = 0
        for chunk in range(nchunks):
            slot = self._acquire_slot()
            off = _CTRL_OFF + slot * _SLOT_STRIDE
            pay = self._pay[slot]
            room = min(slot_bytes, total - chunk * slot_bytes)
            woff = 0
            while woff < room:
                v = flat[vi]
                take = min(room - woff, v.nbytes - vo)
                pay[woff:woff + take] = v[vo:vo + take]
                woff += take
                vo += take
                if vo == v.nbytes:
                    vi += 1
                    vo = 0
            flags = _F_CONT if chunk < nchunks - 1 else 0
            # Metadata first, READY last: the consumer's load of READY
            # is its license to read the metadata and the payload.
            _SLOT_META.pack_into(buf, off + 4, flags, room, total,
                                 self._head)
            _SLOT_STATE.pack_into(buf, off, _READY)
            self._head += 1
        if nchunks > 1:
            count("SHM_CHUNKED_FRAMES")
        count("SHM_FRAMES")
        count("SHM_BYTES", total)

    def request_close(self) -> None:
        self._closed = True

    def destroy(self, unmap: bool = True) -> None:
        """Unlink the segment (creator's duty) and drop the mapping.
        ``unmap=False`` when the writer thread could still be touching
        the buffer (failed join): the mapping parks on the graveyard
        and the fields stay intact so a straggling write faults
        nowhere."""
        self._closed = True
        _unlink_name(self.name)
        if not unmap:
            _graveyard.append(self._shm)
            return
        shm, self._shm = self._shm, None
        if shm is None:
            return
        self._pay = None
        try:
            shm.close()
        except BufferError:  # pragma: no cover - sender keeps no exports
            _graveyard.append(shm)


class _InRing:
    """The receiver's half: attached by the ring service when the announce
    arrives, consumed in place, closed (never unlinked — the creator
    owns the name) on retire."""

    def __init__(self, name: str, shm, nslots: int, slot_bytes: int,
                 nonce: int):
        self.name = name
        self.nonce = nonce
        self._shm = shm
        self._nslots = nslots
        self._slot_bytes = slot_bytes
        pay = _pay_off(nslots)
        self._pay = [shm.buf[pay + i * slot_bytes:
                             pay + (i + 1) * slot_bytes]
                     for i in range(nslots)]
        self._tail = 0  # next slot to consume (loop-thread only)
        self._lock = named_lock(f"shm.in[{name}]")
        self._closed = False  # guarded_by: _lock
        self._parked: List[Tuple[int, "_SlotLease"]] = []  # guarded_by: _lock
        self._inplace = 0  # outstanding in-place leases; guarded_by: _lock
        self._chunk = None  # chunked-frame assembly lease (loop only)
        self._chunk_off = 0

    @classmethod
    def attach(cls, name: str, nonce: int) -> Optional["_InRing"]:
        """Attach by name, validating magic/version/nonce — None on any
        mismatch (caller retries: the announce always postdates the
        create, so a miss is a dead peer or a superseded segment)."""
        try:
            shm = shared_memory.SharedMemory(name=name, create=False)
        except (FileNotFoundError, ValueError, OSError):
            return None
        _untrack(shm)
        if shm.size < _RING_HDR.size:
            shm.close()
            return None
        magic, version, nslots, slot_bytes, seg_nonce = \
            _RING_HDR.unpack_from(shm.buf, 0)
        if (magic != _RING_MAGIC or version != _RING_VERSION
                or seg_nonce != nonce or nslots < 1
                or shm.size < _pay_off(nslots) + nslots * slot_bytes):
            shm.close()
            return None
        return cls(name, shm, nslots, slot_bytes, nonce)

    def consume(self, pool, deliver, budget: int = 32) -> int:
        """Drain up to ``budget`` READY frames, delivering parsed
        Messages through ``deliver`` (the inner TcpNet inbox — one
        queue keeps blocking recv and per-src FIFO intact). Single-slot
        frames parse IN PLACE: the Blob views alias the slot and a
        _SlotLease holds it READY until they die. Chunked frames copy
        out into a pooled lease (SHM_BYTES_COPIED).

        Pinned-slot pressure valve: once live consumer-held frames pin
        half the ring (a stashing consumer — the allreduce engine's
        out-of-order stash is the canonical case — or a slow actor),
        further frames COPY out through the pool instead of parsing in
        place (SHM_PIN_COPIES). Copied slots free immediately, so the
        writer always makes progress — without this, a consumer that
        stashes ``nslots`` undelivered frames pins every slot and
        deadlocks the pair."""
        done = 0
        buf = self._shm.buf
        while done < budget:
            # The writer claims ANY free slot (_acquire_slot), so the
            # next frame in delivery order — seq == _tail — can sit in
            # any slot: scan for it, starting at the FIFO guess (the
            # hit on the first probe whenever nothing is pinned). A
            # READY slot with an older seq is a still-pinned in-place
            # frame; skip it.
            guess = self._tail % self._nslots
            slot = None
            for probe in range(self._nslots):
                cand = (guess + probe) % self._nslots
                off = _CTRL_OFF + cand * _SLOT_STRIDE
                (state,) = _SLOT_STATE.unpack_from(buf, off)
                if state != _READY:
                    continue
                flags, nbytes, total, seq = _SLOT_META.unpack_from(
                    buf, off + 4)
                if seq == self._tail:
                    slot = cand
                    break
            if slot is None:
                break
            self._tail += 1
            if (flags & _F_CONT) or self._chunk is not None:
                # Oversize frame: reassemble through the receive pool.
                if self._chunk is None:
                    self._chunk = pool.lease(total)
                    self._chunk_off = 0
                lease = self._chunk
                view = lease.view(total)
                view[self._chunk_off:self._chunk_off + nbytes] = \
                    self._pay[slot][:nbytes]
                view = None
                count("SHM_BYTES_COPIED", nbytes)
                self._chunk_off += nbytes
                self._free(slot)  # copied out: recycle immediately
                if not (flags & _F_CONT):
                    self._chunk = None
                    with monitor("shm_recv"):
                        msg = _deserialize_frame(lease.view(total), lease)
                    deliver(msg)
                    done += 1
                continue
            with self._lock:
                crowded = self._inplace >= max(1, self._nslots // 2)
                if not crowded:
                    self._inplace += 1
            if crowded:
                # Pressure valve: copy out so the slot frees now and
                # the writer keeps flowing (docstring above).
                lease = pool.lease(nbytes)
                view = lease.view(nbytes)
                view[:] = self._pay[slot][:nbytes]
                view = None
                count("SHM_PIN_COPIES")
                count("SHM_BYTES_COPIED", nbytes)
                self._free(slot)
                with monitor("shm_recv"):
                    msg = _deserialize_frame(lease.view(nbytes), lease)
                lease = None
                deliver(msg)
                done += 1
                continue
            # In-place path: _deserialize_frame cuts numpy views
            # straight into the slot body; the lease weak-tracks their
            # backing arrays, and the slot stays READY until every one
            # (and every user view pinning one) is dead.
            body = self._pay[slot][:nbytes]
            lease = _SlotLease(self, slot)
            with monitor("shm_recv"):
                msg = _deserialize_frame(body, lease)
            lease.watch([b._data for b in msg.data])
            body = None
            lease = None
            deliver(msg)
            done += 1
        return done

    def _free(self, slot: int) -> None:
        with self._lock:
            if self._closed:
                return
            _SLOT_STATE.pack_into(self._shm.buf,
                                  _CTRL_OFF + slot * _SLOT_STRIDE, _FREE)

    def _free_inplace(self, slot: int) -> None:
        """Free from a dying _SlotLease: also retires its pinned-slot
        count (parked slots stay counted — still pinned)."""
        with self._lock:
            self._inplace -= 1
            if self._closed:
                return
            _SLOT_STATE.pack_into(self._shm.buf,
                                  _CTRL_OFF + slot * _SLOT_STRIDE, _FREE)

    def _park(self, slot: int, lease: "_SlotLease") -> None:
        count("SHM_SLOT_PARKED")
        with self._lock:
            if self._closed:
                return  # retire already moved the mapping to safety
            self._parked.append((slot, lease))

    def reprobe_parked(self) -> None:
        """Poller duty: retry parked slots — once the last Blob array
        dies its weakref clears and the slot frees."""
        with self._lock:
            if self._closed or not self._parked:
                return
            still: List[Tuple[int, "_SlotLease"]] = []
            for slot, lease in self._parked:
                if lease.exports_alive():
                    still.append((slot, lease))
                    continue
                self._inplace -= 1
                _SLOT_STATE.pack_into(self._shm.buf,
                                      _CTRL_OFF + slot * _SLOT_STRIDE,
                                      _FREE)
            self._parked = still

    def retire(self) -> None:
        """Close the mapping (the creator unlinks the name). A live
        Blob view makes ``close`` raise BufferError — the mapping then
        parks on the graveyard so the view stays valid forever."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._pay = None
            self._parked = []
            chunk, self._chunk = self._chunk, None
            shm, self._shm = self._shm, None
        if chunk is not None:
            chunk.release()
        try:
            shm.close()
        except BufferError:
            _graveyard.append(shm)


class _ShmPeerWriter:
    """Per-destination ring writer thread + bounded frame queue (same
    queue discipline, -send_queue_mb backpressure, and parked-error
    contract as the TCP transport's ``_Peer`` queues — but a dedicated
    WRITER thread, because a full ring legitimately BLOCKS the producer
    in ``_acquire_slot``'s spin, which the event loop must never do).
    The ring segment is created lazily on THIS thread at the first
    frame, and the TCP-borne announce goes out just before it — so
    ring frames can never overtake the pre-ring TCP stream. After each
    frame the writer rings the receiver's doorbell FIFO, which wakes
    the peer's event loop out of ``selector.select`` — no busy-polling
    consumer on the other side."""

    def __init__(self, net: "ShmNet", dst: int):
        self._net = net
        self._dst = dst
        self._cond = named_condition(f"shm[r{net.rank}].writer[d{dst}]")
        self._frames: collections.deque = collections.deque()  # guarded_by: _cond
        self._queued_bytes = 0  # guarded_by: _cond
        self._writing = False  # guarded_by: _cond
        self._closed = False  # guarded_by: _cond
        self.error: Optional[BaseException] = None  # guarded_by: _cond
        self._ring: Optional[_OutRing] = None  # writer thread; read post-join
        self._thread = thread_roles.spawn(
            thread_roles.WRITER, target=self._main,
            name=f"mv-shm-write-r{net.rank}-d{dst}")

    def submit(self, views: List[memoryview], nbytes: int) -> None:
        cap = max(1, int(get_flag("send_queue_mb"))) << 20
        with self._cond:
            while (self._queued_bytes >= cap and self.error is None
                   and not self._closed):
                self._cond.wait(timeout=1.0)
            if self.error is not None:
                raise PeerLostError(
                    f"send to rank {self._dst} failed: peer shm ring "
                    f"is dead ({self.error})") from self.error
            if self._closed:
                raise RuntimeError("ShmNet finalized")
            self._frames.append((views, nbytes))
            self._queued_bytes += nbytes
            self._cond.notify_all()

    def flush(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while (self._frames or self._writing) and self.error is None:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise RuntimeError(
                        f"flush_sends: {self._queued_bytes} bytes to rank "
                        f"{self._dst} not drained within {timeout}s")
                self._cond.wait(timeout=1.0 if remaining is None
                                else min(remaining, 1.0))
            if self.error is not None:
                raise PeerLostError(
                    f"send to rank {self._dst} failed: peer shm ring "
                    f"is dead ({self.error})") from self.error

    @property
    def queued_bytes(self) -> int:
        with self._cond:
            return self._queued_bytes

    def retire(self, timeout: float = 2.0) -> None:
        """Stop accepting frames, unblock a ring-full wait, join, and
        destroy the out-ring (unlink; unmap only if the thread really
        finished — else the mapping parks on the graveyard)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        ring = self._ring
        if ring is not None:
            ring.request_close()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=timeout)
        ring = self._ring
        if ring is not None:
            ring.destroy(unmap=not self._thread.is_alive())

    def _main(self) -> None:
        while True:
            with self._cond:
                while not self._frames and not self._closed:
                    self._cond.wait()
                if not self._frames:  # closed and drained
                    return
                views, nbytes = self._frames.popleft()
                self._writing = True
            try:
                ring = self._ring
                if ring is None:
                    ring = self._ring = self._net._open_ring(self._dst)
                with monitor("shm_send"):
                    ring.write_frame(views, nbytes)
                self._net._count_sent(nbytes)
                self._net._ding(self._dst)
            except BaseException as exc:  # noqa: BLE001 - no caller to
                # raise into: park the error, wake waiters — submit()
                # and flush() turn it into PeerLostError.
                with self._cond:
                    self.error = exc
                    self._frames.clear()
                    self._queued_bytes = 0
                    self._writing = False
                    self._cond.notify_all()
                return
            # Drop the views BEFORE parking: they alias payload buffers
            # (possibly a pooled frame being forwarded) and an idle
            # writer must not pin them until the next send.
            views = None
            with self._cond:
                self._queued_bytes -= nbytes
                self._writing = False
                self._cond.notify_all()


class _ShmBell:
    """Receiver-side doorbell: a named FIFO in /dev/shm that senders
    write one byte to after stamping a ring slot READY. Registered on
    the inner TcpNet's event loop, so a co-located peer's frame wakes
    this rank's loop out of ``selector.select`` — the ring consumer
    went from a busy-polling BACKGROUND thread to an fd on the same
    selector every socket lives on. The payload is meaningless; the
    readiness edge is the signal, and a full FIFO just means a ding is
    already pending."""

    def __init__(self, net: "ShmNet", name: str):
        self._net = net
        self.name = name
        path = "/dev/shm/" + name
        try:
            os.mkfifo(path)
        except FileExistsError:
            # Stale leftover from a SIGKILL'd predecessor of this rank
            # (the rejoin path): reap it and claim the name.
            _unlink_name(name)
            os.mkfifo(path)
        _created_names.add(name)  # atexit reap if we die before finalize
        # O_RDWR (not O_RDONLY) on our own FIFO: the Linux trick that
        # keeps one writer reference alive forever, so a sender closing
        # its end can never leave the read side at EOF (a persistently
        # readable fd would spin the selector).
        self.fd = os.open(path, os.O_RDWR | os.O_NONBLOCK)

    def on_misc_io(self, mask: int) -> None:
        while True:
            try:
                chunk = os.read(self.fd, 4096)
            except (BlockingIOError, OSError):
                break
            if not chunk:
                break
        self._net._ring_service()

    def close(self) -> None:
        try:
            os.close(self.fd)
        except OSError:
            pass


class ShmNet(NetInterface):
    """A TcpNet wrapped with per-peer shared-memory rings for
    co-located ranks. Remote and non-shm peers, bootstrap, control
    handshakes and peer-death detection all stay on the inner TCP
    mesh; only negotiated same-host data frames switch transports."""

    def __init__(self, tcp: TcpNet):
        self._tcp = tcp
        self._loop = tcp._loop  # ring service rides the TCP event loop
        rank = tcp.rank
        self._lifecycle = named_lock(f"shm[r{rank}].lifecycle")
        self._stats_lock = named_lock(f"shm[r{rank}].stats")
        self._writers: Dict[int, _ShmPeerWriter] = {}  # guarded_by: _lifecycle
        self._closed = False  # guarded_by: _lifecycle
        self._token: Optional[int] = None  # guarded_by: _lifecycle
        self._shm_bytes = 0  # guarded_by: _stats_lock
        # Negotiated co-located peer set (static after enable_shm) and
        # the live ring-send target set. GIL-atomic set/dict ops by
        # design: the one race — a send routed to TCP right as a ring
        # peer (re)appears, or to a ring right as a peer dies — is
        # benign either way (TCP always works; a dead ring raises the
        # same PeerLostError the TCP path would).
        self._shm_peers: frozenset = frozenset()
        self._ring_peers: set = set()
        self._announced: Dict[int, Tuple[int, int]] = {}  # src -> (nonce, token)
        self._attached: Dict[int, _InRing] = {}  # loop-thread only
        self._dead: set = set()  # srcs whose in-ring the service must retire
        self._reaped: Dict[int, str] = {}  # dead peers' segment names
        self._reaped_bells: Dict[int, str] = {}  # dead peers' bell names
        # Doorbell state. _bell and the service bookkeeping below are
        # loop-thread only; _bell_fds maps dst -> cached O_WRONLY fd of
        # the PEER's bell, touched by that dst's writer thread (and
        # closed by drop_connection only after the writer is joined).
        self._bell: Optional[_ShmBell] = None
        self._bell_fds: Dict[int, int] = {}
        self._attach_retry: Dict[int, float] = {}  # loop-thread only
        self._svc_stopped = False  # loop-thread only
        self._timer_armed = False  # loop-thread only
        self._idle_delay = 0.001  # loop-thread only

    # -- NetInterface delegation --
    @property
    def rank(self) -> int:
        return self._tcp.rank

    @property
    def size(self) -> int:
        return self._tcp.size

    @property
    def bytes_sent(self) -> int:
        with self._stats_lock:
            mine = self._shm_bytes
        return mine + self._tcp.bytes_sent

    def _count_sent(self, nbytes: int) -> None:
        with self._stats_lock:
            self._shm_bytes += nbytes

    @property
    def on_peer_lost(self):
        # The inner TCP readers are the death detector; the hook lives
        # there so dirty closes fire it directly.
        return self._tcp.on_peer_lost

    @on_peer_lost.setter
    def on_peer_lost(self, hook) -> None:
        self._tcp.on_peer_lost = hook

    # -- negotiation --
    def enable_shm(self, token: int, peers) -> None:
        """Zoo callback after the register reply: ``peers`` is the set
        of same-host ranks that advertised CAP_SHM; ``token`` is the
        controller-chosen cluster constant naming every segment.
        Configures the SEND side only — receiving is announce-driven
        and needs no state here (a later-registering rank consumes the
        controller's ring before its own negotiation completes)."""
        mine = frozenset(int(p) for p in peers if int(p) != self.rank)
        with self._lifecycle:
            if self._closed:
                return
            self._token = int(token)
            self._shm_peers = mine
        for p in mine:
            self._ring_peers.add(p)
        if mine:
            # Kick the ring service so our doorbell FIFO exists before
            # the first peer ding (a miss is covered by the fallback
            # timer, but the bell makes delivery latency selector-fast
            # from frame one).
            self._loop.call_soon(self)
            log.info("shm transport enabled: rank %d ring-sends to %s "
                     "(token %08x)", self.rank, sorted(mine),
                     int(token) & 0xFFFFFFFF)

    def is_shm_peer(self, dst: int) -> bool:
        """Is traffic toward ``dst`` currently ring-routed? (The
        communicator skips the wire codec below the socket.)"""
        return dst in self._ring_peers

    # -- send path --
    def send(self, msg: Message) -> int:
        dst = msg.dst
        if dst not in self._ring_peers:
            return self._tcp.send(msg)
        writer = self._writer(dst)
        with monitor("tcp_serialize"):
            views, nbytes = _frame_views(msg)
        # One queue per destination keeps sync frames FIFO with queued
        # async ones; the flush makes this blocking like TcpNet.send.
        writer.submit(views, nbytes)
        writer.flush(timeout=60.0)
        return nbytes

    def send_async(self, msg: Message) -> int:
        dst = msg.dst
        if dst not in self._ring_peers:
            return self._tcp.send_async(msg)
        # Chaos harness parity (-chaos_frames): ring sends pass the
        # same fault filter as TCP ones — the inner send_async applies
        # it for delegated frames, so filter only on the ring branch.
        faulted = chaos.filter_frames(msg)
        if faulted is not None:
            total = 0
            for m in faulted:
                total += self._submit_ring(m)
            return total
        return self._submit_ring(msg)

    def _submit_ring(self, msg: Message) -> int:
        dst = msg.dst
        if dst not in self._ring_peers:  # a held chaos frame may outlive
            return self._tcp.send_async(msg)  # the peer's ring
        with monitor("tcp_serialize"):
            views, nbytes = _frame_views(msg)
        self._writer(dst).submit(views, nbytes)
        return nbytes

    def _writer(self, dst: int) -> _ShmPeerWriter:
        writer = self._writers.get(dst)  # mvlint: ignore[guarded-by]
        if writer is None:
            with self._lifecycle:
                if self._closed:
                    raise RuntimeError("ShmNet finalized")
                writer = self._writers.get(dst)
                if writer is None:
                    writer = self._writers[dst] = _ShmPeerWriter(self, dst)
        return writer

    def _open_ring(self, dst: int) -> _OutRing:
        """Writer-thread duty: create the outbound segment and send
        the TCP-borne announce. ``TcpNet.send`` flushes the
        destination's TCP writer first, so the announce — and with it
        the transport switch — orders after every frame already queued
        toward ``dst`` over TCP."""
        with self._lifecycle:
            if self._closed:
                raise RuntimeError("ShmNet finalized")
            token = self._token
        if token is None:
            raise RuntimeError("shm ring send before negotiation")
        ring = _OutRing.create(token, self.rank, dst)
        try:
            ann = Message(src=self.rank, dst=dst,
                          msg_type=MsgType.Control_Shm_Announce)
            ann.push(Blob(np.array([ring.nonce, token], dtype=np.int64)))
            self._tcp.send(ann)
        except BaseException:
            ring.destroy(unmap=True)
            raise
        log.debug("shm ring %s created (%d -> %d)", ring.name,
                  self.rank, dst)
        return ring

    def flush_sends(self, dst: Optional[int] = None,
                    timeout: Optional[float] = None) -> None:
        with self._lifecycle:
            writers = [self._writers[dst]] if dst is not None \
                and dst in self._writers else \
                (list(self._writers.values()) if dst is None else [])
        for writer in writers:
            writer.flush(timeout)
        self._tcp.flush_sends(dst, timeout)

    def queue_depths(self) -> Dict[int, int]:
        """Outbound frames queued per destination, ring and TCP paths
        combined (the same introspection port TcpNet exposes)."""
        with self._lifecycle:
            writers = list(self._writers.items())
        depths = self._tcp.queue_depths()
        for dst, writer in writers:
            with writer._cond:
                depths[dst] = depths.get(dst, 0) + len(writer._frames) \
                    + (1 if writer._writing else 0)
        return depths

    # -- receive path --
    def recv(self, timeout: Optional[float] = None) -> Optional[Message]:
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            remaining = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            msg = self._tcp.recv(timeout=remaining)
            if msg is None:
                return None
            if msg.type_int == int(MsgType.Control_Shm_Announce):
                # Transport-internal: consumed here, below the
                # communicator — actor routing never sees it.
                self._on_announce(msg)
                continue
            return msg

    def deliver(self, msg: Message) -> None:
        """Poller delivery port (LocalFabric precedent): ring frames
        join the same inbox TCP frames land in, preserving blocking
        recv and per-source FIFO."""
        self._tcp.deliver(msg)

    def _on_announce(self, msg: Message) -> None:
        src = msg.src
        vals = msg.data[0].as_array(np.int64)
        nonce, token = int(vals[0]), int(vals[1])
        self._announced[src] = (nonce, token)
        # The announce proves the peer's send side is enabled — after a
        # rejoin this is what re-adds it to OUR ring-send set (the
        # negotiated set is static; membership in it is the consent).
        if src in self._shm_peers:
            self._ring_peers.add(src)
        self._reaped.pop(src, None)  # it rejoined: nothing to reap
        self._loop.call_soon(self)  # service attaches the new ring

    # -- ring service (event-loop thread) --
    def on_misc_timer(self) -> None:
        """Loop-job entry: announce kicks and enable_shm land here via
        call_soon(self)."""
        self._ring_service()

    def _timer_fire(self) -> None:
        self._timer_armed = False
        self._ring_service()

    def _ring_service(self) -> None:
        """One service pass on the event loop — the old poller's loop
        body: attach announced rings (with per-src backoff), retire
        dead ones, consume READY frames in place, re-probe parked
        slots. Normally woken by the doorbell FIFO; an adaptive
        fallback timer (1ms busy, decaying to 50ms idle) covers what no
        doorbell announces — attach retries, parked-slot lease deaths,
        and dings lost before the bell existed."""
        if self._svc_stopped:
            return
        busy = False
        self._ensure_bell()
        now = time.monotonic()
        # Attach newly announced (or re-announced after rejoin) rings.
        # The announce postdates the create, so a miss means a dead
        # peer or a superseded segment — retry with backoff until the
        # announce table says otherwise.
        for src, (nonce, token) in list(self._announced.items()):
            ring = self._attached.get(src)
            if ring is not None and ring.nonce == nonce:
                continue
            if ring is not None:  # peer rebuilt its segment
                self._attached.pop(src, None)
                ring.retire()
            if now < self._attach_retry.get(src, 0.0):
                continue
            new = _InRing.attach(_seg_name(token, src, self.rank), nonce)
            if new is None:
                self._attach_retry[src] = now + 0.02
                continue
            self._attach_retry.pop(src, None)
            self._attached[src] = new
            busy = True
        while self._dead:
            src = self._dead.pop()
            self._announced.pop(src, None)
            ring = self._attached.pop(src, None)
            if ring is not None:
                ring.retire()
        for src, ring in list(self._attached.items()):
            if ring.consume(self._tcp._pool, self._tcp.deliver):
                busy = True
            ring.reprobe_parked()
        self._idle_delay = 0.001 if busy \
            else min(self._idle_delay * 2, 0.05)
        if not self._timer_armed and (self._announced or self._attached
                                      or self._dead):
            self._timer_armed = True
            self._loop.call_later(self._idle_delay, self._timer_fire)

    def _ensure_bell(self) -> None:
        if self._bell is not None:
            return
        with self._lifecycle:
            token = self._token
        if token is None:
            # Receive side enabled by an inbound announce alone (our
            # own enable_shm still in flight): any announced token IS
            # the cluster token.
            for _nonce, t in self._announced.values():
                token = t
                break
        if token is None:
            return
        try:
            bell = _ShmBell(self, _bell_name(token, self.rank))
        except OSError:  # pragma: no cover - no FIFO support in
            return  # /dev/shm: the fallback timer alone serves rings
        self._bell = bell
        self._loop.register(bell.fd, selectors.EVENT_READ, bell)

    def _ding(self, dst: int) -> None:
        """Writer-thread duty, right after a frame's slots flip READY:
        one byte into the receiver's doorbell FIFO so its event loop
        wakes now instead of at the next fallback tick. Every failure
        mode is quietly survivable — the receiver's timer covers a
        missing or torn-down bell, and a full FIFO means a ding is
        already pending."""
        fd = self._bell_fds.get(dst)
        if fd is None:
            with self._lifecycle:
                token = self._token
            if token is None:
                return
            path = "/dev/shm/" + _bell_name(token, dst)
            try:
                fd = os.open(path, os.O_WRONLY | os.O_NONBLOCK)
            except OSError:
                return  # bell not up (yet): ENXIO/ENOENT
            self._bell_fds[dst] = fd
        try:
            os.write(fd, b"\0")
        except BlockingIOError:
            pass  # FIFO full: the pending ding covers this frame too
        except OSError:
            # Receiver closed its bell (teardown or rejoin): drop the
            # cached fd so the next frame re-opens the new one.
            stale = self._bell_fds.pop(dst, None)
            if stale is not None:
                try:
                    os.close(stale)
                except OSError:
                    pass

    def interrupt_recv(self) -> None:
        self._tcp.interrupt_recv()

    # -- peer death / lifecycle --
    def drop_connection(self, dst: int) -> None:
        """Peer declared dead: retire its ring state on both sides and
        fall back to TCP-only toward it until a fresh announce proves
        it rejoined. The dead peer's own inbound segment is NOT
        unlinked here — a rejoining replacement recreates the same
        name, and racing its create is worse than deferring the reap
        to finalize (only peers that never rejoin are reaped then)."""
        self._ring_peers.discard(dst)
        ann = self._announced.pop(dst, None)
        with self._lifecycle:
            writer = self._writers.pop(dst, None)
        if writer is not None:
            writer.retire(timeout=1.0)
        # The writer is joined: its cached doorbell fd toward the dead
        # peer is safe to close here, and the dead peer's bell name is
        # recorded for the finalize reap (it never unlinks here — a
        # rejoining replacement recreates the same name).
        fd = self._bell_fds.pop(dst, None)
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass
        if ann is not None:
            self._reaped[dst] = _seg_name(ann[1], dst, self.rank)
            self._reaped_bells[dst] = _bell_name(ann[1], dst)
        self._dead.add(dst)  # the ring service retires the in-ring
        self._loop.call_soon(self)
        self._tcp.drop_connection(dst)

    def finalize(self) -> None:
        with self._lifecycle:
            already = self._closed
            self._closed = True
            writers, self._writers = dict(self._writers), {}
        if already:
            self._tcp.finalize()  # inner finalize is idempotent too
            return
        for writer in writers.values():
            pending = writer.queued_bytes
            drain = 2.0 + pending / (4 << 20)
            try:
                writer.flush(timeout=drain)
            except (RuntimeError, PeerLostError):
                pass
            writer.retire()
        # Writers are joined: the cached doorbell fds are dead weight.
        for fd in list(self._bell_fds.values()):
            try:
                os.close(fd)
            except OSError:
                pass
        self._bell_fds.clear()
        # Retire the attached rings and our own bell ON the loop (they
        # are loop-thread state; the inner TcpNet is not finalized yet,
        # so the loop is still serving).
        self._loop.run_sync(self._teardown_rings, timeout=5.0)
        # Reap every inbound segment we know of — both the recorded
        # dead-peer names AND every announced name. A peer that died
        # without ever reaching drop_connection (the abort path raises
        # ClusterAborted straight into shutdown) left its out-segment
        # linked with nobody else to unlink it; a live peer's own
        # destroy turns our unlink into a handled FileNotFoundError
        # (whichever side unlinks first wins, the name is dead either
        # way, and unlink never invalidates an established mapping). A
        # leaked /dev/shm entry outliving the cluster is the one
        # failure mode the lifecycle-hygiene tests treat as fatal.
        # Dead peers' doorbell FIFOs are reaped the same way.
        for src, (nonce, token) in list(self._announced.items()):
            _unlink_name(_seg_name(token, src, self.rank))
            # The announcer's doorbell FIFO too: a SIGKILL'd peer (no
            # atexit) reaches finalize via the abort path, which never
            # calls drop_connection — without this the dead rank's
            # bell outlives the cluster. Unlinking a LIVE peer's bell
            # is as survivable as unlinking its segment: the owner
            # keeps its O_RDWR fd, cached sender fds stay valid, and
            # new opens fall back to the service timer.
            _unlink_name(_bell_name(token, src))
        self._announced.clear()
        for name in self._reaped.values():
            _unlink_name(name)
        self._reaped.clear()
        for name in self._reaped_bells.values():
            _unlink_name(name)
        self._reaped_bells.clear()
        self._tcp.finalize()

    def _teardown_rings(self) -> None:
        """Finalize, on the loop: stop the ring service, detach every
        in-ring (live Blob views park mappings on the graveyard), and
        retire the doorbell."""
        self._svc_stopped = True
        for ring in list(self._attached.values()):
            ring.retire()
        self._attached.clear()
        bell, self._bell = self._bell, None
        if bell is not None:
            try:
                self._loop.unregister(bell.fd)
            except (KeyError, ValueError):
                pass
            bell.close()
            _unlink_name(bell.name)
