"""Server actor: owns table shards and applies updates.

TPU-native equivalent of the reference's ``Server``/``SyncServer``
(ref: include/multiverso/server.h:13-24, src/server.cpp:23-233). The async
server invokes table logic directly and replies; the BSP ``SyncServer``
gates requests behind per-worker vector clocks so that every worker's i-th
Get observes exactly the state after all workers' j-th Adds — the same
contract as the reference (ref: src/server.cpp:60-66). The table storage the
server fronts is a sharded ``jax.Array`` in device HBM; the per-message work
here is host-side control only, with the arithmetic jit-dispatched.
"""

from __future__ import annotations

import collections
from typing import Deque, List

from ..core.message import Message, MsgType, mark_error
from ..util import log
from ..util.configure import define_double, get_flag
from ..util.dashboard import monitor
from . import actor as actors
from .actor import Actor

define_double("backup_worker_ratio", 0,
              "reserved: PERCENTAGE of workers treated as backups by the "
              "sync server ('set 20 means 20%' — defined-but-unused in "
              "the reference too, ref: src/server.cpp:21). Parsed as a "
              "double so pre-existing fractional configs (-backup_worker_"
              "ratio=0.2) keep parsing; readers should round to an int "
              "percentage")

_INF = float("inf")


class Server(Actor):
    def __init__(self, zoo) -> None:
        super().__init__(actors.SERVER, zoo)
        self._store: List = []  # registered ServerTables, indexed by table id
        self.register_handler(MsgType.Request_Get, self._process_get)
        self.register_handler(MsgType.Request_Add, self._process_add)

    @staticmethod
    def get_server(zoo) -> "Server":
        """Factory on the -sync flag (ref: src/server.cpp:224-231)."""
        if get_flag("sync", False):
            log.info("Create a sync server")
            return SyncServer(zoo)
        log.debug("Create a async server")
        return Server(zoo)

    def register_table(self, server_table) -> int:
        self._store.append(server_table)
        return len(self._store) - 1

    # ref: src/server.cpp:36-46
    def _process_get(self, msg: Message) -> None:
        with monitor("SERVER_PROCESS_GET"):
            reply = msg.create_reply_message()
            # The reply goes out even if table logic raises — a swallowed
            # reply would deadlock the requester's waiter forever — and a
            # failure travels back as an error reply so the requester's
            # wait() RAISES instead of consuming an empty payload (the
            # actor loop only logs; without this, every server-side CHECK
            # degrades to silent garbage at the caller).
            try:
                reply.data = self._store[msg.table_id].process_get(msg.data)
            except Exception as exc:  # noqa: BLE001
                mark_error(reply, exc)
                raise
            finally:
                self.send_to(actors.COMMUNICATOR, reply)

    # ref: src/server.cpp:48-58
    def _process_add(self, msg: Message) -> None:
        with monitor("SERVER_PROCESS_ADD"):
            reply = msg.create_reply_message()
            try:
                self._store[msg.table_id].process_add(msg.data)
            except Exception as exc:  # noqa: BLE001
                mark_error(reply, exc)
                raise
            finally:
                self.send_to(actors.COMMUNICATOR, reply)


class _VectorClock:
    """SyncServer's specialized vector clock (ref: src/server.cpp:81-137).

    ``update(i)`` ticks worker i's local clock and returns True exactly when
    the global clock catches up to the max local clock (all workers level).
    ``finish_train(i)`` retires worker i (clock -> +inf).
    """

    def __init__(self, n: int):
        self._local = [0.0] * n
        self.global_clock = 0.0

    def local_clock(self, i: int) -> float:
        return self._local[i]

    def _max_finite(self) -> float:
        finite = [v for v in self._local if v != _INF]
        return max([self.global_clock] + finite)

    def update(self, i: int) -> bool:
        self._local[i] += 1
        if self.global_clock < min(self._local):
            self.global_clock += 1
            if self.global_clock == self._max_finite():
                return True
        return False

    def finish_train(self, i: int) -> bool:
        self._local[i] = _INF
        if self.global_clock < min(self._local):
            self.global_clock = min(self._local)
            if self.global_clock == self._max_finite():
                return True
        return False


class SyncServer(Server):
    """BSP server (ref: src/server.cpp:67-222).

    Assumes all workers issue the same number of Adds/Gets per iteration.
    Faster workers' requests are cached and drained when the global clock
    advances; ``Server_Finish_Train`` releases stragglers at shutdown.
    """

    def __init__(self, zoo) -> None:
        super().__init__(zoo)
        self.register_handler(MsgType.Server_Finish_Train,
                              self._process_finish_train)
        n = zoo.num_workers
        self._get_clocks = _VectorClock(n)
        self._add_clocks = _VectorClock(n)
        self._num_waited_add = [0] * n
        self._add_cache: Deque[Message] = collections.deque()
        self._get_cache: Deque[Message] = collections.deque()

    # ref: src/server.cpp:141-163
    def _process_add(self, msg: Message) -> None:
        worker = self._zoo.rank_to_worker_id(msg.src)
        if (self._get_clocks.local_clock(worker)
                > self._get_clocks.global_clock):
            self._add_cache.append(msg)
            self._num_waited_add[worker] += 1
            return
        # The clock MUST tick even when table logic raises (the error
        # reply went out and the worker sees a recoverable failure) —
        # skipping it would leave this worker's clock permanently behind
        # and the BSP gate would cache every other worker's requests
        # forever: a cluster-wide hang from one bad request.
        try:
            super()._process_add(msg)
        finally:
            if self._add_clocks.update(worker):
                assert not self._add_cache
                self._drain_get_cache()

    # ref: src/server.cpp:165-188
    def _process_get(self, msg: Message) -> None:
        worker = self._zoo.rank_to_worker_id(msg.src)
        if (self._add_clocks.local_clock(worker)
                > self._add_clocks.global_clock
                or self._num_waited_add[worker] > 0):
            self._get_cache.append(msg)
            return
        try:
            super()._process_get(msg)
        finally:
            if self._get_clocks.update(worker):
                self._drain_add_cache()

    # ref: src/server.cpp:190-213
    def _process_finish_train(self, msg: Message) -> None:
        worker = self._zoo.rank_to_worker_id(msg.src)
        if self._add_clocks.finish_train(worker):
            assert not self._add_cache
            self._drain_get_cache()
        if self._get_clocks.finish_train(worker):
            assert not self._get_cache
            self._drain_add_cache()

    def _drain_get_cache(self) -> None:
        while self._get_cache:
            get_msg = self._get_cache.popleft()
            worker = self._zoo.rank_to_worker_id(get_msg.src)
            # A raising drained request already sent its error reply;
            # swallow here (with the log line Server._process_* emitted
            # via its raise path unavailable, log directly) so the rest
            # of the cache still drains and the clocks stay level.
            try:
                Server._process_get(self, get_msg)
            except Exception:  # noqa: BLE001
                log.error("sync server: drained get failed "
                          "(error reply sent)")
            leveled = self._get_clocks.update(worker)
            assert not leveled

    def _drain_add_cache(self) -> None:
        while self._add_cache:
            add_msg = self._add_cache.popleft()
            worker = self._zoo.rank_to_worker_id(add_msg.src)
            try:
                Server._process_add(self, add_msg)
            except Exception:  # noqa: BLE001
                log.error("sync server: drained add failed "
                          "(error reply sent)")
            leveled = self._add_clocks.update(worker)
            assert not leveled
            self._num_waited_add[worker] -= 1
