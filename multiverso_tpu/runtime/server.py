"""Server actor: owns table shards and applies updates.

TPU-native equivalent of the reference's ``Server``/``SyncServer``
(ref: include/multiverso/server.h:13-24, src/server.cpp:23-233). The async
server invokes table logic directly and replies; the BSP ``SyncServer``
gates requests behind per-worker vector clocks so that every worker's i-th
Get observes exactly the state after all workers' j-th Adds — the same
contract as the reference (ref: src/server.cpp:60-66). The table storage the
server fronts is a sharded ``jax.Array`` in device HBM; the per-message work
here is host-side control only, with the arithmetic jit-dispatched.
"""

from __future__ import annotations

import collections
import contextlib
import threading
from typing import Deque, List, Optional

import numpy as np

from ..core.blob import Blob
from ..core.message import (PEER_LOST_MARK, Message, MsgType, mark_error,
                            mark_replica_reply, stamp_trace,
                            stamp_version, trace_of, unpack_add_batch)
from ..util import log, mt_queue, tracing
from ..util.configure import define_double, get_flag
from ..util.dashboard import count, monitor, samples
from . import actor as actors
from . import device_lock
# Imported eagerly so the -server_fuse_* flag definitions are
# registered before Zoo.start parses the command line.
from . import fusion
from . import replica as replica_mod
# Imported eagerly so the -snapshot_* flag definitions are registered
# before Zoo.start parses the command line (a lazily-imported module's
# flags would silently fail to parse).
from . import snapshot as snapshot_mod
from .actor import Actor

define_double("backup_worker_ratio", 0.0,
              "straggler cutoff for the BSP sync server: this share of "
              "workers ('set 20 means 20%'; fractional 0.2 accepted "
              "too) are treated as BACKUPS — the global vector clock "
              "advances once the fastest (1 - ratio) of workers have "
              "ticked, so an epoch finishes despite a straggling or "
              "dead worker (its late ticks still apply, they just no "
              "longer gate anyone). 0 (default) = strict BSP, the "
              "reference's semantics (where this flag existed but was "
              "unused, ref: src/server.cpp:21)")

_INF = float("inf")


def backup_worker_count(num_workers: int) -> int:
    """-backup_worker_ratio as a worker count: 'set 20 means 20%' (the
    reference's convention) with fractional values (0.2) accepted too;
    clamped so at least one worker always gates the clock."""
    ratio = float(get_flag("backup_worker_ratio"))
    if ratio >= 1.0:
        ratio = ratio / 100.0
    if ratio <= 0 or num_workers <= 1:
        return 0
    return min(int(ratio * num_workers), num_workers - 1)


class Server(Actor):
    #: Process-wide: table logic dispatches jitted programs over the
    #: process's (shared) device mesh, and TWO server actor threads —
    #: virtual ranks on a LocalFabric — interleaving multi-device
    #: executions deadlock inside XLA's CPU runtime (observed: both
    #: threads parked in pxla __call__ forever). One server per process
    #: (the real deployment) never contends; RLock because the sync
    #: server's drain paths re-enter through Server._process_*.
    #: SCOPED to device-backed tables only (``needs_device_lock``):
    #: host-only table logic (KV control plane) must not serialize two
    #: in-process server shards against each other — that regression
    #: put ps_two_servers at 0.809x of single-server in BENCH_r05.
    #: The lock object itself is the process-wide device-dispatch lock
    #: (runtime/device_lock.py): in multi-zoo mode trainer and worker
    #: dispatch sites serialize on the SAME lock.
    _table_lock = device_lock.TABLE_LOCK
    _no_lock = contextlib.nullcontext()

    def _lock_for(self, table):
        """Device-backed tables serialize on the process-wide device
        lock — but only while multi-device serialization is ACTIVE
        (``device_lock.active()``): on a single-device process the
        wedge class the lock exists for cannot occur (no inter-device
        rendezvous to deadlock the execution pool), and process-wide
        serialization of sibling server actors was the measured bulk of
        the two-server regression (BENCH_r05 0.809x). Inactive mode
        falls back to the table's per-instance state lock, which still
        pairs (state, version) against the async snapshotter. Host-only
        tables always take their own state lock — cheap (uncontended
        except versus the snapshotter, since the actor thread is the
        only writer) but required so the snapshotter's capture cannot
        tear against a concurrent host-side add."""
        if getattr(table, "needs_device_lock", True) \
                and device_lock.active():
            return self._table_lock
        return getattr(table, "_state_lock", self._no_lock)

    def __init__(self, zoo) -> None:
        super().__init__(actors.SERVER, zoo)
        # Mailbox pressure is the admission-control signal of the
        # serving tier (serving/admission.py sheds over the high
        # watermark) and a bench observable (docs/SERVING.md) — record
        # per-push depth into the MAILBOX_DEPTH[*] Samples family.
        # Gated: a training-only deployment must not pay a reservoir
        # append per message for samples nobody reads.
        if mt_queue.depth_sampling_enabled():
            self.mailbox.track_depth("MAILBOX_DEPTH[server]")
        self._store: List = []  # registered ServerTables, indexed by table id
        self.register_handler(MsgType.Request_Get, self._process_get)
        self.register_handler(MsgType.Request_Add, self._process_add)
        self.register_handler(MsgType.Request_BatchAdd,
                              self._process_batch_add)
        # Hot-shard read replication (runtime/replica.py,
        # docs/SHARDING.md): owner refresh pushes land here; the
        # promoted-row map broadcast arrives via the communicator's
        # per-actor clone routing.
        self.register_handler(MsgType.Request_ReplicaSync,
                              self._process_replica_sync)
        self.register_handler(MsgType.Control_Replica_Map,
                              self._process_replica_map)
        # Live elastic resharding (runtime/shard_map.py,
        # docs/SHARDING.md): controller-ordered range migration between
        # live servers + the dual-read/forwarding window.
        self.register_handler(MsgType.Request_ShardBegin,
                              self._process_shard_begin)
        self.register_handler(MsgType.Server_Shard_Pump,
                              self._process_shard_pump)
        self.register_handler(MsgType.Request_ShardData,
                              self._process_shard_data)
        self.register_handler(MsgType.Request_ShardAck,
                              self._process_shard_ack)
        self.register_handler(MsgType.Request_ShardAbort,
                              self._process_shard_abort)
        self.register_handler(MsgType.Request_FwdGet,
                              self._process_fwd_get)
        self.register_handler(MsgType.Request_FwdAdd,
                              self._process_fwd_add)
        self.register_handler(MsgType.Control_Shard_Map,
                              self._process_shard_map)
        # Fault tolerance: periodic async snapshots + rejoin restore
        # (runtime/snapshot.py), enabled by -snapshot_dir.
        self._snapshots = None
        if str(get_flag("snapshot_dir", "")):
            self._snapshots = snapshot_mod.SnapshotManager(
                zoo, self._table_lock)
        # Rejoin readiness gate: on a RESTARTED rank, surviving workers
        # start retrying requests the moment the communicator is up —
        # before the application has re-created (and restored) the
        # tables. Registration runs inside the table base constructor,
        # so a registered-but-unready table must NACK retryably, not
        # serve a half-constructed shard.
        self._gate_unready = bool(get_flag("rejoin"))
        self._ready_ids: set = set()
        # Server-side request fusion (runtime/fusion.py,
        # docs/SERVER_ENGINE.md): when the mailbox holds more than one
        # message, drain a bounded batch and execute one device
        # program per (table, op) group. Read at construction, like
        # -sparse_compress; SyncServer forces max to 1 — the BSP
        # vector clocks count one request per worker per step.
        self._fuse_max = max(int(get_flag("server_fuse_max")), 1)
        self._fuse_bytes = max(int(get_flag("server_fuse_bytes")), 1)

    def start(self) -> None:
        super().start()
        if self._snapshots is not None:
            self._snapshots.start()

    def stop(self) -> None:
        if self._snapshots is not None:
            self._snapshots.stop()
        super().stop()

    @staticmethod
    def get_server(zoo) -> "Server":
        """Factory on the -sync flag (ref: src/server.cpp:224-231)."""
        if get_flag("sync", False):
            log.info("Create a sync server")
            return SyncServer(zoo)
        log.debug("Create a async server")
        return Server(zoo)

    def register_table(self, server_table) -> int:
        self._store.append(server_table)
        table_id = len(self._store) - 1
        if not self._gate_unready:
            self._ready_ids.add(table_id)
        if self._snapshots is not None:
            # Track for the periodic cut. Restore (rejoin) and the
            # snapshot-readiness mark wait for table_ready —
            # registration runs inside the base constructor, before
            # the shard's storage exists.
            self._snapshots.track(table_id, server_table)
        return table_id

    def table_ready(self, server_table) -> None:
        """A server table finished construction (table factory hook):
        on a rejoining rank, restore it from the latest snapshot before
        it serves its first request; in all cases, open it to the
        snapshotter and (under the rejoin gate) to requests."""
        if self._snapshots is not None:
            self._snapshots.restore_if_pending(server_table)
        try:
            table_id = self._store.index(server_table)
        except ValueError:
            return
        self._ready_ids.add(table_id)

    def _table(self, table_id: int):
        """The registered-and-ready table, or a RETRYABLE error: on a
        rejoining restarted rank, requests can land after the server
        actor starts but before the application re-created (or
        finished constructing) this table — the requester must back
        off and re-issue, not treat it as a fatal table-logic
        failure."""
        if 0 <= table_id < len(self._store) \
                and table_id in self._ready_ids:
            return self._store[table_id]
        raise RuntimeError(
            f"{PEER_LOST_MARK} table {table_id} not (yet) registered "
            f"on rank {self._zoo.rank} — rejoin in progress?")

    # -- server-side request fusion (runtime/fusion.py,
    #    docs/SERVER_ENGINE.md) --
    def _main(self) -> None:
        if self._fuse_max <= 1:
            return super()._main()
        while True:
            batch = self.mailbox.pop_batch(
                self._fuse_max, self._fuse_bytes,
                size_of=fusion.message_nbytes)
            if not batch:
                break
            if len(batch) == 1:
                self._safe_dispatch(batch[0])
                continue
            samples("SERVER_FUSE_BATCH").add(len(batch))
            try:
                self._dispatch_fused(batch)
            except Exception:  # noqa: BLE001 - the actor must not die
                # silently (same contract as _safe_dispatch); per-entry
                # errors were already captured into error replies, so
                # reaching here means the planner/reply layer itself
                # broke — log loudly.
                log.error("server: fused batch dispatch raised")
                import traceback
                traceback.print_exc()

    def _dispatch_fused(self, batch: List[Message]) -> None:
        """Execute one drained batch: eligible Get/Add/BatchAdd units
        fuse into (table, op) groups (one device program each);
        everything else is a barrier that dispatches through the
        ordinary serial handler. Replies are deferred and emitted in
        arrival order at each barrier and at batch end."""
        infos = [fusion.classify(self, i, m)
                 for i, m in enumerate(batch)]
        plan = fusion.split_plan(batch, infos)
        cursor = 0

        def emit(upto: int) -> None:
            nonlocal cursor
            while cursor < upto:
                if infos[cursor] is not None:
                    self._send_fused_reply(batch[cursor], infos[cursor])
                cursor += 1

        for kind, payload in plan:
            if kind == "serial":
                # Every fusable message before the barrier has fully
                # executed (split_plan flushes windows first): its
                # replies must leave before the barrier's handler can
                # send anything, preserving global reply order.
                emit(payload)
                self._safe_dispatch(batch[payload])
                cursor = payload + 1
            else:
                self._run_fused_step(payload)
        emit(len(batch))

    def _run_fused_step(self, groups) -> None:
        touched = []
        for table, is_get, entries in groups:
            self._run_fused_group(table, is_get, entries)
            touched.append(table)
        for table in touched:
            try:
                self._replica_flush(table)
            except Exception:  # noqa: BLE001 - replica traffic is
                # best-effort; the served entries' replies must still
                # go out.
                log.error("server: replica flush after fused group "
                          "failed")
                import traceback
                traceback.print_exc()

    def _run_fused_group(self, table, is_get: bool, entries) -> None:
        """One (table, op) group, ONE device program. A failure falls
        back to per-entry serial replay — exact serial semantics, with
        per-entry errors captured into the deferred replies."""
        name = "SERVER_PROCESS_GET" if is_get else "SERVER_PROCESS_ADD"
        if len(entries) == 1:
            # Singleton "group": the fused paths would only add
            # overhead (a forced host materialization of the gather,
            # dedup bookkeeping) with nothing to amortize it over —
            # run the exact serial path; replies, stamps and metrics
            # are identical to an unfused dispatch.
            with monitor(name):
                self._replay_serial(table, is_get, entries)
            return
        try:
            with monitor(name):
                if is_get:
                    with self._lock_for(table):
                        results = table.process_fused_get(
                            [e.blobs for e in entries])
                        if device_lock.active():
                            device_lock.settle(
                                [b.data for blobs in results
                                 for b in blobs if b.on_device])
                        v = table.version
                    for e, blobs in zip(entries, results):
                        e.result = blobs
                        e.version = v
                else:
                    with self._lock_for(table):
                        table.process_fused_add(
                            [e.blobs for e in entries])
                        device_lock.settle(
                            getattr(table, "_data", None))
                        # One bump per fused Add, all inside the lock
                        # (snapshot consistency — see _process_add);
                        # every reply carries the POST-BATCH version.
                        # Conservatively LATER than the serial stamp,
                        # which keeps read-your-writes sound: a floor
                        # can only over-demand freshness, never admit
                        # a stale read (docs/SERVER_ENGINE.md).
                        table.version += len(entries)
                        v = table.version
                    for e in entries:
                        e.version = v
            if table.needs_device_lock:
                count("SERVER_DEVICE_DISPATCHES", 1)
        except fusion.PartialFuseError as err:
            # The fused apply folded a prefix into table state before
            # failing: account the prefix (version bump + stamps),
            # then replay only the unapplied tail — replaying an
            # applied request would double-count its delta.
            log.error("server: fused add group failed after %d of %d "
                      "— replaying the tail serially",
                      err.applied, len(entries))
            import traceback
            traceback.print_exc()
            if err.applied:
                with self._lock_for(table):
                    device_lock.settle(getattr(table, "_data", None))
                    table.version += err.applied
                    v = table.version
                for e in entries[:err.applied]:
                    e.version = v
                if table.needs_device_lock:
                    count("SERVER_DEVICE_DISPATCHES", 1)
            self._replay_serial(table, is_get, entries,
                                start=err.applied)
        except Exception:  # noqa: BLE001
            log.error("server: fused %s group failed — replaying "
                      "serially", "get" if is_get else "add")
            import traceback
            traceback.print_exc()
            self._replay_serial(table, is_get, entries)

    def _replay_serial(self, table, is_get: bool, entries,
                       start: int = 0) -> None:
        """Per-entry fallback with exact serial semantics; failures
        travel back per entry in the deferred replies."""
        for e in entries[start:]:
            try:
                if is_get:
                    with self._lock_for(table):
                        e.result = table.process_get(e.blobs)
                        if device_lock.active():
                            device_lock.settle(
                                [b.data for b in e.result
                                 if b.on_device])
                    e.version = table.version
                else:
                    with self._lock_for(table):
                        table.process_add(e.blobs)
                        device_lock.settle(
                            getattr(table, "_data", None))
                        table.version += 1
                    e.version = table.version
                if table.needs_device_lock:
                    count("SERVER_DEVICE_DISPATCHES", 1)
            except Exception as exc:  # noqa: BLE001
                e.error = exc
                e.version = getattr(table, "version", -1)
                log.error("server: serial replay of fused entry "
                          "failed (error travels in the reply)")
                import traceback
                traceback.print_exc()

    def _send_fused_reply(self, msg: Message, entries) -> None:
        """Emit the deferred reply for one fully-executed message:
        the per-message Reply_Get/Reply_Add twin of the serial
        handlers, or the reassembled Reply_BatchAdd descriptor
        [n, (table_id, msg_id, err, version)...] + one utf-8 text
        blob per failed sub (core/message.py pack_add_batch)."""
        if msg.type_int == int(MsgType.Request_BatchAdd):
            reply = msg.create_reply_message()
            desc: List[int] = [len(entries)]
            err_blobs: List[Blob] = []
            for e in entries:
                failed = e.error is not None
                desc.extend((e.table_id, e.msg_id,
                             1 if failed else 0, e.version))
                if failed:
                    text = f"{type(e.error).__name__}: {e.error}" \
                        .encode(errors="replace")
                    err_blobs.append(
                        Blob(np.frombuffer(text, np.uint8).copy()))
            reply.push(Blob(np.asarray(desc, dtype=np.int32)))
            reply.data.extend(err_blobs)
            self.send_to(actors.COMMUNICATOR, reply)
            return
        e = entries[0]
        reply = msg.create_reply_message()
        if e.error is not None:
            mark_error(reply, e.error)
        else:
            if e.is_get:
                reply.data = e.result
            stamp_version(reply, e.version)
        self.send_to(actors.COMMUNICATOR, reply)

    # ref: src/server.cpp:36-46
    def _process_get(self, msg: Message) -> None:
        with monitor("SERVER_PROCESS_GET"), \
                tracing.span(trace_of(msg), "server_process_get",
                             self._zoo.rank,
                             args={"table": msg.table_id}):
            reply = msg.create_reply_message()
            # The reply goes out even if table logic raises — a swallowed
            # reply would deadlock the requester's waiter forever — and a
            # failure travels back as an error reply so the requester's
            # wait() RAISES instead of consuming an empty payload (the
            # actor loop only logs; without this, every server-side CHECK
            # degrades to silent garbage at the caller).
            forwarded = False
            try:
                if not msg.data:
                    # Sync-mode clock-tick shard (worker full-coverage
                    # padding): no table logic, no payload — the empty
                    # reply only counts down the requester's waiter
                    # (on a SyncServer the wrapper already ticked the
                    # vector clock).
                    return
                table = self._table(msg.table_id)
                # Dual-read window (docs/SHARDING.md): rows this shard
                # handed off forward to their new owner, which replies
                # to the requester directly (with OUR still-owned rows
                # piggybacked) — no reply leaves from here.
                outs = table.shard_forward_get(msg)
                if outs is not None:
                    forwarded = True
                    for out in outs:
                        self.send_to(actors.COMMUNICATOR, out)
                    return
                with self._lock_for(table), \
                        tracing.span(trace_of(msg), "table_op:get",
                                     self._zoo.rank):
                    reply.data = table.process_get(msg.data)
                    # Multi-zoo mode: the gather must finish before the
                    # lock releases, or its execution overlaps a sibling
                    # rank's next program (device_lock.py). active()
                    # gate keeps the list build off the production hot
                    # path.
                    if device_lock.active():
                        device_lock.settle([b.data for b in reply.data
                                            if b.on_device])
                if table.needs_device_lock:
                    # One gather program per serial Get — the
                    # denominator the fusion bench divides down
                    # (docs/SERVER_ENGINE.md).
                    count("SERVER_DEVICE_DISPATCHES", 1)
                # Version stamp: the shard state this Get observed
                # (client-cache freshness anchor). Error replies stay
                # unstamped — the worker checks the error flag first.
                stamp_version(reply, table.version)
                # Replica-served trailing rows (docs/SHARDING.md): the
                # worker needs the count to find the reply's replica
                # descriptor blob.
                replica_rows = table.take_reply_replica_rows()
                if replica_rows:
                    mark_replica_reply(reply, replica_rows)
            except Exception as exc:  # noqa: BLE001
                mark_error(reply, exc)
                raise
            finally:
                if not forwarded:
                    self.send_to(actors.COMMUNICATOR, reply)
            self._replica_flush(table)

    def _replica_flush(self, table) -> None:
        """Send whatever replica/reshard traffic the served request
        made due: write-through refreshes of dirty promoted rows
        toward the holders, the hot-row report toward the controller,
        and any pending migration re-announcements (a lost
        Control_Shard_Done resends on traffic)."""
        for out in table.replica_flush_if_due():
            self.send_to(actors.COMMUNICATOR, out)
        for out in table.shard_announce():
            self.send_to(actors.COMMUNICATOR, out)

    def _process_replica_sync(self, msg: Message) -> None:
        """An owner server's refresh push for promoted rows this rank
        holds replicas of. Fire-and-forget: no waiter exists, so no
        reply — and no lock either, the replica store is touched only
        from this actor thread (serve in process_get, refresh here).
        A sync whose src is THIS rank is the communicator's failure
        echo (the push toward a dead holder never left): re-dirty its
        rows so the next flush re-pushes them, keeping the version
        watermark sound."""
        try:
            table = self._table(msg.table_id)
        except RuntimeError:
            return  # rejoin gap — replica content rebuilds on the
            # next flush cadence; nothing to NACK
        if msg.src == self._zoo.rank:
            table.replica_redirty(msg.data)
            return
        table.apply_replica_sync(msg.data)

    def _process_replica_map(self, msg: Message) -> None:
        """Promoted-row map broadcast (cloned to this actor by the
        communicator's routing): each named table adopts its row set —
        owner shards reply with the initial value push for their newly
        promoted rows, holders prune demoted entries."""
        try:
            epoch, promoted = replica_mod.unpack_replica_map(
                [b.as_array(np.int32) for b in msg.data])
        except Exception:  # noqa: BLE001 - a malformed map must not
            # kill the server loop; the next broadcast replaces it.
            log.error("server: undecodable replica map %r", msg)
            return
        for table_id, rows in promoted.items():
            if not (0 <= table_id < len(self._store)) \
                    or table_id not in self._ready_ids:
                continue
            for out in self._store[table_id].apply_replica_map(epoch,
                                                               rows):
                self.send_to(actors.COMMUNICATOR, out)

    # -- live elastic resharding (runtime/shard_map.py,
    #    docs/SHARDING.md; all on this actor thread) --
    def _process_shard_begin(self, msg: Message) -> None:
        """Controller's move order: the source table starts streaming,
        driven by local pump messages so serving traffic interleaves
        between chunks; an unsupported table (sparse bitmap, stateful
        updater, range not owned) NACKs and the controller rolls the
        move back."""
        from .zoo import CONTROLLER_RANK
        desc = msg.data[0].as_array(np.int64)
        epoch = int(desc[5])
        try:
            table = self._table(msg.table_id)
            ok = table.shard_begin_out(desc)
        except Exception:  # noqa: BLE001 - unready table / bad desc
            ok = False
        if not ok:
            log.error("rank %d: refusing shard migration of table %d "
                      "(epoch %d) — unsupported or not owned",
                      self._zoo.rank, msg.table_id, epoch)
            nack = Message(src=self._zoo.rank, dst=CONTROLLER_RANK,
                           msg_type=MsgType.Control_Shard_Done,
                           table_id=msg.table_id)
            nack.push(Blob(np.asarray([epoch, 0, self._zoo.server_id],
                                      dtype=np.int64)))
            self.send_to(actors.COMMUNICATOR, nack)
            return
        self.receive(Message(src=self._zoo.rank, dst=self._zoo.rank,
                             msg_type=MsgType.Server_Shard_Pump,
                             table_id=msg.table_id))

    def _process_shard_pump(self, msg: Message) -> None:
        try:
            table = self._table(msg.table_id)
        except RuntimeError:
            return
        outs, more = table.shard_pump()
        for out in outs:
            self.send_to(actors.COMMUNICATOR, out)
        if more:
            # Re-enqueue so queued serving requests interleave with
            # the stream — a migration must not starve the shard.
            self.receive(Message(src=self._zoo.rank, dst=self._zoo.rank,
                                 msg_type=MsgType.Server_Shard_Pump,
                                 table_id=msg.table_id))

    def _process_shard_data(self, msg: Message) -> None:
        try:
            table = self._table(msg.table_id)
        except RuntimeError:
            return  # rejoin gap: the source retransmits on the ack path
        for out in table.shard_import_chunk(msg):
            self.send_to(actors.COMMUNICATOR, out)

    def _process_shard_ack(self, msg: Message) -> None:
        try:
            table = self._table(msg.table_id)
        except RuntimeError:
            return
        for out in table.shard_ack(msg):
            self.send_to(actors.COMMUNICATOR, out)

    def _process_shard_abort(self, msg: Message) -> None:
        try:
            table = self._table(msg.table_id)
        except RuntimeError:
            return
        for out in table.shard_abort(
                int(msg.data[0].as_array(np.int64)[0])):
            self.send_to(actors.COMMUNICATOR, out)

    def _process_shard_map(self, msg: Message) -> None:
        """Epoch-stamped shard-map broadcast (cloned to this actor by
        the communicator, like Control_Replica_Map): the named table
        commits/prunes its migration state."""
        from . import shard_map as shard_map_mod
        try:
            table_id, smap, alive = shard_map_mod.ShardMap.unpack(
                [b.as_array(np.int64) for b in msg.data])
        except Exception:  # noqa: BLE001 - malformed broadcast must
            # not kill the server loop; the next broadcast replaces it.
            log.error("server: undecodable shard map %r", msg)
            return
        if not (0 <= table_id < len(self._store)) \
                or table_id not in self._ready_ids:
            return
        for out in self._store[table_id].apply_shard_map_server(
                smap.epoch, smap, alive):
            self.send_to(actors.COMMUNICATOR, out)

    def _process_fwd_get(self, msg: Message) -> None:
        """A source-forwarded Get (dual-read window): serve the moved
        rows here, merge the source's piggybacked rows, and reply
        IMPERSONATING the source rank — the requester's in-flight
        accounting keys on the shard it actually sent to, and the
        moved rows ride the reply as a replica group attributed to
        THIS shard (core/message.py Request_FwdGet)."""
        with monitor("SERVER_PROCESS_GET"), \
                tracing.span(trace_of(msg), "server_process_fwd_get",
                             self._zoo.rank,
                             args={"table": msg.table_id}):
            src_rank = int(msg.data[0].as_array(np.int64)[0]) \
                if msg.data else msg.src
            reply = Message(src=src_rank, dst=msg.src,
                            msg_type=MsgType.Reply_Get,
                            table_id=msg.table_id, msg_id=msg.msg_id)
            tid = trace_of(msg)
            if tid:
                stamp_trace(reply, tid)
            try:
                table = self._table(msg.table_id)
                with self._lock_for(table):
                    blobs, n_rep, src_rank2, src_version = \
                        table.process_forward_get(msg.data)
                    if device_lock.active():
                        device_lock.settle([b.data for b in blobs
                                            if b.on_device])
                reply.src = src_rank2
                reply.data = blobs
                stamp_version(reply, src_version)
                if n_rep:
                    mark_replica_reply(reply, n_rep)
            except Exception as exc:  # noqa: BLE001
                mark_error(reply, exc)
                raise
            finally:
                self.send_to(actors.COMMUNICATOR, reply)
            # A grow destination may see ONLY forwarded traffic until
            # the commit lands — the pending-Done re-announce must ride
            # it (docs/SHARDING.md).
            self._replica_flush(table)

    def _process_fwd_add(self, msg: Message) -> None:
        """A source-forwarded Add subset: apply, then ack the
        requester impersonating the source rank — version-UNSTAMPED
        (the moved rows' versions now come from THIS shard's counter;
        stamping it under the source's identity would fire the
        generation-regression guard spuriously). msg_id < 0 marks a
        secondary-window forward: applied, never acked."""
        with monitor("SERVER_PROCESS_ADD"), \
                tracing.span(trace_of(msg), "server_process_fwd_add",
                             self._zoo.rank,
                             args={"table": msg.table_id}):
            src_rank = int(msg.data[0].as_array(np.int64)[0]) \
                if msg.data else msg.src
            reply = None
            if msg.msg_id >= 0:
                reply = Message(src=src_rank, dst=msg.src,
                                msg_type=MsgType.Reply_Add,
                                table_id=msg.table_id,
                                msg_id=msg.msg_id)
                tid = trace_of(msg)
                if tid:
                    stamp_trace(reply, tid)
            try:
                table = self._table(msg.table_id)
                with self._lock_for(table):
                    table.process_add(msg.data[1:])
                    device_lock.settle(getattr(table, "_data", None))
                    table.version += 1
            except Exception as exc:  # noqa: BLE001
                if reply is not None:
                    mark_error(reply, exc)
                raise
            finally:
                if reply is not None:
                    self.send_to(actors.COMMUNICATOR, reply)
            self._replica_flush(table)

    # ref: src/server.cpp:48-58
    def _process_add(self, msg: Message) -> None:
        with monitor("SERVER_PROCESS_ADD"), \
                tracing.span(trace_of(msg), "server_process_add",
                             self._zoo.rank,
                             args={"table": msg.table_id}):
            reply = msg.create_reply_message()
            silent = False
            try:
                if not msg.data:
                    # Clock-tick shard: see _process_get. No version
                    # bump — nothing was applied.
                    return
                table = self._table(msg.table_id)
                # Dual-write window (docs/SHARDING.md): moved rows'
                # deltas forward to the new owner, which acks the
                # requester; the full add ALSO applies here without an
                # ack (both-apply — exactly one copy survives the
                # commit-or-rollback outcome).
                route = table.shard_forward_add(msg)
                if route is not None:
                    silent = True
                    local_msg, outs = route
                    for out in outs:
                        self.send_to(actors.COMMUNICATOR, out)
                    if local_msg is not None:
                        with self._lock_for(table):
                            # Both-apply exemption: this deliberate
                            # write into the handoff copy must bypass
                            # the own-window NACK.
                            table._in_both_apply = True
                            try:
                                table.process_add(local_msg.data)
                            finally:
                                table._in_both_apply = False
                            device_lock.settle(
                                getattr(table, "_data", None))
                            table.version += 1
                    return
                with self._lock_for(table), \
                        tracing.span(trace_of(msg), "table_op:add",
                                     self._zoo.rank):
                    table.process_add(msg.data)
                    # Multi-zoo mode: the update program (new table
                    # state) must land before the lock releases.
                    device_lock.settle(getattr(table, "_data", None))
                    # One bump per APPLIED Add; the ack carries the
                    # post-add version so the adder can resolve its
                    # self-invalidated cache slots (read-your-writes).
                    # INSIDE _lock_for(table): the snapshotter's
                    # capture acquires the same lock (device lock or
                    # the table's state lock) around each state cut and
                    # version read, so a restore can never restore
                    # state ahead of (or behind) its recorded version.
                    table.version += 1
                if table.needs_device_lock:
                    count("SERVER_DEVICE_DISPATCHES", 1)
                stamp_version(reply, table.version)
            except Exception as exc:  # noqa: BLE001
                mark_error(reply, exc)
                raise
            finally:
                if not silent:
                    self.send_to(actors.COMMUNICATOR, reply)
            self._replica_flush(table)

    def _process_batch_add(self, msg: Message) -> None:
        """Coalesced adds: apply every sub-add, ack them all in ONE
        Reply_BatchAdd (descriptor [n, (table_id, msg_id, err,
        version)...] + one utf-8 text blob per failed sub; version is
        the shard version after the sub applied, the batched twin of
        the per-message VERSION_SLOT stamp). A sub failure must not
        stop the siblings: each waiter still gets its notify, failed
        ones with the error recorded so the caller's wait() raises.
        The reply goes out in EVERY path — a swallowed reply would
        strand every sub-add's waiter forever (same invariant as
        _process_get/_process_add above) — so a batch whose payload
        blobs fail to unpack still acks each sub the descriptor names,
        all marked failed."""
        with monitor("SERVER_PROCESS_BATCH_ADD"), \
                tracing.span(trace_of(msg), "server_process_batch_add",
                             self._zoo.rank):
            reply = msg.create_reply_message()
            desc: List[int] = [0]
            err_blobs: List[Blob] = []
            touched: dict = {}  # table_id -> table (replica flush)

            def record(table_id: int, msg_id: int,
                       exc: Optional[BaseException],
                       version: int = -1) -> None:
                desc.extend((table_id, msg_id,
                             0 if exc is None else 1, version))
                desc[0] += 1
                if exc is not None:
                    text = f"{type(exc).__name__}: {exc}" \
                        .encode(errors="replace")
                    err_blobs.append(
                        Blob(np.frombuffer(text, np.uint8).copy()))

            try:
                try:
                    subs = unpack_add_batch(msg)
                except Exception as exc:  # noqa: BLE001 - malformed
                    # batch: the descriptor (blob 0) usually still
                    # parses even when the payload blobs are short —
                    # ack every sub it names as failed so no waiter
                    # hangs; a garbage descriptor leaves only the
                    # error-marked empty reply (worker logs it).
                    log.error("server: batch add unpack failed")
                    import traceback
                    traceback.print_exc()
                    try:
                        raw = msg.data[0].as_array(np.int32)
                        for i in range(int(raw[0])):
                            record(int(raw[1 + 3 * i]),
                                   int(raw[2 + 3 * i]), exc)
                    except Exception:  # noqa: BLE001
                        mark_error(reply, exc)
                        return
                    return
                for sub in subs:
                    try:
                        table = self._table(sub.table_id)
                        route = table.shard_forward_add(sub)
                        if route is not None:
                            # Dual-write window: the destination acks
                            # this sub under its own Reply_Add — it
                            # must NOT appear in this batch ack too.
                            local_msg, outs = route
                            for out in outs:
                                self.send_to(actors.COMMUNICATOR, out)
                            if local_msg is not None:
                                with self._lock_for(table):
                                    table._in_both_apply = True
                                    try:
                                        table.process_add(
                                            local_msg.data)
                                    finally:
                                        table._in_both_apply = False
                                    device_lock.settle(
                                        getattr(table, "_data", None))
                                    table.version += 1
                            touched[sub.table_id] = table
                            continue
                        with self._lock_for(table):
                            table.process_add(sub.data)
                            device_lock.settle(
                                getattr(table, "_data", None))
                            # Inside the lock for snapshot consistency
                            # (see _process_add).
                            table.version += 1
                        if table.needs_device_lock:
                            count("SERVER_DEVICE_DISPATCHES", 1)
                        record(sub.table_id, sub.msg_id, None,
                               table.version)
                        touched[sub.table_id] = table
                    except Exception as exc:  # noqa: BLE001 - per-sub
                        # failure travels back in the batch ack
                        try:
                            at = self._store[sub.table_id].version
                        except Exception:  # noqa: BLE001 - bad table id
                            at = -1
                        record(sub.table_id, sub.msg_id, exc, at)
                        log.error("server: batched add failed "
                                  "(error travels in the batch ack)")
                        import traceback
                        traceback.print_exc()
            finally:
                if not reply.data:  # mark_error path already has payload
                    reply.push(Blob(np.asarray(desc, dtype=np.int32)))
                    reply.data.extend(err_blobs)
                self.send_to(actors.COMMUNICATOR, reply)
            for table in touched.values():
                self._replica_flush(table)


class _VectorClock:
    """SyncServer's specialized vector clock (ref: src/server.cpp:81-137).

    ``update(i)`` ticks worker i's local clock and returns True exactly when
    the global clock catches up to the max local clock (all workers level).
    ``finish_train(i)`` retires worker i (clock -> +inf).

    **Backup-worker straggler cutoff** (``num_backup`` > 0, from
    ``-backup_worker_ratio``): the global clock follows the
    ``num_backup``-th smallest local clock instead of the strict
    minimum — i.e. the slowest ``num_backup`` workers no longer gate
    anyone. Their late ticks still count (a straggler's Adds apply when
    they arrive; a DEAD worker simply never contributes), the fast
    workers just stop waiting for them. With ``num_backup == 0`` every
    code path below is the reference's strict-BSP logic, unchanged."""

    def __init__(self, n: int, num_backup: int = 0):
        self._local = [0.0] * n
        self.global_clock = 0.0
        self._num_backup = min(max(int(num_backup), 0), max(n - 1, 0))

    @property
    def num_backup(self) -> int:
        return self._num_backup

    def local_clock(self, i: int) -> float:
        return self._local[i]

    def _max_finite(self) -> float:
        finite = [v for v in self._local if v != _INF]
        return max([self.global_clock] + finite)

    def _cutoff_min(self) -> float:
        """The clock the global follows: the (num_backup+1)-th smallest
        local clock — retired (+inf) workers sort fastest and never
        hold anything back; the num_backup slowest are skipped."""
        return sorted(self._local)[self._num_backup]

    def update(self, i: int) -> bool:
        self._local[i] += 1
        if self._num_backup == 0:
            if self.global_clock < min(self._local):
                self.global_clock += 1
                if self.global_clock == self._max_finite():
                    return True
            return False
        advanced = False
        # A straggler's late tick can move the cutoff several steps at
        # once (its clock stops being the skipped one); catch up fully.
        target = min(self._cutoff_min(), self._max_finite())
        while self.global_clock < target:
            self.global_clock += 1
            advanced = True
        return advanced and self.global_clock == self._max_finite()

    def finish_train(self, i: int) -> bool:
        self._local[i] = _INF
        if self._num_backup == 0:
            if self.global_clock < min(self._local):
                self.global_clock = min(self._local)
                if self.global_clock == self._max_finite():
                    return True
            return False
        target = self._cutoff_min()
        if self.global_clock < target:
            self.global_clock = min(target, max(self._max_finite(),
                                                self.global_clock))
            if self.global_clock == self._max_finite():
                return True
        return False


class SyncServer(Server):
    """BSP server (ref: src/server.cpp:67-222).

    Assumes all workers issue the same number of Adds/Gets per iteration.
    Faster workers' requests are cached and drained when the global clock
    advances; ``Server_Finish_Train`` releases stragglers at shutdown.
    """

    def __init__(self, zoo) -> None:
        super().__init__(zoo)
        # Request fusion is force-disabled in BSP mode regardless of
        # -server_fuse_max: the vector clocks count ONE request per
        # worker per step, and the clock-gated caching below reorders
        # requests in ways the fusion planner must never see
        # (docs/SERVER_ENGINE.md).
        if self._fuse_max > 1:
            log.debug("sync server: request fusion force-disabled "
                      "(BSP clock accounting)")
        self._fuse_max = 1
        self.register_handler(MsgType.Server_Finish_Train,
                              self._process_finish_train)
        n = zoo.num_workers
        # Straggler cutoff (-backup_worker_ratio): the slowest
        # num_backup workers stop gating the clocks — an epoch
        # finishes despite a straggling or dead worker; its late
        # requests still serve/apply when they arrive.
        self._num_backup = backup_worker_count(n)
        if self._num_backup:
            log.info("sync server: %d of %d workers treated as "
                     "backups (straggler cutoff)", self._num_backup, n)
        self._get_clocks = _VectorClock(n, self._num_backup)
        self._add_clocks = _VectorClock(n, self._num_backup)
        self._num_waited_add = [0] * n
        self._add_cache: Deque[Message] = collections.deque()
        self._get_cache: Deque[Message] = collections.deque()

    # ref: src/server.cpp:141-163
    def _process_add(self, msg: Message) -> None:
        worker = self._zoo.rank_to_worker_id(msg.src)
        if (self._get_clocks.local_clock(worker)
                > self._get_clocks.global_clock):
            self._add_cache.append(msg)
            self._num_waited_add[worker] += 1
            return
        # The clock MUST tick even when table logic raises (the error
        # reply went out and the worker sees a recoverable failure) —
        # skipping it would leave this worker's clock permanently behind
        # and the BSP gate would cache every other worker's requests
        # forever: a cluster-wide hang from one bad request.
        try:
            super()._process_add(msg)
        finally:
            if self._add_clocks.update(worker):
                # Strict BSP invariant: at add-level no add can be
                # cached. With a straggler cutoff the skipped worker's
                # requests may still sit cached at leveling — the
                # tolerant alternating drain handles both caches.
                if self._num_backup == 0:
                    assert not self._add_cache
                self._drain_caches(gets=True)

    def _process_batch_add(self, msg: Message) -> None:
        """Defense in depth: workers never coalesce in sync mode (the
        vector clocks count one request per worker per step), but a
        batch that arrives anyway unpacks through the clock-gated
        per-add path — each sub ticks the clocks and acks itself, so
        BSP accounting stays exact."""
        for sub in unpack_add_batch(msg):
            self._process_add(sub)

    # ref: src/server.cpp:165-188
    def _process_get(self, msg: Message) -> None:
        worker = self._zoo.rank_to_worker_id(msg.src)
        if (self._add_clocks.local_clock(worker)
                > self._add_clocks.global_clock
                or self._num_waited_add[worker] > 0):
            self._get_cache.append(msg)
            return
        try:
            super()._process_get(msg)
        finally:
            if self._get_clocks.update(worker):
                self._drain_caches(adds=True)

    # ref: src/server.cpp:190-213
    def _process_finish_train(self, msg: Message) -> None:
        worker = self._zoo.rank_to_worker_id(msg.src)
        if self._add_clocks.finish_train(worker):
            if self._num_backup == 0:
                assert not self._add_cache
            self._drain_caches(gets=True)
        if self._get_clocks.finish_train(worker):
            if self._num_backup == 0:
                assert not self._get_cache
            self._drain_caches(adds=True)

    def _drain_caches(self, gets: bool = False, adds: bool = False) -> None:
        """Drain the requested cache(s); when a drained request levels
        the OTHER clock (possible only under a straggler cutoff, where
        a late tick can move the global clock several steps), alternate
        into the other cache until both settle. Strict BSP keeps the
        reference's single-pass behavior and its no-releveling
        invariant."""
        while gets or adds:
            if gets:
                gets = False
                while self._get_cache:
                    get_msg = self._get_cache.popleft()
                    worker = self._zoo.rank_to_worker_id(get_msg.src)
                    # A raising drained request already sent its error
                    # reply; swallow here (with the log line
                    # Server._process_* emitted via its raise path
                    # unavailable, log directly) so the rest of the
                    # cache still drains and the clocks stay level.
                    try:
                        Server._process_get(self, get_msg)
                    except Exception:  # noqa: BLE001
                        log.error("sync server: drained get failed "
                                  "(error reply sent)")
                    leveled = self._get_clocks.update(worker)
                    if self._num_backup == 0:
                        assert not leveled
                    elif leveled:
                        adds = True
            elif adds:
                adds = False
                while self._add_cache:
                    add_msg = self._add_cache.popleft()
                    worker = self._zoo.rank_to_worker_id(add_msg.src)
                    try:
                        Server._process_add(self, add_msg)
                    except Exception:  # noqa: BLE001
                        log.error("sync server: drained add failed "
                                  "(error reply sent)")
                    leveled = self._add_clocks.update(worker)
                    if self._num_backup == 0:
                        assert not leveled
                    elif leveled:
                        gets = True
                    self._num_waited_add[worker] -= 1
