"""Zoo: per-rank runtime singleton — bootstrap, routing, barrier.

TPU-native equivalent of the reference's ``Zoo``
(ref: include/multiverso/zoo.h:19-85, src/zoo.cpp:41-188). One Zoo per rank;
a process normally hosts exactly one (the TPU deployment: one JAX process,
role=ALL, tables sharded over the local device mesh), but may host several
*virtual ranks* on a shared ``LocalFabric`` — the moral equivalent of the
reference's ``mpirun -np N`` single-host tests, without MPI.

Start order mirrors the reference (ref: src/zoo.cpp:73-102): controller on
rank 0, communicator, register with the controller to learn the global
rank→worker_id/server_id map, then server and worker actors, then a barrier.
The ``-ma`` flag skips the PS entirely (model-average mode,
ref: src/zoo.cpp:49).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..core.blob import Blob
from ..core.message import Message, MsgType, take_error
from ..core.node import Node, Role, is_server, is_worker, role_from_string
# Imported eagerly so the -serving_* flag definitions are registered
# before Zoo.start parses the command line (the -snapshot_* precedent
# in runtime/server.py). Only the admission half: it is io-/runtime-
# import-free, while the frontend pulls in io/ (-> stream -> this
# module — a cycle at import time) and is therefore loaded lazily in
# _start_serving.
from ..serving import admission as _serving_admission  # noqa: F401
from ..util import log
from ..util.configure import (define_bool, define_double, define_int,
                              define_string, get_flag, parse_cmd_flags)
from ..util.mt_queue import MtQueue
from . import actor as actors
from .communicator import Communicator
from .controller import Controller
from .net import LocalFabric, NetInterface, PeerLostError
from .server import Server, backup_worker_count
# Imported eagerly so the -shm* flag definitions are registered before
# Zoo.start parses the command line (same reason as the admission
# import above): a lazy import inside _maybe_wrap_shm would register
# them only *after* parse_cmd_flags has already discarded -shm=0.
from . import shm as _shm
from .tcp import TcpNet, take_pending_net
from .worker import Worker

define_string("ps_role", "default", "none / worker / server / default(all)")
define_bool("ma", False, "model-average mode: skip the parameter server")
define_bool("sync", False, "BSP sync server")
define_bool("rejoin", False,
            "this process is a RESTARTED rank rejoining a live cluster: "
            "registration takes the controller's solo-reply path, the "
            "start barrier and table-creation barriers are skipped "
            "(the survivors are long past them), and — with "
            "-snapshot_dir set — server tables restore from the latest "
            "manifest-consistent snapshot as they register")
define_int("rpc_retry_max", 0,
           "how many times a failed sync table Get/Add is re-issued "
           "after a PeerLostError (bounded exponential backoff from "
           "-rpc_backoff_ms). 0 (default) disables the retry path AND "
           "the peer-loss containment that feeds it: a lost peer then "
           "aborts the whole zoo, the pre-fault-tolerance behavior")
define_double("rpc_backoff_ms", 50.0,
              "initial backoff before a PeerLostError retry; doubles "
              "per attempt, capped at 5s")

CONTROLLER_RANK = 0

_ABORT = object()  # mailbox sentinel: unblocks control waits on abort


class ClusterAborted(RuntimeError):
    """Raised out of blocking control calls after Zoo.abort()."""


_tls = threading.local()
_default_zoo: Optional["Zoo"] = None


def current_zoo() -> "Zoo":
    zoo = getattr(_tls, "zoo", None) or _default_zoo
    if zoo is None:
        raise RuntimeError("multiverso not initialized: call mv.init() first")
    return zoo


def set_thread_zoo(zoo: Optional["Zoo"]) -> None:
    _tls.zoo = zoo


class Zoo:
    def __init__(self) -> None:
        self._net: Optional[NetInterface] = None
        self._actors: Dict[str, object] = {}
        self.mailbox: MtQueue = MtQueue()
        self._nodes: List[Node] = []
        self._num_workers = 0
        self._num_servers = 0
        self._started = False
        self._aborted = False
        self._role_override: Optional[str] = None
        self._worker_table_count = 0
        self._server_table_count = 0
        self._server_tables: List = []  # owned for cleanup + checkpoint
        # -- fault tolerance --
        self._rejoining = False
        self._dead_peers: set = set()
        self._heartbeat = None  # HeartbeatMonitor when enabled
        self._last_controller_reply = 0.0
        # -- observability (runtime/metrics.py, io/metrics_http.py) --
        self._metrics_reporter = None
        self._metrics_http = None
        # -- online serving tier (serving/frontend.py, docs/SERVING.md) --
        self._serving = None
        # Last fleet-aggregate serving-pressure view received from the
        # controller (Control_Reply_Serving; written by the
        # communicator recv thread or the controller actor, read by
        # /v1/status handler threads — tuple assignment, GIL-atomic).
        self._serving_fleet: Optional[tuple] = None

    # -- lifecycle (ref: src/zoo.cpp:41-60) --
    def start(self, argv: Optional[List[str]] = None,
              net: Optional[NetInterface] = None,
              role: Optional[str] = None) -> List[str]:
        """``role`` overrides the -ps_role flag for this zoo (the flag
        registry is process-global; virtual ranks with heterogeneous roles
        need a per-zoo override)."""
        remaining = parse_cmd_flags(argv)
        self._rejoining = bool(get_flag("rejoin"))
        self._net = net if net is not None else self._resolve_net()
        if hasattr(self._net, "on_peer_lost"):
            # Failure detection (absent in the reference, SURVEY.md
            # section 5.3): a TCP peer dying mid-run reports through
            # peer_lost — with the retry path off that aborts this zoo
            # so blocked barriers/registrations/table waits raise
            # instead of hanging; with -rpc_retry_max set, only the
            # dead rank's in-flight requests fail (retryably).
            self._net.on_peer_lost = \
                lambda rank=None: self.peer_lost(rank, "connection died")
        self._role_override = role
        if not get_flag("ma"):
            try:
                self._start_ps()
                self._last_controller_reply = time.monotonic()
                interval = float(get_flag("heartbeat_interval_s", 0.0))
                if interval > 0:
                    from .controller import HeartbeatMonitor
                    self._heartbeat = HeartbeatMonitor(self)
                    self._heartbeat.start()
                self._start_observability()
                self._start_serving()
            except BaseException:
                # A sibling rank's abort can land while this rank is
                # still inside the start barrier: the caller never sees
                # _started and skips stop(), which would leave the
                # actor threads spawned above idling in their mailboxes
                # forever. Reap them before surfacing the error.
                try:
                    self._teardown_partial_start()
                except Exception:  # noqa: BLE001 - keep the cause
                    log.error("Rank %d: partial-start teardown raised",
                              self.rank)
                raise
        self._started = True
        log.debug("Rank %d: multiverso started", self.rank)
        return remaining

    def _teardown_partial_start(self) -> None:
        """Stop whatever a failed start() already brought up, in the
        same reverse order stop() uses. Only reached on the error path
        out of start(); barriers/drains are skipped — peers may already
        be gone."""
        for attr in ("_serving", "_metrics_reporter", "_heartbeat",
                     "_metrics_http"):
            obj = getattr(self, attr)
            if obj is not None:
                obj.stop()
                setattr(self, attr, None)
        controller = self._actors.get(actors.CONTROLLER)
        if controller is not None:
            controller.autotune.stop()
        for name in (actors.WORKER, actors.SERVER, actors.CONTROLLER):
            actor = self._actors.get(name)
            if actor is not None:
                actor.stop()
        comm = self._actors.get(actors.COMMUNICATOR)
        if comm is not None:
            comm.stop()
        elif self._net is not None:
            self._net.finalize()
        self._actors.clear()

    def _start_observability(self) -> None:
        """Metrics export (-metrics_interval_s) + the controller-rank
        scrape surface (-metrics_port). After registration, so reports
        can route; no-ops at the default flag values."""
        if float(get_flag("metrics_interval_s", 0.0)) > 0:
            from .metrics import MetricsReporter
            self._metrics_reporter = MetricsReporter(self)
            self._metrics_reporter.start()
        controller = self._actors.get(actors.CONTROLLER)
        if controller is not None \
                and float(get_flag("autotune_interval_s", 0.0)) > 0:
            # Closed-loop self-tuning (runtime/autotune.py,
            # docs/AUTOTUNE.md): controller rank only, after
            # registration — the first broadcast must be routable.
            controller.autotune.start()
        port = int(get_flag("metrics_port", 0))
        if port > 0 and self.rank == CONTROLLER_RANK \
                and controller is not None:
            from ..io.metrics_http import (MetricsHttpServer,
                                           json_route,
                                           prometheus_route)
            self._metrics_http = MetricsHttpServer(port, {
                "/metrics": prometheus_route(
                    lambda c=controller:
                    c.metrics.prometheus_text()
                    + c.autotune.prometheus_text()),
                "/trace.json": json_route(
                    controller.metrics.chrome_trace_json),
            })

    def metrics_flush(self) -> None:
        """One immediate metrics report from this rank (deterministic
        final cut before a scrape — pair with a barrier); no-op when
        the reporter is off."""
        if self._metrics_reporter is not None:
            self._metrics_reporter.flush()

    def _start_serving(self) -> None:
        """The online serving frontend (-serving_port,
        docs/SERVING.md) on ranks hosting a worker actor — serving
        reads route through worker tables, so a pure-server rank has
        nothing to serve from. No-op at the default flag value."""
        port = int(get_flag("serving_port", 0))
        if port > 0 and self._actors.get(actors.WORKER) is not None:
            from ..serving.frontend import ServingFrontend
            self._serving = ServingFrontend(self, port)

    @property
    def serving(self):
        """The live ServingFrontend, or None (flag off / no worker)."""
        return self._serving

    def serve_table(self, name: str, worker_table,
                    vocab: Optional[dict] = None) -> None:
        """Expose a worker table on the serving frontend under
        ``/v1/tables/<name>`` (``vocab``: word -> row id, enables the
        neighbors endpoint's word lookups). Safe to call with serving
        off — the registration is simply skipped, so application code
        need not fork on the flag."""
        if self._serving is None:
            log.debug("Rank %d: serve_table(%r) ignored — serving "
                      "frontend off (-serving_port)", self.rank, name)
            return
        self._serving.register_table(name, worker_table, vocab)

    def stop(self, finalize_net: bool = True) -> None:
        """ref: src/zoo.cpp:52-60,104-114."""
        if not self._started:
            return
        if self._serving is not None:
            # FIRST: the frontend's graceful drain needs the worker/
            # communicator stack still alive to finish in-flight reads;
            # once drained, no new HTTP work can reach the actors.
            self._serving.stop()
            self._serving = None
        if self._metrics_reporter is not None:
            self._metrics_reporter.stop()
            self._metrics_reporter = None
        controller = self._actors.get(actors.CONTROLLER)
        if controller is not None:
            # The autotune thread broadcasts through the actors; it
            # must stop before the actor teardown below.
            controller.autotune.stop()
        if self._metrics_http is not None:
            self._metrics_http.stop()
            self._metrics_http = None
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        if not get_flag("ma"):
            self._stop_ps(finalize_net)
        if finalize_net:
            self._net.finalize()
        self._actors.clear()
        self._server_tables.clear()
        self._started = False
        log.debug("Rank %d: multiverso shut down", self.rank)

    def _resolve_net(self) -> NetInterface:
        """Transport selection after flag parsing: an endpoint prepared by
        net_bind/net_connect wins, then a -machine_file TCP mesh
        (ref: zmq_net.h:25-61), else the single-rank in-process default."""
        pending = take_pending_net()
        if pending is not None:
            return self._maybe_wrap_shm(pending)
        if get_flag("machine_file"):
            return self._maybe_wrap_shm(TcpNet.from_flags())
        return LocalFabric(1).endpoint(0)

    @staticmethod
    def _maybe_wrap_shm(net: NetInterface) -> NetInterface:
        """Layer the shared-memory ring transport over a TCP mesh when
        ``-shm`` is on (runtime/shm.py): co-located peers negotiate
        per-pair rings at registration; everything else stays TCP."""
        if (bool(get_flag("shm")) and _shm.supported()
                and isinstance(net, TcpNet)):
            return _shm.ShmNet(net)
        return net

    def _start_ps(self) -> None:
        role = int(role_from_string(self._role_override
                                    or get_flag("ps_role")))
        self._nodes = [Node(rank=r, role=int(Role.NONE))
                       for r in range(self.net_size)]
        self._nodes[self.rank].role = role
        # Start order is non-trivial (ref: src/zoo.cpp:83-99): the
        # controller must be routable before any register traffic lands.
        if self.rank == CONTROLLER_RANK:
            Controller(self).start()
        Communicator(self).start()
        self._register_node(role)
        if is_server(role):
            Server.get_server(self).start()
        if is_worker(role):
            Worker(self).start()
        if not self._rejoining:
            # A rejoining restarted rank must not enter the start
            # barrier: the survivors passed it long ago, and a fresh
            # Control_Barrier from one rank would poison the NEXT
            # full-cluster barrier's count.
            self.barrier()

    def _stop_ps(self, finalize_net: bool = True) -> None:
        # After an abort the graceful drain (finish_train + barrier) would
        # block on peers that are gone; tear the actors down directly.
        if not self._aborted:
            if get_flag("sync"):
                self.finish_train()
            self.barrier()
        # Reverse start order (ref: src/zoo.cpp:104-113); communicator last
        # so in-flight replies still route.
        for name in (actors.WORKER, actors.SERVER, actors.CONTROLLER):
            actor = self._actors.get(name)
            if actor is not None:
                actor.stop()
        comm = self._actors.get(actors.COMMUNICATOR)
        if comm is not None:
            comm.stop(finalize_net=finalize_net)

    # -- registration protocol (ref: src/zoo.cpp:116-145) --
    def _register_node(self, role: int) -> None:
        from ..util.wire_codec import CAP_WIRE_CODEC
        caps = CAP_WIRE_CODEC if get_flag("wire_codec") else 0
        shm_ok = (bool(get_flag("shm"))
                  and hasattr(self._net, "enable_shm"))
        if shm_ok:
            caps |= _shm.CAP_SHM
        msg = Message(src=self.rank, dst=CONTROLLER_RANK,
                      msg_type=MsgType.Control_Register)
        # Third int advertises wire capabilities (codec negotiation);
        # the fourth a host fingerprint (shm co-location detection).
        # A controller that only reads [:2] still registers this rank,
        # it just never learns the capability — which degrades to
        # passthrough/TCP, the safe direction.
        msg.push(Blob(np.array([self.rank, role, caps,
                                _shm.host_fingerprint()],
                               dtype=np.int32)))
        self.send_to(actors.COMMUNICATOR, msg)
        reply = self._pop_control()
        assert reply is not None and reply.type == MsgType.Control_Reply_Register
        table = reply.data[0].as_array(np.int32).reshape(-1, 4)
        counts = reply.data[1].as_array(np.int32)
        for rank, node_role, worker_id, server_id in table:
            node = self._nodes[rank]
            node.role = int(node_role)
            node.worker_id = int(worker_id)
            node.server_id = int(server_id)
        self._num_workers = int(counts[0])
        self._num_servers = int(counts[1])
        # Per-rank capability vector (reply blob 2). An older controller
        # that doesn't broadcast it leaves every peer at 0 = passthrough.
        if len(reply.data) >= 3:
            self._peer_caps = reply.data[2].as_array(np.int32).copy()
        else:
            self._peer_caps = np.zeros(self.net_size, dtype=np.int32)
        # Shm negotiation (reply blobs 3+4, runtime/shm.py): the
        # controller's per-rank host-id vector plus the cluster-wide
        # segment-naming token. Peers on MY host that advertised
        # CAP_SHM become ring-send targets; an older controller (or a
        # -shm=0 cluster) simply never ships the blobs — TCP stays.
        if shm_ok and len(reply.data) >= 5:
            host_ids = reply.data[3].as_array(np.int32)
            token = int(reply.data[4].as_array(np.int32)[0])
            me = _shm.host_fingerprint()
            peers = [r for r in range(self.net_size)
                     if r != self.rank and r < len(host_ids)
                     and int(host_ids[r]) == me
                     and self.peer_caps(r) & _shm.CAP_SHM]
            if peers:
                self._net.enable_shm(token, peers)
        log.debug("Rank %d registered: workers=%d servers=%d caps=%s",
                  self.rank, self._num_workers, self._num_servers,
                  self._peer_caps.tolist())

    def peer_caps(self, rank: int) -> int:
        """Wire capabilities the peer advertised at registration
        (0 before registration completes / for pre-codec peers)."""
        caps = getattr(self, "_peer_caps", None)
        if caps is None or not 0 <= rank < len(caps):
            return 0
        return int(caps[rank])

    # -- identity --
    @property
    def net(self) -> NetInterface:
        return self._net

    @property
    def rank(self) -> int:
        return self._net.rank if self._net is not None else 0

    @property
    def size(self) -> int:
        return self.net_size

    @property
    def net_size(self) -> int:
        return self._net.size if self._net is not None else 1

    @property
    def num_workers(self) -> int:
        return self._num_workers

    @property
    def num_servers(self) -> int:
        return self._num_servers

    def rank_to_worker_id(self, rank: int) -> int:
        return self._nodes[rank].worker_id

    def rank_to_server_id(self, rank: int) -> int:
        return self._nodes[rank].server_id

    def worker_rank(self, worker_id: int) -> int:
        for node in self._nodes:
            if node.worker_id == worker_id:
                return node.rank
        return -1

    def server_rank(self, server_id: int) -> int:
        for node in self._nodes:
            if node.server_id == server_id:
                return node.rank
        return -1

    @property
    def servers_in_process(self) -> bool:
        """True when EVERY server shard lives in this process — the
        zero-copy device data plane (live ``jax.Array`` blobs in
        requests and replies) is then valid even when the cluster's
        transport is a real wire to other ranks. This is the locality
        rule that lets a co-located worker+server rank keep the fast
        device pipeline in a multi-process deployment (the reference's
        -ps_role split runs such mixed topologies; remote workers use
        the host-batch paths)."""
        if self.net.in_process:
            return True
        return self._num_servers > 0 and all(
            self.server_rank(s) == self.rank
            for s in range(self._num_servers))

    @property
    def worker_id(self) -> int:
        return self.rank_to_worker_id(self.rank)

    @property
    def server_id(self) -> int:
        return self.rank_to_server_id(self.rank)

    # -- actor registry / routing (ref: src/zoo.cpp:64-71,146-149) --
    def register_actor(self, actor) -> None:
        self._actors[actor.name] = actor

    def deregister_actor(self, actor) -> None:
        self._actors.pop(actor.name, None)

    def send_to(self, name: str, msg: Message) -> None:
        actor = self._actors.get(name)
        if actor is None:
            raise RuntimeError(f"no actor named {name!r} on rank {self.rank}")
        actor.receive(msg)

    route = send_to  # alias used by the communicator's inbound path

    # -- abort: unblock every control wait after a peer failure --
    def abort(self) -> None:
        """Mark this zoo dead and wake any thread blocked in barrier(),
        registration, or a table wait. Used by LocalCluster when a
        sibling rank errors and by the TCP transport when a peer
        disconnects — without it, mispaired barriers and requests to the
        dead rank hang forever."""
        self._aborted = True
        self.mailbox.push(_ABORT)
        worker = self._actors.get(actors.WORKER)
        if worker is not None:
            worker.abort_tables(f"rank {self.rank}: cluster aborted")

    # -- fault containment: a lost peer need not kill the zoo --
    @property
    def rejoining(self) -> bool:
        """True while this zoo is a restarted rank rejoining a live
        cluster (-rejoin): collective-creation barriers are skipped."""
        return self._rejoining

    def note_controller_alive(self) -> None:
        """A heartbeat reply arrived (communicator routing)."""
        self._last_controller_reply = time.monotonic()

    # -- serving-fleet pressure (serving/frontend.py, docs/SERVING.md)
    def note_serving_fleet(self, doc: dict) -> None:
        """A fleet-aggregate view arrived (Control_Reply_Serving via
        the communicator's by-name routing, or directly from a
        co-located controller actor)."""
        self._serving_fleet = (doc, time.monotonic())

    def serving_fleet(self) -> Optional[dict]:
        """The last fleet-aggregate serving-pressure view, stamped
        with its local age — None until a report round-trips."""
        ent = self._serving_fleet
        if ent is None:
            return None
        doc, ts = ent
        return {**doc, "age_s": round(time.monotonic() - ts, 3)}

    def controller_silent_for(self) -> float:
        return time.monotonic() - self._last_controller_reply

    def peer_lost(self, rank: Optional[int], reason: str) -> None:
        """A peer died (broken connection, or declared dead by the
        controller's liveness monitor). With the retry path enabled
        (-rpc_retry_max > 0) and the dead rank identified — and not the
        controller, whose loss is unrecoverable — only that rank's
        in-flight table requests fail, with a retryable PeerLostError;
        everything else keeps serving so the rank can restart and
        rejoin. Otherwise this degrades to ``abort()``: the
        pre-fault-tolerance kill-the-zoo behavior.

        BSP (``-sync``) narrows containment: the sync servers count
        exactly one request per worker per step on their vector
        clocks, so a lost SERVER cannot be papered over by re-issuing
        requests (the surviving servers would double-count the step —
        see ``retrying_wait``) and a lost WORKER permanently stalls
        the clocks unless backup workers (-backup_worker_ratio) cover
        its ticks. Only the covered-dead-worker case stays contained
        in sync mode; everything else aborts."""
        if rank == self.rank or self._aborted:
            return
        if rank is not None and rank in self._dead_peers:
            # Already swept (a TCP writer death and the controller's
            # monitor often both report the same corpse); re-running
            # would drop_connection a REPLACEMENT's fresh socket if the
            # rank already rejoined between the two reports.
            return
        retryable = (int(get_flag("rpc_retry_max")) > 0
                     and rank is not None and rank != CONTROLLER_RANK)
        if retryable and get_flag("sync", False):
            node = self._nodes[rank] if rank < len(self._nodes) else None
            retryable = (node is not None
                         and not is_server(node.role)
                         and backup_worker_count(self._num_workers) > 0)
        if not retryable:
            log.error("Rank %d: peer %s lost (%s) — aborting this zoo",
                      self.rank, "?" if rank is None else rank, reason)
            self.abort()
            return
        log.error("Rank %d: peer %d lost (%s) — failing its in-flight "
                  "requests, cluster keeps serving", self.rank, rank,
                  reason)
        self._dead_peers.add(rank)
        if hasattr(self._net, "drop_connection"):
            # Stale outbound state toward the dead peer must go: a
            # restarted process on the same endpoint is a NEW socket.
            self._net.drop_connection(rank)
        worker = self._actors.get(actors.WORKER)
        if worker is not None:
            notice = Message(src=self.rank, dst=self.rank,
                             msg_type=MsgType.Control_Dead_Peer)
            notice.push(Blob(np.array([rank], dtype=np.int32)))
            worker.receive(notice)

    def notice_peer_alive(self, rank: int) -> None:
        """Inbound traffic from a previously-declared-dead rank: its
        restarted process is talking again — clear the death mark so a
        SECOND death of the same rank sweeps again instead of being
        swallowed by peer_lost's idempotency guard."""
        if rank in self._dead_peers:
            self._dead_peers.discard(rank)
            log.info("Rank %d: peer %d is back (traffic resumed)",
                     self.rank, rank)

    def _pop_control(self):
        reply = self.mailbox.pop()
        if reply is _ABORT or self._aborted:
            raise ClusterAborted(f"rank {self.rank}: cluster aborted")
        return reply

    # -- collective control (ref: src/zoo.cpp:152-176) --
    def barrier(self) -> None:
        msg = Message(src=self.rank, dst=CONTROLLER_RANK,
                      msg_type=MsgType.Control_Barrier)
        self.send_to(actors.COMMUNICATOR, msg)
        reply = self._pop_control()
        assert reply is not None and reply.type == MsgType.Control_Reply_Barrier
        error = take_error(reply)
        if error is not None:
            # The controller failed the round: a declared-dead rank
            # stayed gone past -rejoin_grace_s, so the barrier could
            # never have completed. Retryable — a later rejoin lets
            # the next barrier() succeed.
            raise PeerLostError(error)

    # -- live elastic resharding (runtime/shard_map.py,
    #    docs/SHARDING.md) --
    def reshard_table(self, table, server_ids,
                      wait_s: float = 60.0) -> None:
        """Ask the controller to respread ``table`` over exactly
        ``server_ids`` (grow onto standbys / drain a retiring server)
        with live row migration — no stop-the-world. Fire-and-forget
        toward the controller; with ``wait_s`` > 0 this then POLLS the
        worker table's adopted map until its owner set matches (the
        commit broadcast is the only completion signal — there is
        nothing to block on, traffic keeps flowing throughout).

        BSP sync mode refuses (the vector clocks count requests per
        server); tables whose type cannot migrate (sparse matrix,
        array) are NACKed by their server and the move rolls back."""
        if get_flag("sync", False):
            raise RuntimeError("reshard_table: BSP sync mode pins the "
                               "frozen shard map")
        space = table.reshard_space()
        if space <= 0:
            raise ValueError(
                f"table {table.table_id} does not support live "
                f"resharding (docs/SHARDING.md support matrix)")
        target = sorted({int(s) for s in server_ids})
        if not target or target[-1] >= self._num_servers or target[0] < 0:
            raise ValueError(f"bad server id set {target} "
                             f"(num_servers={self._num_servers})")
        msg = Message(src=self.rank, dst=CONTROLLER_RANK,
                      msg_type=MsgType.Control_Shard_Request,
                      table_id=table.table_id)
        msg.push(Blob(np.asarray(
            [space, int(table.reshard_kind())] + target,
            dtype=np.int64)))
        self.send_to(actors.COMMUNICATOR, msg)
        if wait_s <= 0:
            return
        # Poll for the EXACT target layout, not just the owner set —
        # a multi-move plan passes through intermediate maps whose
        # owner set already matches (the first grow move creates the
        # new server's first interval long before the spread evens).
        from ..tables.matrix_table import row_offsets
        offsets = row_offsets(space, len(target))
        expected = (list(offsets),
                    [target[i] for i in range(len(offsets) - 1)])
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            if self._aborted:
                raise ClusterAborted(
                    f"rank {self.rank}: cluster aborted mid-reshard")
            if table.shard_layout() == expected:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"reshard of table {table.table_id} to servers {target} "
            f"did not commit within {wait_s}s (layout now: "
            f"{table.shard_layout()}, wanted {expected})")

    def table_shard_epoch(self, table) -> int:
        """The shard-map epoch ``table`` has adopted (-1 = frozen
        creation layout). Bench/test observability."""
        return table.shard_epoch()

    def finish_train(self) -> None:
        """Retire this rank's worker from the BSP clocks on all servers."""
        if self.worker_id < 0:
            return
        for server_id in range(self._num_servers):
            msg = Message(src=self.rank, dst=self.server_rank(server_id),
                          msg_type=MsgType.Server_Finish_Train)
            self.send_to(actors.COMMUNICATOR, msg)

    # -- table registration (ref: src/zoo.cpp:178-186) --
    def register_worker_table(self, worker_table) -> int:
        worker = self._actors.get(actors.WORKER)
        if worker is None:
            raise RuntimeError("no worker actor on this rank")
        tid = worker.register_table(worker_table)
        self._worker_table_count = tid + 1
        return tid

    def register_server_table(self, server_table) -> int:
        server = self._actors.get(actors.SERVER)
        if server is None:
            raise RuntimeError("no server actor on this rank")
        tid = server.register_table(server_table)
        self._server_tables.append(server_table)
        self._server_table_count = tid + 1
        return tid

    def server_table_ready(self, server_table) -> None:
        """Table-factory hook: the server table is fully constructed —
        a rejoining rank restores it from the latest snapshot now."""
        server = self._actors.get(actors.SERVER)
        if server is not None:
            server.table_ready(server_table)

    @property
    def server_tables(self) -> List:
        return self._server_tables


def set_default_zoo(zoo: Optional[Zoo]) -> None:
    global _default_zoo
    _default_zoo = zoo
