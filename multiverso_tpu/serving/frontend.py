"""Online serving frontend: the PS as a read-mostly inference service.

ROADMAP item 4, the "training + serving system" step (docs/SERVING.md):
an HTTP frontend running on worker ranks that turns the parameter
server's versioned, staleness-bounded read path into an inference
surface while a trainer concurrently pushes Adds. Endpoints:

- ``GET /v1/tables``                          — registered tables;
- ``GET /v1/tables/<name>/rows?ids=3,17,42``  — row read;
- ``GET /v1/tables/<name>/neighbors?word=w&k=8`` (or ``id=<row>``)
                                              — word2vec nearest
                                                neighbors by cosine;
- ``GET /v1/status``                          — admission + pressure
                                                (never shed: health
                                                must answer under
                                                overload).

Reads route through the PR-3 client cache (``tables/client_cache.py``:
version tracking, partial row hits, read-your-writes floors) and the
PR-7 replica striping underneath it — the PS itself only sees cache
misses. Every response carries the serving version, its staleness
bound, and a cache-hit marker (JSON fields + ``X-MV-*`` headers); the
reported ``max_staleness <= staleness_bound`` invariant holds even
while Adds land concurrently (``MatrixWorker.read_rows_versioned``).

Survival under load is delegated to ``serving/admission.py``: shed
requests answer ``429/503 + Retry-After`` with the precise
``retry_after_s`` in the JSON body; shutdown drains gracefully.

Built on the shared ``io/http_server.py`` base (the same plumbing as
the observability scrape surface). The frontend itself is runtime-thin:
it holds the zoo only for actor-mailbox pressure probes and never
imports table implementations — tables register by handle
(``mv.serve_table``) and are used duck-typed.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..runtime import thread_roles
from ..io.http_server import (HttpError, HttpServer, Response,
                              json_response)
from ..util import log
from ..util.configure import get_flag
from ..util.dashboard import count as count_event
from ..util.dashboard import samples
# The -serving_* flag definitions live in admission.py (imported
# eagerly by the zoo for parse-time registration; this module pulls in
# the io/ stack and cannot be imported that early).
from .admission import AdmissionController, ShedError
from .ann import IVFIndex
from .batch import BatchedTableReader, HotRowCache, UpstreamReadError

#: Metric names (util/dashboard.py METRIC_NAMES).
REQUESTS = "SERVING_REQUESTS"
LATENCY_MS = "SERVING_LATENCY_MS"
CACHE_HIT = "SERVING_CACHE_HIT"
ANN_PROBE_MS = "ANN_PROBE_MS"

#: Neighbor-endpoint k cap: top-k over the full table is O(rows) per
#: request regardless of k, but an unbounded k makes response bodies
#: a memory lever.
MAX_NEIGHBORS = 64

#: Actor registry names (runtime/actor.py) — plain strings here so the
#: serving package stays runtime-import-free (the zoo imports THIS
#: module eagerly for flag registration; an import back into runtime/
#: would cycle).
_SERVER, _WORKER, _COMMUNICATOR, _CONTROLLER = (
    "server", "worker", "communicator", "controller")


class _ServedTable:
    """Registry entry: a worker-table handle plus the serving-side
    per-table state — the index lock (whole-table snapshot fetches
    still ride the table's one-get-in-flight registers), the batched
    scatter reader + hot-response cache (serving/batch.py), and the
    lazily refreshed nearest-neighbor index (brute snapshot + the
    optional IVF structure over it, serving/ann.py)."""

    __slots__ = ("name", "table", "vocab", "words", "lock",
                 "index_version", "index_generation", "index_values",
                 "index_norms", "ivf", "reader", "hot")

    def __init__(self, name: str, table, vocab: Optional[Dict[str, int]]):
        self.name = name
        self.table = table
        self.vocab = dict(vocab) if vocab else None
        self.words: Optional[List[Optional[str]]] = None
        if self.vocab:
            self.words = [None] * int(table.num_row)
            for word, row in self.vocab.items():
                if 0 <= int(row) < len(self.words):
                    self.words[int(row)] = word
        self.lock = threading.Lock()
        self.index_version = -1
        self.index_generation = -1
        self.index_values: Optional[np.ndarray] = None
        self.index_norms: Optional[np.ndarray] = None
        self.ivf: Optional[IVFIndex] = None
        self.reader: Optional[BatchedTableReader] = None
        self.hot: Optional[HotRowCache] = None


class ServingFrontend(HttpServer):
    def __init__(self, zoo, port: Optional[int] = None,
                 host: str = "0.0.0.0"):
        self._zoo = zoo
        self._tables: Dict[str, _ServedTable] = {}
        self._tables_lock = threading.Lock()
        self._max_rows = int(get_flag("serving_max_rows", 4096))
        self._scatter = bool(get_flag("serving_scatter", True))
        self._ann_nlist = int(get_flag("ann_nlist", 0))
        self._ann_nprobe = int(get_flag("ann_nprobe", 8))
        self.admission = AdmissionController(
            depth_of=self._mailbox_depth)
        super().__init__(
            int(get_flag("serving_port", 0)) if port is None else port,
            self._resolve_path, host=host, name="serving")
        # Fleet-pressure reporting (docs/SERVING.md fleet section):
        # ship this frontend's admission stats to the controller on a
        # cadence; the reply carries the fleet aggregate /v1/status
        # exposes for external load balancers.
        self._fleet_stop = threading.Event()
        self._fleet_thread: Optional[threading.Thread] = None
        interval = float(get_flag("serving_fleet_interval_s", 2.0))
        if interval > 0:
            self._fleet_thread = thread_roles.spawn(
                thread_roles.BACKGROUND, target=self._fleet_main,
                args=(interval,),
                name=f"mv-serving-fleet-{self.port}")

    # -- registry --
    def register_table(self, name: str, table,
                       vocab: Optional[Dict[str, int]] = None) -> None:
        """Expose a worker table under ``/v1/tables/<name>``. ``table``
        must speak the serving read contract (``read_rows_versioned``;
        dense matrix worker tables do). ``vocab`` (word -> row id)
        additionally enables word lookups on the neighbors endpoint."""
        if not hasattr(table, "read_rows_versioned"):
            raise ValueError(
                f"table {name!r} ({type(table).__name__}) does not "
                f"support serving reads (read_rows_versioned) — only "
                f"dense matrix worker tables serve (docs/SERVING.md)")
        entry = _ServedTable(name, table, vocab)
        if self._scatter and hasattr(table, "read_rows_scatter") \
                and not getattr(table, "is_sparse", False):
            entry.reader = BatchedTableReader(
                name, table, lambda t=table: self._bound_of_table(t))
            if int(get_flag("serving_hot_rows", 4096)) > 0 \
                    and hasattr(table, "cache_generation"):
                entry.hot = HotRowCache(
                    table, lambda t=table: self._bound_of_table(t))
        with self._tables_lock:
            self._tables[name] = entry
        log.info("serving: table %r registered (%d x %d, scatter=%s, "
                 "hot_cache=%s)", name, table.num_row, table.num_col,
                 entry.reader is not None, entry.hot is not None)

    # -- pressure probe (admission's depth gate) --
    def _mailbox_depth(self) -> int:
        depth = 0
        for name in (_SERVER, _WORKER):
            actor = self._zoo._actors.get(name)
            if actor is not None:
                depth = max(depth, actor.mailbox.size())
        return depth

    def _mailbox_report(self) -> dict:
        # The communicator is registered in the zoo but owns no mailbox
        # (it routes inline on caller threads; runtime/communicator.py),
        # so only mailbox-bearing registrants report.
        report = {}
        for name in (_SERVER, _WORKER, _COMMUNICATOR):
            actor = self._zoo._actors.get(name)
            mailbox = getattr(actor, "mailbox", None)
            if mailbox is not None:
                report[name] = {
                    "depth": mailbox.size(),
                    "high_watermark": mailbox.depth_high_watermark}
        return report

    # -- routing --
    def _resolve_path(self, path: str):
        if path == "/v1/status":
            return self._status
        if path == "/v1/tables":
            return self._list_tables
        parts = [p for p in path.split("/") if p]
        if len(parts) == 4 and parts[0] == "v1" \
                and parts[1] == "tables":
            name, endpoint = parts[2], parts[3]
            if endpoint == "rows":
                return lambda query: self._rows(name, query)
            if endpoint == "neighbors":
                return lambda query: self._neighbors(name, query)
        return None

    def describe(self) -> str:
        return ("/v1/status, /v1/tables, /v1/tables/<name>/rows, "
                "/v1/tables/<name>/neighbors")

    def _entry(self, name: str) -> _ServedTable:
        with self._tables_lock:
            entry = self._tables.get(name)
        if entry is None:
            with self._tables_lock:
                known = sorted(self._tables)
            raise HttpError(404, f"no table named {name!r} "
                                 f"(registered: {known})")
        return entry

    def _admit(self, endpoint: str) -> None:
        """Admission gate -> HTTP: a shed becomes 429/503 with the
        integer-seconds Retry-After header (HTTP grammar) and the
        precise float in the body."""
        try:
            self.admission.admit(endpoint)
        except ShedError as exc:
            raise HttpError(
                exc.status, str(exc),
                headers={"Retry-After": str(
                    max(int(math.ceil(exc.retry_after_s)), 1))},
                extra={"retry_after_s": exc.retry_after_s,
                       "shed": True}) from exc

    # -- endpoints --
    def _status(self, query) -> Response:
        with self._tables_lock:
            tables = {name: {"num_row": int(e.table.num_row),
                             "num_col": int(e.table.num_col),
                             "vocab": e.vocab is not None}
                      for name, e in self._tables.items()}
        # Rank identity + the controller-aggregated fleet view: behind
        # a load balancer every frontend answers /v1/status, and
        # without these fields the ranks are indistinguishable and
        # only LOCAL pressure is visible (docs/SERVING.md fleet
        # section). fleet is None until the first report round trips
        # (or with -serving_fleet_interval_s=0).
        fleet = getattr(self._zoo, "serving_fleet", None)
        return json_response({
            "rank": int(self._zoo.rank),
            "tables": tables,
            "admission": self.admission.stats(),
            "mailboxes": self._mailbox_report(),
            "fleet": fleet() if callable(fleet) else None})

    def _list_tables(self, query) -> Response:
        with self._tables_lock:
            names = sorted(self._tables)
        return json_response({"tables": names})

    def _parse_ids(self, entry: _ServedTable, query) -> np.ndarray:
        raw = query.get("ids")
        if not raw:
            raise HttpError(400, "missing ids= (comma-separated row "
                                 "ids)")
        try:
            ids = np.asarray([int(v) for v in raw.split(",") if v],
                             dtype=np.int32)
        except ValueError:
            raise HttpError(400, f"unparseable ids {raw!r}") from None
        if ids.size == 0:
            raise HttpError(400, "empty ids list")
        if ids.size > self._max_rows:
            raise HttpError(400, f"{ids.size} ids exceeds the "
                                 f"per-request cap "
                                 f"(-serving_max_rows="
                                 f"{self._max_rows})")
        if ids.min() < 0 or ids.max() >= entry.table.num_row:
            raise HttpError(400, f"row ids out of range [0, "
                                 f"{entry.table.num_row})")
        return ids

    def _rows(self, name: str, query) -> Response:
        entry = self._entry(name)
        ids = self._parse_ids(entry, query)
        self._admit("rows")
        t0 = time.perf_counter()
        try:
            # Hot-response cache first: the Zipf head serves straight
            # from rendered rows — no table call, no device, not even
            # the ndarray->list prep (serving/batch.py HotRowCache;
            # freshness = staleness bound + data generation).
            if entry.hot is not None:
                served = entry.hot.lookup(ids)
                if served is not None:
                    rendered, meta = served
                    count_event(CACHE_HIT)
                    return self._rows_response(
                        name, ids, rendered, meta, t0,
                        response_cache="hit")
            if entry.reader is not None:
                try:
                    values, meta, detail = entry.reader.read(ids)
                except UpstreamReadError as exc:
                    # Row-scoped upstream failure (dead shard owner /
                    # timeout): typed retryable rejection naming
                    # exactly the affected rows — rows on healthy
                    # shards in OTHER requests of the same batch were
                    # served normally, and a wrong value is never
                    # substituted.
                    retry = self.admission.retry_after_s
                    if exc.retryable:
                        raise HttpError(
                            503, str(exc),
                            headers={"Retry-After": str(max(
                                int(math.ceil(retry)), 1))},
                            extra={"retry_after_s": retry,
                                   "failed_rows": exc.rows,
                                   "retryable": True}) from exc
                    raise HttpError(
                        500, str(exc),
                        extra={"failed_rows": exc.rows,
                               "retryable": False}) from exc
                if entry.hot is not None:
                    entry.hot.store(detail)
                rendered = np.asarray(values).tolist()
            else:
                # -serving_scatter=false escape hatch: the serialized
                # PR-10 one-get-in-flight path.
                with entry.lock:
                    values, meta = entry.table.read_rows_versioned(ids)
                rendered = np.asarray(values).tolist()
            return self._rows_response(name, ids, rendered, meta, t0)
        finally:
            self.admission.release("rows")

    def _rows_response(self, name: str, ids: np.ndarray,
                       rendered: List, meta: dict, t0: float,
                       response_cache: str = "miss") -> Response:
        samples(LATENCY_MS).add((time.perf_counter() - t0) * 1e3)
        count_event(REQUESTS)
        return json_response(
            {"table": name, "ids": ids.tolist(), "rows": rendered,
             "response_cache": response_cache, **meta},
            headers=self._meta_headers(meta))

    @staticmethod
    def _meta_headers(meta: dict) -> Dict[str, str]:
        return {"X-MV-Version": str(meta["served_version"]),
                "X-MV-Latest-Version": str(meta["latest_version"]),
                "X-MV-Staleness-Bound": str(meta["staleness_bound"]),
                "X-MV-Cache":
                    "hit" if meta.get("cache_hit") else "miss"}

    # -- nearest neighbors (the word2vec inference demo) --
    def _neighbors(self, name: str, query) -> Response:
        entry = self._entry(name)
        try:
            k = int(query.get("k", "8"))
        except ValueError:
            raise HttpError(400, f"unparseable k {query.get('k')!r}") \
                from None
        k = min(max(k, 1), MAX_NEIGHBORS)
        word = query.get("word")
        if word is not None:
            if not entry.vocab:
                raise HttpError(400, f"table {name!r} has no vocab — "
                                     f"query by id= instead")
            row = entry.vocab.get(word)
            if row is None or not 0 <= int(row) < entry.table.num_row:
                raise HttpError(404, f"unknown word {word!r}")
            row = int(row)
        else:
            raw = query.get("id")
            if raw is None:
                raise HttpError(400, "need word= or id=")
            try:
                row = int(raw)
            except ValueError:
                raise HttpError(400, f"unparseable id {raw!r}") \
                    from None
            if not 0 <= row < entry.table.num_row:
                raise HttpError(400, f"row id {row} out of range "
                                     f"[0, {entry.table.num_row})")
        brute = query.get("brute") == "1"
        try:
            nprobe = int(query.get("nprobe", self._ann_nprobe))
        except ValueError:
            raise HttpError(400, f"unparseable nprobe "
                                 f"{query.get('nprobe')!r}") from None
        self._admit("neighbors")
        t0 = time.perf_counter()
        try:
            with entry.lock:
                refreshed = self._refresh_index(entry)
                values = entry.index_values
                norms = entry.index_norms
                index_version = entry.index_version
                ivf = entry.ivf
            # Scoring stays INSIDE the admission bracket: the scan
            # (IVF probe or the O(rows x cols) brute matmul) + top-k
            # is this endpoint's dominant cost, and releasing before
            # it would let an unbounded number of scoring threads run
            # concurrently — exactly the accepted-p99 convoy the
            # in-flight cap exists to prevent.
            q = values[row]
            if ivf is not None and not brute:
                # Probe-only timing: t0 would fold in the lock wait
                # and any index REBUILD (a whole-table fetch +
                # k-means), burying probe-latency regressions.
                t_probe = time.perf_counter()
                top_ids, top_scores, scanned = ivf.search(
                    q, k, nprobe, exclude=row)
                samples(ANN_PROBE_MS).add(
                    (time.perf_counter() - t_probe) * 1e3)
                index_kind = {"kind": "ivf", "nlist": ivf.nlist,
                              "nprobe": min(max(nprobe, 1), ivf.nlist),
                              "candidates": scanned}
            else:
                qn = float(np.linalg.norm(q))
                scores = (values @ q) / (norms * max(qn, 1e-12))
                scores[row] = -np.inf  # not its own neighbor
                top = np.argpartition(-scores,
                                      min(k, scores.size - 1))[:k]
                top_ids = top[np.argsort(-scores[top])]
                top_scores = scores[top_ids]
                index_kind = {"kind": "brute",
                              "candidates": int(scores.size)}
            neighbors = []
            for i, s in zip(top_ids, top_scores):
                item = {"id": int(i), "score": round(float(s), 6)}
                if entry.words is not None \
                        and entry.words[int(i)] is not None:
                    item["word"] = entry.words[int(i)]
                neighbors.append(item)
        finally:
            self.admission.release("neighbors")
        samples(LATENCY_MS).add((time.perf_counter() - t0) * 1e3)
        count_event(REQUESTS)
        latest = max(entry.table.observed_versions().values(),
                     default=-1)
        bound = self._bound_of(entry)
        meta = {"served_version": int(index_version),
                "latest_version": int(latest),
                "staleness_bound": int(bound),
                "cache_hit": not refreshed}
        return json_response(
            {"table": name,
             "query": {"id": int(row),
                       **({"word": word} if word is not None else {})},
             "k": k, "neighbors": neighbors, "index": index_kind,
             "index_refreshed": bool(refreshed), **meta},
            headers=self._meta_headers(meta))

    @staticmethod
    def _bound_of_table(table) -> int:
        cache = getattr(table, "_row_cache", None)
        return int(cache.bound) if cache is not None else 0

    @classmethod
    def _bound_of(cls, entry: _ServedTable) -> int:
        return cls._bound_of_table(entry.table)

    @staticmethod
    def _generation_of(entry: _ServedTable) -> int:
        gen = getattr(entry.table, "cache_generation", None)
        return int(gen()) if callable(gen) else 0

    def _refresh_index(self, entry: _ServedTable) -> bool:
        """Refresh the neighbor index when it has aged past the
        staleness bound — the SAME freshness rule the row cache
        applies, lifted to the whole-table snapshot: an index built
        when the newest observed shard version was ``v`` serves while
        ``latest - v <= bound`` — OR when the table's data generation
        changed (elastic reshard / server rejoin). Version staleness
        alone misses the latter: a restored or remapped shard's
        counter can restart BELOW the index anchor, so ``latest -
        index_version`` stays negative forever while the underlying
        rows change arbitrarily. Caller holds ``entry.lock``."""
        latest = max(entry.table.observed_versions().values(),
                     default=-1)
        generation = self._generation_of(entry)
        if entry.index_values is not None \
                and generation == entry.index_generation \
                and latest - entry.index_version <= \
                self._bound_of(entry):
            return False
        # Anchor to the versions observed BEFORE the fetch (the
        # read_rows_versioned rule): the get returns data at least
        # this fresh, while anchoring AFTER it would credit the index
        # with add-acks that landed mid-fetch — under a concurrent
        # trainer the index would then serve past the bound
        # undetected and served_version would overstate the snapshot.
        # The generation is pre-anchored for the same reason: a
        # reshard landing mid-fetch must invalidate THIS build.
        entry.index_version = latest
        entry.index_generation = generation
        values = np.array(self._fetch_all(entry), copy=True)
        entry.index_values = values
        norms = np.linalg.norm(values, axis=1)
        entry.index_norms = np.maximum(norms, 1e-12)
        entry.ivf = None
        if self._ann_nlist > 0:
            t0 = time.perf_counter()
            entry.ivf = IVFIndex(values, entry.index_norms,
                                 self._ann_nlist)
            log.debug("serving: IVF index for %r rebuilt (%d lists, "
                      "%.1f ms)", entry.name, entry.ivf.nlist,
                      (time.perf_counter() - t0) * 1e3)
        return True

    @staticmethod
    def _fetch_all(entry: _ServedTable) -> np.ndarray:
        return entry.table.get()

    # -- fleet-pressure reporting (docs/SERVING.md fleet section) --
    def _fleet_main(self, interval: float) -> None:
        """Reporter thread: every ``interval`` ship this frontend's
        admission pressure to the controller (Control_Serving_Report)
        and let the reply refresh the zoo's fleet-aggregate view.
        Frames ride ``net.send_async`` — never the communicator
        mailbox, whose dispatch thread can park toward a dead peer
        (the PR-6 liveness-frame discipline)."""
        while not self._fleet_stop.wait(timeout=interval):
            try:
                self._send_fleet_report()
            except Exception as exc:  # noqa: BLE001 - reporting is
                # best-effort; a hiccup must not kill the thread
                log.debug("serving: fleet report failed: %s", exc)

    def _send_fleet_report(self) -> None:
        from ..core.blob import Blob
        from ..core.message import Message, MsgType
        from ..runtime.zoo import CONTROLLER_RANK
        stats = self.admission.stats()
        msg = Message(src=self._zoo.rank, dst=CONTROLLER_RANK,
                      msg_type=MsgType.Control_Serving_Report)
        msg.push(Blob(np.asarray(
            [self._zoo.rank, stats["admitted"], stats["shed"],
             sum(stats["inflight"].values())], dtype=np.int64)))
        if self._zoo.rank == CONTROLLER_RANK:
            controller = self._zoo._actors.get(_CONTROLLER)
            if controller is not None:
                controller.receive(msg)
        else:
            self._zoo.net.send_async(msg)

    # -- lifecycle --
    def stop(self) -> None:
        """Graceful drain, then close: new requests reject with 503
        immediately; in-flight ones get up to ``-serving_drain_s``."""
        self._fleet_stop.set()
        if self._fleet_thread is not None:
            self._fleet_thread.join(timeout=5)
            self._fleet_thread = None
        drained = self.admission.begin_drain()
        if not drained:
            log.error("serving: drain timed out with requests still "
                      "in flight — closing anyway (%s)",
                      self.admission.stats()["inflight"])
        # Batcher threads stop AFTER the drain: in-flight requests may
        # still be parked on a batch that must execute.
        with self._tables_lock:
            entries = list(self._tables.values())
        for entry in entries:
            if entry.reader is not None:
                entry.reader.stop()
        super().stop()
