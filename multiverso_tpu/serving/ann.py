"""IVF approximate-nearest-neighbor index for the serving tier.

The PR-10 neighbors endpoint scored every row per query — an
O(rows x dims) cosine matmul that caps a frontend at a few hundred
QPS and scales linearly with the table. This module replaces it with
a classic two-level inverted-file (IVF) search over the SAME
staleness-bounded snapshot the brute scan used (docs/SERVING.md):

1. **build** — a k-means coarse quantizer over the row directions
   (unit vectors; cosine similarity is dot product after
   normalization) partitions the rows into ``nlist`` inverted lists;
2. **search** — a query scores the ``nlist`` centroids (tiny), scans
   only the ``nprobe`` closest lists, and exact-scores those
   candidates — ``~nprobe/nlist`` of the table per query.

Recall is a knob, not a constant: embedding tables are clustered by
construction (that is what training does), so small ``nprobe``
reaches high recall; the bench measures recall@10 against the brute
scan and the endpoint keeps a ``brute=1`` escape hatch. The index is
a DERIVED cache: it rebuilds under the same pre-fetch-anchored
version rule as the brute snapshot, plus forced invalidation on a
data-generation change (reshard / server rejoin — see
``WorkerTable.cache_generation``).

Pure numpy, host-side: the snapshot is already host memory and a
query touches a few thousand rows — a device roundtrip per request
would cost more than it saves.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

#: k-means refinement passes. Lloyd converges fast on the sampled
#: training set and the quantizer only has to be balanced, not
#: optimal — recall comes from nprobe, not centroid perfection.
_KMEANS_ITERS = 6

#: Rows sampled for centroid training on big tables: k-means cost is
#: O(sample x nlist x dims x iters) and a subsample trains an
#: equally-good quantizer; ASSIGNMENT still covers every row.
_KMEANS_SAMPLE = 16384


def _unit_rows(values: np.ndarray) -> np.ndarray:
    norms = np.linalg.norm(values, axis=1)
    return values / np.maximum(norms, 1e-12)[:, None]


class IVFIndex:
    """Inverted-file cosine index over a fixed snapshot.

    ``values`` is the ``[N, D]`` snapshot (NOT copied — the caller
    owns snapshot lifetime, exactly as with the brute scan's
    ``index_values``); ``norms`` its per-row L2 norms.
    """

    def __init__(self, values: np.ndarray, norms: np.ndarray,
                 nlist: int, seed: int = 0):
        n = values.shape[0]
        unit = _unit_rows(values)
        rng = np.random.default_rng(seed)
        train = unit if n <= _KMEANS_SAMPLE else \
            unit[rng.choice(n, _KMEANS_SAMPLE, replace=False)]
        # Clamped to the TRAINING sample, not just the table: each
        # centroid seeds on a distinct training row, so an oversized
        # -ann_nlist on a big table must not ask for more seeds than
        # the sample holds.
        self.nlist = int(max(1, min(nlist, train.shape[0])))
        centroids = train[rng.choice(train.shape[0], self.nlist,
                                     replace=False)]
        for _ in range(_KMEANS_ITERS):
            assign = np.argmax(train @ centroids.T, axis=1)
            for c in range(self.nlist):
                members = train[assign == c]
                if members.shape[0]:
                    mean = members.mean(axis=0)
                    centroids[c] = mean / max(
                        float(np.linalg.norm(mean)), 1e-12)
                else:
                    # Empty cluster: reseed on a random training row so
                    # no list degenerates to zero coverage.
                    centroids[c] = train[rng.integers(train.shape[0])]
        self.centroids = centroids
        # Full-table assignment + CSR-style inverted lists: rows
        # sorted by cluster, offsets[c]:offsets[c+1] slices cluster c.
        # The VALUES are stored cluster-sorted too (one extra snapshot
        # copy): a probe then scores a few CONTIGUOUS slices instead
        # of fancy-index gathering thousands of scattered rows — the
        # gather's cache misses, not the flops, dominated the scan.
        assign_all = np.argmax(unit @ centroids.T, axis=1)
        self._order = np.argsort(assign_all, kind="stable") \
            .astype(np.int64)
        self._offsets = np.searchsorted(
            assign_all[self._order], np.arange(self.nlist + 1))
        self._sorted_values = np.ascontiguousarray(values[self._order])
        self._sorted_norms = np.ascontiguousarray(
            np.maximum(norms[self._order], 1e-12))

    def search(self, query: np.ndarray, k: int, nprobe: int,
               exclude: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray, int]:
        """Top-``k`` rows by cosine against ``query`` scanning the
        ``nprobe`` closest inverted lists. Returns ``(ids, scores,
        candidates_scanned)``; ``exclude`` drops one row id (the
        query row is not its own neighbor)."""
        nprobe = int(max(1, min(nprobe, self.nlist)))
        qn = max(float(np.linalg.norm(query)), 1e-12)
        qunit = (query / qn).astype(np.float32, copy=False)
        cscores = self.centroids @ qunit
        if nprobe < self.nlist:
            probe = np.argpartition(-cscores, nprobe - 1)[:nprobe]
        else:
            probe = np.arange(self.nlist)
        id_parts, score_parts = [], []
        for c in probe:
            lo, hi = self._offsets[c], self._offsets[c + 1]
            if lo == hi:
                continue
            id_parts.append(self._order[lo:hi])
            score_parts.append(
                (self._sorted_values[lo:hi] @ qunit)
                / self._sorted_norms[lo:hi])
        if not id_parts:
            return (np.empty(0, np.int64), np.empty(0, np.float32), 0)
        cand = np.concatenate(id_parts)
        scores = np.concatenate(score_parts)
        if exclude is not None:
            keep = cand != exclude
            cand, scores = cand[keep], scores[keep]
        if cand.size == 0:
            return (np.empty(0, np.int64), np.empty(0, np.float32), 0)
        k = min(k, cand.size)
        top = np.argpartition(-scores, k - 1)[:k]
        top = top[np.argsort(-scores[top])]
        return cand[top], scores[top], int(cand.size)
