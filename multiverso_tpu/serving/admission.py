"""Admission control and backpressure for the serving frontend.

The serving tier's survival-under-load half (docs/SERVING.md): a
Zipf-skewed user-read flood must degrade into FAST TYPED REJECTIONS,
never into an unbounded queue. Three gates, checked in order per
request:

1. **drain gate** — a frontend shutting down rejects new work (503)
   while in-flight requests finish (``begin_drain`` waits for them,
   bounded by ``-serving_drain_s``);
2. **mailbox-pressure gate** — the actor mailboxes behind the reads
   (server/worker, ``MtQueue.track_depth``) are the real queue; when
   the observed depth exceeds the ``-serving_shed_depth`` high
   watermark, admitting more reads only lengthens every queued
   trainer Add and user read, so the request sheds (429);
3. **per-endpoint in-flight cap** — ``-serving_max_inflight``
   concurrent requests per endpoint; the cap bounds the frontend's own
   thread/table-lock convoy so the p99 of ACCEPTED requests stays flat
   under overload instead of collapsing.

A shed is a ``ShedError``: typed, retryable, carrying the machine
fields the HTTP layer maps to ``429/503 + Retry-After``
(``-serving_retry_after_s``). Shed decisions never block and never
allocate — under overload the reject path IS the hot path.
"""

from __future__ import annotations

import itertools
import time
from typing import Callable, Dict, Optional

from ..util.configure import (define_bool, define_double, define_int,
                              get_flag, register_tunable_hook)
from ..util.dashboard import count as count_event
from ..util.lock_witness import named_condition, named_lock

# ALL serving flags are registered here (not in frontend.py): the zoo
# imports this module eagerly so -serving_* parse at init, and this
# module is the one corner of the serving package that imports neither
# the HTTP stack nor anything under io/ or runtime/ — the frontend
# would cycle (io/__init__ -> stream -> runtime.zoo).
define_int("serving_port", 0,
           "start the online serving frontend (docs/SERVING.md) on "
           "this port on every rank hosting a worker actor; 0 "
           "(default) = serving off. Port 0 is never ephemeral here — "
           "tests construct ServingFrontend directly for that")
define_int("serving_max_rows", 4096,
           "per-request row cap on the serving frontend's rows "
           "endpoint: larger id lists answer 400 (one request must "
           "not monopolize the table lock)")
define_int("serving_max_inflight", 64,
           "per-endpoint cap on concurrently admitted serving-frontend "
           "requests: arrivals past it shed with a retryable 429 + "
           "Retry-After instead of convoying on the table lock. "
           "0 disables the cap")
define_int("serving_shed_depth", 256,
           "actor-mailbox depth high watermark for the serving "
           "frontend's load shedding: requests arriving while the "
           "deepest local server/worker mailbox exceeds this shed with "
           "429 + Retry-After (admitting more reads would only "
           "lengthen every queued request). 0 disables depth shedding")
define_double("serving_retry_after_s", 0.05,
              "the retry hint a shed serving request carries: rounded "
              "up to whole seconds in the Retry-After header (HTTP "
              "grammar), exact in the JSON body's retry_after_s")
define_double("serving_drain_s", 5.0,
              "graceful-drain bound at serving-frontend shutdown: new "
              "requests are rejected (503) immediately, in-flight ones "
              "get up to this many seconds to finish before the HTTP "
              "server closes")
define_bool("serving_scatter", True,
            "serve multi-row reads through the concurrent scatter-"
            "gather read path (read_rows_scatter: per-shard-owner "
            "sub-requests, partial-failure containment, request "
            "batching). false = the serialized PR-10 per-request "
            "read_rows_versioned path (A/B escape hatch)")
define_double("serving_batch_window_ms", 2.0,
              "request-batching window on the serving frontend's rows "
              "endpoint: concurrent reads arriving within this many "
              "ms fold into ONE scatter-gather table read (one device "
              "gather per shard per batch instead of per request). "
              "0 = no batching, each request issues its own scatter "
              "read (still concurrent-safe)")
define_int("serving_batch_max_rows", 1024,
           "size cap on one serving read batch, in merged unique "
           "rows: a batch reaching it flushes immediately instead of "
           "waiting out the window (bounds per-gather payload and "
           "worst-case head-of-line latency)")
define_int("serving_hot_rows", 4096,
           "row capacity of the serving frontend's hot-response "
           "cache: per-row rendered responses keyed on (table, row, "
           "served_version), served without touching the worker "
           "table while fresh within the staleness bound (and the "
           "data generation — reshard/rejoin force-invalidate). "
           "0 disables it")
define_double("serving_fleet_interval_s", 2.0,
              "how often a serving frontend reports its admission "
              "pressure to the controller and refreshes the fleet-"
              "aggregate view /v1/status exposes (rank identity + "
              "fleet-wide in-flight/shed counters, for external load "
              "balancers). 0 disables fleet reporting")
define_int("ann_nlist", 0,
           "IVF coarse-quantizer cluster count for the serving "
           "neighbors endpoint: > 0 replaces the O(rows x dims) "
           "linear cosine scan with an inverted-file search over the "
           "same staleness-bounded snapshot (k-means over unit "
           "vectors, rebuilt with the index). 0 (default) keeps the "
           "exact brute-force scan")
define_int("ann_nprobe", 8,
           "how many IVF clusters a neighbors query scans (recall/"
           "latency knob; per-request override via ?nprobe=). Clamped "
           "to -ann_nlist; brute=1 on the query string bypasses the "
           "index entirely")

#: Metric names (util/dashboard.py METRIC_NAMES).
SHED = "SERVING_SHED"

_serial = itertools.count()


class ShedError(RuntimeError):
    """A request the frontend refused to admit. Retryable by
    construction — the client backs off ``retry_after_s`` and
    re-issues; nothing about the request itself was wrong."""

    def __init__(self, reason: str, retry_after_s: float,
                 status: int = 429):
        super().__init__(reason)
        self.retry_after_s = float(retry_after_s)
        self.status = int(status)


class AdmissionController:
    """Bounded admission over named endpoints.

    ``depth_of`` is the mailbox-pressure probe (max depth across the
    rank's server/worker actor mailboxes, injected by the frontend so
    this module stays runtime-import-free). ``admit``/``release``
    bracket every admitted request; ``begin_drain`` flips the drain
    gate and waits (bounded) for in-flight work.
    """

    def __init__(self, depth_of: Optional[Callable[[], int]] = None,
                 max_inflight: Optional[int] = None,
                 shed_depth: Optional[int] = None,
                 retry_after_s: Optional[float] = None):
        self._depth_of = depth_of
        self._max_inflight = int(
            get_flag("serving_max_inflight", 64)
            if max_inflight is None else max_inflight)
        self._shed_depth = int(
            get_flag("serving_shed_depth", 256)
            if shed_depth is None else shed_depth)
        self._retry_after = float(
            get_flag("serving_retry_after_s", 0.05)
            if retry_after_s is None else retry_after_s)
        serial = next(_serial)
        self._lock = named_lock(f"serving.admission[{serial}]")
        self._idle = named_condition(
            f"serving.admission[{serial}].idle", self._lock)
        self._inflight: Dict[str, int] = {}
        self._total = 0
        self._draining = False
        self.admitted = 0
        self.shed = 0
        # Live retuning (docs/AUTOTUNE.md): both watermarks were
        # cached above at construction — a Control_Config broadcast
        # lands through these hooks (weakly held; a stopped frontend's
        # controller unregisters itself via GC).
        register_tunable_hook("serving_max_inflight",
                              self._retune_max_inflight)
        register_tunable_hook("serving_shed_depth",
                              self._retune_shed_depth)

    def _retune_max_inflight(self, value) -> None:
        self.configure(max_inflight=int(value))

    def _retune_shed_depth(self, value) -> None:
        self.configure(shed_depth=int(value))

    def configure(self, max_inflight: Optional[int] = None,
                  shed_depth: Optional[int] = None,
                  retry_after_s: Optional[float] = None) -> None:
        """Re-knob a live controller (bench overload arms and tests;
        production sets the flags before init)."""
        with self._lock:
            if max_inflight is not None:
                self._max_inflight = int(max_inflight)
            if shed_depth is not None:
                self._shed_depth = int(shed_depth)
            if retry_after_s is not None:
                self._retry_after = float(retry_after_s)

    @property
    def retry_after_s(self) -> float:
        return self._retry_after

    # -- the per-request bracket --
    def admit(self, endpoint: str) -> None:
        """Admit or raise ``ShedError``; a successful admit MUST be
        paired with ``release(endpoint)`` (the frontend's finally)."""
        # Depth probe outside the admission lock: it reads other locks
        # (mailbox mutexes) and must not nest under ours.
        if self._depth_of is not None and self._shed_depth > 0:
            depth = self._depth_of()
            if depth > self._shed_depth:
                self._note_shed()
                raise ShedError(
                    f"mailbox depth {depth} over the "
                    f"{self._shed_depth} shed watermark "
                    f"(-serving_shed_depth)", self._retry_after)
        with self._lock:
            if self._draining:
                reason, status = "serving frontend draining", 503
            elif 0 < self._max_inflight \
                    <= self._inflight.get(endpoint, 0):
                reason, status = (
                    f"{endpoint}: {self._inflight[endpoint]} requests "
                    f"already in flight (-serving_max_inflight="
                    f"{self._max_inflight})", 429)
            else:
                self._inflight[endpoint] = \
                    self._inflight.get(endpoint, 0) + 1
                self._total += 1
                self.admitted += 1
                return
        self._note_shed()
        raise ShedError(reason, self._retry_after, status=status)

    def release(self, endpoint: str) -> None:
        with self._lock:
            n = self._inflight.get(endpoint, 0) - 1
            if n > 0:
                self._inflight[endpoint] = n
            else:
                self._inflight.pop(endpoint, None)
            self._total = max(self._total - 1, 0)
            if self._total == 0:
                self._idle.notify_all()

    def _note_shed(self) -> None:
        with self._lock:
            self.shed += 1
        count_event(SHED)

    # -- graceful drain (frontend shutdown) --
    def begin_drain(self, timeout_s: Optional[float] = None) -> bool:
        """Reject new requests from now on; wait (bounded) for the
        in-flight ones. True when the frontend drained clean."""
        if timeout_s is None:
            timeout_s = float(get_flag("serving_drain_s", 5.0))
        deadline = time.monotonic() + max(float(timeout_s), 0.0)
        with self._lock:
            self._draining = True
            while self._total > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(timeout=min(remaining, 0.5))
            return True

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def stats(self) -> dict:
        with self._lock:
            return {"admitted": self.admitted, "shed": self.shed,
                    "inflight": dict(self._inflight),
                    "draining": self._draining,
                    "max_inflight": self._max_inflight,
                    "shed_depth": self._shed_depth,
                    "retry_after_s": self._retry_after}
