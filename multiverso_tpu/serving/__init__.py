"""Online serving tier: the parameter server as a read-mostly
inference service (docs/SERVING.md).

``frontend.ServingFrontend`` is the HTTP surface (started by the zoo
on ``-serving_port``, tables registered via ``mv.serve_table``);
``admission.AdmissionController`` is its survival-under-load half
(in-flight caps, mailbox-depth shedding, graceful drain).

``ServingFrontend`` is re-exported LAZILY: the zoo imports this
package at module load for -serving_* flag registration
(``admission.py``), before ``io/``'s stream module — which the
frontend pulls in — can be imported without a cycle.
"""

from .admission import AdmissionController, ShedError

__all__ = ["AdmissionController", "ServingFrontend", "ShedError"]


def __getattr__(name):
    if name == "ServingFrontend":
        from .frontend import ServingFrontend
        return ServingFrontend
    raise AttributeError(name)
