"""Request batching and hot-response caching for the serving frontend.

Two layers between the HTTP handler threads and the worker table's
scatter-gather read path (docs/SERVING.md fleet section):

**BatchedTableReader** — concurrent HTTP reads landing within a
``-serving_batch_window_ms`` window fold into ONE merged
``read_rows_scatter`` call: one device gather per shard per BATCH
instead of per request (the gather program is jitted per bucket
width, so folding N requests into one id set also folds N program
launches into one). A batch flushes on its window deadline or when
its merged row count reaches ``-serving_batch_max_rows``, whichever
first — a lone request therefore never waits longer than the window.
Failures are row-scoped end to end: a sub-request that died (dead
shard owner, RPC timeout) fails only the batch members whose rows it
carried, as a typed retryable ``UpstreamReadError`` the frontend maps
to ``503 + Retry-After``; every other member serves normally.

**HotRowCache** — rendered per-row response payloads keyed on
``(table, row, served_version)``: the Zipf head of a read workload is
a handful of rows requested thousands of times per second, and while
a row's fetch version is within the staleness bound of the owner's
latest OBSERVED version there is nothing to recompute — not even the
``ndarray -> list`` JSON prep. Freshness rides the existing
``VersionTracker`` machinery (``observed_versions``); a data-
generation change (elastic reshard, server rejoin — events that make
version arithmetic against the old shard counters meaningless) is a
FORCED invalidation via ``WorkerTable.cache_generation``.
"""

from __future__ import annotations

import itertools
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..util import log
from ..util.configure import get_flag, register_tunable_hook
from ..util.dashboard import samples
from ..util.lock_witness import named_condition, named_lock

#: Metric names (util/dashboard.py METRIC_NAMES).
BATCH_SIZE = "SERVING_BATCH_SIZE"

_serial = itertools.count()


class UpstreamReadError(RuntimeError):
    """A serving read failed upstream (dead shard owner, timeout,
    table error) for ``rows``. ``retryable`` mirrors the table
    layer's typed-failure split: True maps to 503 + Retry-After (the
    client backs off and re-issues), False to 500."""

    def __init__(self, reason: str, rows: List[int],
                 retryable: bool = True):
        super().__init__(reason)
        self.rows = [int(r) for r in rows]
        self.retryable = bool(retryable)


def request_meta(info: dict, pos: np.ndarray, bound: int) -> dict:
    """Per-request serving metadata from a (possibly merged) scatter
    read's ``info`` arrays at positions ``pos`` — the same fields and
    anchoring rule as ``read_rows_versioned`` (shard latests read
    BEFORE the fetch, so ``max_staleness <= bound`` is race-free
    under concurrent Adds)."""
    versions = info["versions"][pos]
    owners = info["owners"][pos]
    latest_map = info["latest_by_sid"]
    row_latest = np.asarray([latest_map[int(o)] for o in owners],
                            dtype=np.int64)
    # -1 = wire-fresh-but-unstamped/absent: staleness 0 by the
    # read_rows_versioned precedent.
    eff = np.where(versions >= 0, versions, row_latest)
    latest = int(max(row_latest.max(initial=-1), eff.max(initial=-1)))
    served = int(eff.min()) if eff.size else latest
    max_stale = int(np.maximum(row_latest - eff, 0).max(initial=0))
    cached = info["cached"][pos]
    return {"served_version": served, "latest_version": latest,
            "max_staleness": max_stale,
            "staleness_bound": int(bound),
            "cache_hit": bool(cached.all()) if cached.size else False,
            "rows_requested": int(pos.size),
            "rows_cached": int(cached.sum())}


class _PendingRead:
    __slots__ = ("ids", "uniq", "done", "values", "meta", "detail",
                 "error")

    def __init__(self, ids: np.ndarray):
        import threading
        self.ids = ids
        self.uniq = np.unique(ids)
        self.done = threading.Event()
        self.values = None
        self.meta = None
        self.detail = None
        self.error: Optional[Exception] = None


class BatchedTableReader:
    """Per-served-table read batcher. ``bound_of`` injects the active
    staleness bound (the frontend already owns that probe). Flags are
    read at construction, like every other serving knob."""

    def __init__(self, name: str, table,
                 bound_of: Callable[[], int],
                 window_ms: Optional[float] = None,
                 max_rows: Optional[int] = None):
        import threading
        self._name = name
        self._table = table
        self._bound_of = bound_of
        self._window = (float(get_flag("serving_batch_window_ms", 2.0))
                        if window_ms is None else float(window_ms)) \
            / 1e3
        self._max_rows = int(get_flag("serving_batch_max_rows", 1024)
                             if max_rows is None else max_rows)
        serial = next(_serial)
        self._lock = named_lock(f"serving.batch[{serial}]")
        self._cond = named_condition(f"serving.batch[{serial}].arrive",
                                     self._lock)
        self._pending: List[_PendingRead] = []  # guarded_by: _lock
        #: MERGED unique rows of the open batch (the documented
        #: -serving_batch_max_rows unit): counting the per-request sum
        #: would flush early exactly in the high-overlap regime where
        #: folding pays most.
        self._pending_row_set: set = set()  # guarded_by: _lock
        self._open_t = 0.0  # guarded_by: _lock
        self._stopping = False  # guarded_by: _lock
        self.batches = 0      # observability (tests/bench)
        self.requests = 0
        self._thread = None
        if self._window > 0:
            from ..runtime import thread_roles
            self._thread = thread_roles.spawn(
                thread_roles.BACKGROUND, target=self._run,
                name=f"mv-serving-batch-{name}")
        # Live retuning (docs/AUTOTUNE.md): the batcher thread reads
        # _window/_max_rows fresh per batch, so rebinding them is
        # picked up on the next window (a live window change cannot
        # START a batcher constructed with window 0 — the serve-single
        # path stays). Registered LAST: a broadcast may fire the hooks
        # from the recv thread immediately, and they take self._lock.
        register_tunable_hook("serving_batch_window_ms",
                              self._retune_window)
        register_tunable_hook("serving_batch_max_rows",
                              self._retune_max_rows)

    # -- live retuning (dynamic-flag apply hooks) --
    def _retune_window(self, value) -> None:
        with self._lock:
            self._window = max(float(value), 0.0) / 1e3
            self._cond.notify_all()  # an open window re-reads its
            # deadline against the new value immediately

    def _retune_max_rows(self, value) -> None:
        with self._lock:
            self._max_rows = max(int(value), 1)
            self._cond.notify_all()

    # -- the handler-thread API --
    def read(self, ids: np.ndarray):
        """Blocking read for one request's id vector (duplicates and
        order preserved in the returned values). Returns ``(values,
        meta, detail)`` — ``detail`` feeds the hot-response cache.
        Raises ``UpstreamReadError`` for row-scoped failures."""
        if self._thread is None:
            return self._serve_single(ids)
        req = _PendingRead(ids)
        with self._lock:
            if self._stopping:
                raise UpstreamReadError(
                    f"table {self._name!r}: reader stopped", [],
                    retryable=False)
            if not self._pending:
                self._open_t = time.monotonic()
            self._pending.append(req)
            self._pending_row_set.update(int(r) for r in req.uniq)
            self._cond.notify_all()
        # Generous bound: the scatter read itself raises on
        # -rpc_timeout_s; this only guards a dead batcher thread.
        if not req.done.wait(timeout=120.0):
            raise UpstreamReadError(
                f"table {self._name!r}: batched read timed out",
                req.uniq.tolist())
        if req.error is not None:
            raise req.error
        return req.values, req.meta, req.detail

    def _serve_single(self, ids: np.ndarray):
        req = _PendingRead(ids)
        self._execute([req])
        if req.error is not None:
            raise req.error
        return req.values, req.meta, req.detail

    # -- the batcher thread --
    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._stopping:
                    self._cond.wait(timeout=0.5)
                if self._stopping and not self._pending:
                    return
                # Window open: collect until the deadline or the size
                # cap, whichever first (the lone-request bound IS the
                # window). The deadline re-reads _window each pass so
                # a live retune (apply hook) re-times an OPEN window,
                # not just the next one.
                while (not self._stopping
                       and len(self._pending_row_set)
                       < self._max_rows):
                    remaining = self._open_t + self._window \
                        - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                batch = self._pending
                self._pending = []
                self._pending_row_set = set()
            self._execute(batch)

    def _execute(self, batch: List[_PendingRead]) -> None:
        merged = np.unique(np.concatenate([r.uniq for r in batch])) \
            if len(batch) > 1 else batch[0].uniq
        try:
            values, info = self._table.read_rows_scatter(merged)
        except Exception as exc:  # noqa: BLE001 - a failed merged
            # read must resolve every member (a stranded waiter is
            # the one unacceptable outcome), typed non-retryable.
            log.error("serving: batched read on table %r failed: %s",
                      self._name, exc)
            for req in batch:
                req.error = UpstreamReadError(
                    f"read failed: {exc}", req.uniq.tolist(),
                    retryable=False)
                req.done.set()
            return
        self.batches += 1
        self.requests += len(batch)
        samples(BATCH_SIZE).add(float(len(batch)))
        failed = set(int(r) for r in info["failed"])
        fatal = set(int(r) for r in info.get("failed_fatal", ()))
        bound = self._bound_of()
        uniq = info["rows"]
        for req in batch:
            touched = [int(r) for r in req.uniq if int(r) in failed]
            if touched:
                # Retryability decided per MEMBER: only rows whose own
                # failure was fatal make this response a hard error —
                # an unrelated group's table error in the same merged
                # batch must not demote a transient (503) failure.
                req.error = UpstreamReadError(
                    f"{len(touched)} of {req.uniq.size} requested "
                    f"rows failed upstream", touched,
                    retryable=not any(r in fatal for r in touched))
                req.done.set()
                continue
            pos = np.searchsorted(uniq, req.uniq)
            req.values = values[np.searchsorted(uniq, req.ids)]
            req.meta = request_meta(info, pos, bound)
            req.detail = {
                "rows": req.uniq, "values": values[pos],
                "versions": info["versions"][pos],
                "owners": info["owners"][pos],
                "generation": info["generation"]}
            req.done.set()

    def stop(self) -> None:
        if self._thread is None:
            return
        with self._lock:
            self._stopping = True
            self._cond.notify_all()
        self._thread.join(timeout=5)


class HotRowCache:
    """Rendered per-row response cache (see module docstring). All
    methods thread-safe: lookups on handler threads, stores on
    handler or batcher threads."""

    def __init__(self, table, bound_of: Callable[[], int],
                 capacity: Optional[int] = None):
        self._table = table
        self._bound_of = bound_of
        self._capacity = int(get_flag("serving_hot_rows", 4096)
                             if capacity is None else capacity)
        self._lock = named_lock(f"serving.hot_rows[{next(_serial)}]")
        #: row -> (fetch version, owner sid, data generation,
        #:         rendered value list)
        self._rows: Dict[int, tuple] = {}
        self.hits = 0
        self.misses = 0
        # Live retuning (docs/AUTOTUNE.md): capacity was cached at
        # construction; the hook resizes a running cache.
        register_tunable_hook("serving_hot_rows",
                              self._retune_capacity)

    def _retune_capacity(self, value) -> None:
        with self._lock:
            self._capacity = max(int(value), 0)
            while len(self._rows) > self._capacity:
                self._rows.pop(next(iter(self._rows)))

    def lookup(self, ids: np.ndarray):
        """All-or-nothing: every requested row fresh under the bound
        AND the current generation -> ``(values_lists, meta)`` built
        entirely from cached rendered rows (the worker table is never
        touched); else None."""
        generation = self._table.cache_generation()
        latests = self._table.observed_versions()
        bound = self._bound_of()
        uniq = np.unique(ids)
        found: Dict[int, tuple] = {}
        with self._lock:
            for r in uniq:
                ent = self._rows.get(int(r))
                if ent is None:
                    break
                version, owner, gen, rendered = ent
                latest = latests.get(owner)
                if (gen != generation or latest is None
                        or latest - version > bound):
                    break
                found[int(r)] = ent
            hit = len(found) == uniq.size
            if hit:
                self.hits += 1
                # LRU promote: dict order is eviction order, and a hot
                # row served from the cache never re-stores — without
                # promotion the Zipf head stays oldest and capacity
                # overflows evict exactly the rows the cache exists
                # to hold.
                for r, ent in found.items():
                    self._rows.pop(r, None)
                    self._rows[r] = ent
            else:
                self.misses += 1
        if not hit:
            return None
        versions = [found[int(r)][0] for r in uniq]
        row_latest = [latests[found[int(r)][1]] for r in uniq]
        meta = {"served_version": int(min(versions)),
                "latest_version": int(max(max(row_latest),
                                          max(versions))),
                "max_staleness": int(max(
                    max(lt - v for lt, v in zip(row_latest, versions)),
                    0)),
                "staleness_bound": int(bound),
                "cache_hit": True,
                "rows_requested": int(uniq.size),
                "rows_cached": int(uniq.size)}
        return [found[int(r)][3] for r in ids], meta

    def store(self, detail: dict) -> None:
        """Record one read's per-row results (a ``BatchedTableReader``
        ``detail``). Rows with no version stamp are skipped — an
        unstamped row cannot age against the tracker."""
        if detail is None:
            return
        rows = detail["rows"]
        values = detail["values"]
        versions = detail["versions"]
        owners = detail["owners"]
        gen = detail["generation"]
        with self._lock:
            for i, r in enumerate(rows):
                v = int(versions[i])
                if v < 0:
                    continue
                # pop-then-insert: a refreshed row moves to the END of
                # the eviction order instead of keeping its original
                # (oldest) slot.
                self._rows.pop(int(r), None)
                self._rows[int(r)] = (v, int(owners[i]), gen,
                                      values[i].tolist())
            while len(self._rows) > self._capacity:
                self._rows.pop(next(iter(self._rows)))

    @property
    def stats(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "rows": len(self._rows)}
