"""Vocabulary/word-frequency preprocessor CLI.

The reference ships a standalone word_count generator
(ref: Applications/WordEmbedding/preprocess/word_count.cpp:30-46:
``word_count [-train_file f] [-save_vocab_file v] [-min_count n]``, with
an optional stopword list, preprocess/Readme.txt). Same job here: count
the corpus once, filter by min_count and stopwords, save the vocab for
``main.py -vocab_file=`` so multi-worker runs skip per-rank dictionary
builds.

    python -m multiverso_tpu.models.wordembedding.preprocess \\
        -train_file=corpus.txt -save_vocab_file=vocab.txt \\
        [-min_count=5] [-sw_file=stopwords.txt]
"""

from __future__ import annotations

import sys

from ...util import log
from ...util.configure import (define_int, define_string, get_flag,
                               parse_cmd_flags)
from .dictionary import Dictionary

# Shared with main.py (the registry keeps the first definition).
define_string("train_file", "", "training corpus (';'-separated)")
define_int("min_count", 5, "discard words rarer than this")
define_string("save_vocab_file", "", "vocab output path")
define_string("sw_file", "", "optional stopword list (one word per line)")


def run(argv=None) -> Dictionary:
    parse_cmd_flags(list(argv) if argv is not None else sys.argv[1:])
    train_file = get_flag("train_file")
    out = get_flag("save_vocab_file")
    if not train_file or not out:
        raise SystemExit("usage: preprocess -train_file=<corpus> "
                         "-save_vocab_file=<path> [-min_count=5] "
                         "[-sw_file=<stopwords>]")
    stopwords = None
    if get_flag("sw_file"):
        with open(get_flag("sw_file")) as f:
            stopwords = {line.strip() for line in f if line.strip()}
    dictionary = Dictionary.build(train_file,
                                  min_count=get_flag("min_count"),
                                  stopwords=stopwords)
    dictionary.store(out)
    log.info("vocab: %d words (min_count=%d) -> %s", dictionary.size,
             get_flag("min_count"), out)
    return dictionary


if __name__ == "__main__":
    run()
