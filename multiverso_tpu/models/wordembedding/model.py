"""Word2vec models: SGNS + hierarchical softmax, skip-gram + CBOW.

TPU-native re-design of the reference's WordEmbedding compute core
(ref: Applications/WordEmbedding/src/wordembedding.cpp — per-window scalar
FeedForward/BPOutputLayer loops): here one jitted step trains a whole
batch of (center, context) pairs on the MXU —

- negative sampling (SGNS): negatives are drawn inside the jit by
  inverse-CDF over the unigram^0.75 distribution; logits are a gathered
  batched dot product ``einsum('bd,bkd->bk')`` over [positive, K
  negatives]; gradients scatter-add into both embedding matrices;
- hierarchical softmax: each pair trains the Huffman path of the context
  word — codes/points are gathered from device-resident [V, L] tables
  (built by huffman.py) and padded path slots are masked;
- CBOW averages the (padded, masked) context window into the input vector
  and scatters the input gradient back to every window word.

Embeddings are plain device arrays locally; the PS variant keeps them in
row-sharded matrix tables and trains blocks on pulled rows, pushing
``(new - old) / num_workers`` exactly like the reference's
AddDeltaParameter (ref: communicator.cpp:157-249).

The learning rate decays linearly in processed words:
``lr = initial * max(1 - done/total, 1e-4)`` (ref:
distributed_wordembedding.cpp:92-134 recomputes it from the global word
count; in distributed mode that count lives in a KV table).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ... import create_kv_table, create_matrix_table
from ...updater.engine import pad_ids
from .data import CbowBatch, PairBatch
from .dictionary import Dictionary
from .huffman import build_huffman


_MAX_EXP = 6.0  # word2vec.c's sigmoid-table range


class Word2VecConfig:
    """Mirror of the reference's CLI options (ref: WordEmbedding
    src/util.cpp ParseArgs: -size -window -negative -epoch -min_count
    -sample -init_learning_rate -cbow -hs ...)."""

    def __init__(self, embedding_size: int = 100, window: int = 5,
                 negative: int = 5, epochs: int = 1, min_count: int = 5,
                 sample: float = 1e-3, init_learning_rate: float = 0.025,
                 cbow: bool = False, hs: bool = False,
                 batch_size: int = 4096, seed: int = 1,
                 use_ps: bool = False):
        self.embedding_size = embedding_size
        self.window = window
        self.negative = negative
        self.epochs = epochs
        self.min_count = min_count
        self.sample = sample
        self.init_learning_rate = init_learning_rate
        self.cbow = cbow
        self.hs = hs
        self.batch_size = batch_size
        self.seed = seed
        self.use_ps = use_ps


class Word2Vec:
    """Local (single-process) trainer; device-resident embeddings."""

    _DONATE = True  # PS subclass keeps old params to form wire deltas

    def __init__(self, config: Word2VecConfig, dictionary: Dictionary):
        self.config = config
        self.dictionary = dictionary
        vocab, dim = dictionary.size, config.embedding_size
        rng = np.random.default_rng(config.seed)
        # ref init: uniform (-0.5/dim, 0.5/dim) input, zeros output.
        self._emb_in = jnp.asarray(
            (rng.random((vocab, dim)) - 0.5) / dim, jnp.float32)
        if config.hs:
            tree = build_huffman(dictionary.counts)
            self._codes = jnp.asarray(tree.codes)
            self._points = jnp.asarray(tree.points)
            out_rows = max(tree.num_inner_nodes, 1)
        else:
            neg = dictionary.negative_table()
            self._neg_cdf = jnp.asarray(np.cumsum(neg))
            out_rows = vocab
        self._emb_out = jnp.zeros((out_rows, dim), jnp.float32)
        self._key = jax.random.PRNGKey(config.seed)
        self._step = self._build_step()
        self.trained_words = 0
        self.total_words = dictionary.total_count * config.epochs

    # -- learning rate schedule --
    def learning_rate(self) -> float:
        remain = max(1.0 - self.trained_words / max(self.total_words, 1),
                     1e-4)
        return self.config.init_learning_rate * remain

    # -- the fused train step --
    def _build_step(self):
        config = self.config
        if config.hs:
            pair_loss = self._hs_pair_loss
        else:
            pair_loss = self._neg_pair_loss

        # ``pair_mask`` zeroes the tail-batch padding rows — without it the
        # padded (0, 0) pairs would train the most frequent word against
        # itself as a positive example.
        if config.cbow:
            def loss_fn(params, window, centers, pair_mask, key):
                emb_in, emb_out = params
                mask = (window >= 0).astype(jnp.float32)
                safe = jnp.maximum(window, 0)
                vecs = emb_in[safe] * mask[..., None]
                denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1.0)
                v = vecs.sum(axis=1) / denom  # [B, D] averaged window
                return pair_loss(v, centers, emb_out, pair_mask, key)
        else:
            def loss_fn(params, centers, contexts, pair_mask, key):
                emb_in, emb_out = params
                v = emb_in[centers]
                return pair_loss(v, contexts, emb_out, pair_mask, key)

        def step(params, lr, key, pair_mask, *batch):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(p, *batch, pair_mask, key))(params)
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads)
            return new_params, loss

        return jax.jit(step,
                       donate_argnums=(0,) if self._DONATE else ())

    def _neg_pair_loss(self, v, targets, emb_out, pair_mask, key,
                       negatives=None):
        """SGNS: positive target + K negatives — sampled in-jit locally,
        or host-provided in PS mode (the PS pull needs to know the rows
        before the step runs, like the reference's block preparation,
        ref: communicator.cpp:117-155)."""
        k = self.config.negative
        batch = v.shape[0]
        if negatives is None:
            uniform = jax.random.uniform(key, (batch, k))
            negatives = jnp.searchsorted(self._neg_cdf, uniform)
        cols = jnp.concatenate([targets[:, None], negatives], axis=1)
        u = emb_out[cols]  # [B, 1+K, D]
        # MAX_EXP clamp, exactly word2vec's sigmoid table: saturated pairs
        # get ZERO gradient (clip has zero derivative outside the range),
        # which is what keeps hot rows from diverging under batched sums.
        logits = jnp.clip(jnp.einsum("bd,bkd->bk", v, u),
                          -_MAX_EXP, _MAX_EXP)
        labels = jnp.concatenate(
            [jnp.ones((batch, 1)), jnp.zeros((batch, k))], axis=1)
        losses = _sigmoid_xent(logits, labels) * pair_mask[:, None]
        # SUM over the batch: word2vec applies the learning rate per pair
        # (ref trains pair-by-pair); a mean would shrink the per-pair step
        # by the batch size.
        return jnp.sum(losses)

    def _hs_pair_loss(self, v, targets, emb_out, pair_mask, key):
        """Hierarchical softmax over the target's Huffman path."""
        points = self._points[targets]  # [B, L]
        codes = self._codes[targets]
        mask = (codes >= 0).astype(jnp.float32) * pair_mask[:, None]
        u = emb_out[jnp.maximum(points, 0)]  # [B, L, D]
        logits = jnp.clip(jnp.einsum("bd,bld->bl", v, u),
                          -_MAX_EXP, _MAX_EXP)  # word2vec MAX_EXP clamp
        # code 0 = positive class (sigmoid(logit)), 1 = negative — the
        # word2vec convention (ref: wordembedding.cpp HS branch).
        labels = 1.0 - codes.astype(jnp.float32)
        losses = _sigmoid_xent(logits, labels * mask) * mask
        return jnp.sum(losses)  # per-pair lr semantics, as in SGNS

    # -- public API --
    def train_batch_async(self, batch):
        """Dispatch one training step WITHOUT synchronizing; returns the
        device scalar loss. The hot loop must not materialize per-batch
        scalars — a host fetch per step serializes on device/tunnel
        latency and caps words/sec."""
        lr = jnp.float32(self.learning_rate())
        self._key, subkey = jax.random.split(self._key)
        params = (self._emb_in, self._emb_out)
        if isinstance(batch, CbowBatch):
            args = (jnp.asarray(batch.window), jnp.asarray(batch.centers))
            size = batch.centers.shape[0]
        else:
            args = (jnp.asarray(batch.centers), jnp.asarray(batch.contexts))
            size = batch.centers.shape[0]
        pair_mask = _full_mask(size) if batch.count == size \
            else jnp.asarray((np.arange(size) < batch.count)
                             .astype(np.float32))
        (self._emb_in, self._emb_out), loss = self._step(
            params, lr, subkey, pair_mask, *args)
        self.trained_words += batch.words
        return loss

    def train_batch(self, batch) -> float:
        loss = self.train_batch_async(batch)
        return float(loss) / max(batch.count, 1)  # display per-pair loss

    @property
    def embeddings(self) -> np.ndarray:
        return np.asarray(self._emb_in)

    def save_embeddings(self, path: str) -> None:
        """word2vec text format (ref rank-0 save,
        distributed_wordembedding.cpp:231-236)."""
        from ...io import StreamFactory
        emb = self.embeddings
        with StreamFactory.get_stream(path, "w") as stream:
            stream.write(f"{emb.shape[0]} {emb.shape[1]}\n".encode())
            for word, row in zip(self.dictionary.words, emb):
                vec = " ".join(f"{x:.6f}" for x in row)
                stream.write(f"{word} {vec}\n".encode())


@functools.lru_cache(maxsize=None)
def _full_mask(size: int):
    return jnp.ones((size,), jnp.float32)


def _sigmoid_xent(logits, labels):
    """Numerically stable sigmoid cross-entropy."""
    return jnp.maximum(logits, 0) - logits * labels \
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))


class PSWord2Vec(Word2Vec):
    """Distributed trainer: embeddings live in row-sharded matrix tables;
    each batch pulls the rows it touches, trains on device, and pushes
    ``(new - old) / num_workers`` (ref: communicator.cpp:117-249). The
    global word count rides a KV table for the lr schedule
    (ref: communicator.cpp:251-259)."""

    _DONATE = False

    def __init__(self, config: Word2VecConfig, dictionary: Dictionary,
                 num_workers: int = 1):
        super().__init__(config, dictionary)
        vocab, dim = dictionary.size, config.embedding_size
        out_rows = int(self._emb_out.shape[0])
        self._in_table = create_matrix_table(vocab, dim,
                                             updater_type="default")
        self._out_table = create_matrix_table(out_rows, dim,
                                              updater_type="default")
        self._wc_table = create_kv_table()
        self._num_workers = max(num_workers, 1)
        # Seed the server with this worker's init (workers after the first
        # add zeros-delta equivalents; with random per-rank init the model
        # averages, mirroring the reference's master-init convention).
        if self._in_table.zoo.worker_id == 0:
            self._in_table.add(np.asarray(self._emb_in))
        self._in_table.zoo.barrier()
        self._pull_full()

    def _pull_full(self) -> None:
        self._emb_in = self._in_table.get_device().reshape(
            self._emb_in.shape)
        self._emb_out = self._out_table.get_device().reshape(
            self._emb_out.shape)

    def train_batch_async(self, batch):
        # The PS path must push/pull around every step; there is no
        # fire-and-forget variant (the pull is the synchronization point).
        return jnp.float32(self.train_batch(batch))

    def train_batch(self, batch) -> float:
        old_in, old_out = self._emb_in, self._emb_out
        # Base-class async step explicitly: self.train_batch_async is the
        # PS wrapper above and would recurse.
        loss = float(Word2Vec.train_batch_async(self, batch)) \
            / max(batch.count, 1)
        scale = 1.0 / self._num_workers
        delta_in = np.asarray((self._emb_in - old_in) * scale)
        delta_out = np.asarray((self._emb_out - old_out) * scale)
        rows_in = np.unique(np.asarray(
            batch.centers if not isinstance(batch, CbowBatch)
            else batch.window)).astype(np.int32)
        rows_in = rows_in[rows_in >= 0]
        self._in_table.add_rows_async(rows_in, delta_in[rows_in])
        rows_out = np.nonzero(np.abs(delta_out).sum(axis=1))[0] \
            .astype(np.int32)
        if rows_out.size:
            self._out_table.add_rows_async(rows_out, delta_out[rows_out])
        self._wc_table.add([0], [float(batch.words)])
        # Refresh from the server so other workers' updates land.
        self._pull_full()
        global_words = self._wc_table.get([0])[0]
        self.trained_words = int(global_words)
        return loss
