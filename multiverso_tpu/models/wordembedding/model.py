"""Word2vec models: SGNS + hierarchical softmax, skip-gram + CBOW.

TPU-native re-design of the reference's WordEmbedding compute core
(ref: Applications/WordEmbedding/src/wordembedding.cpp — per-window scalar
FeedForward/BPOutputLayer loops): one jitted step trains a whole batch of
(center, context) pairs on the MXU.

The central design decision (round 2): **both** the local and the
parameter-server trainer work on COMPACT row sets. A host-side
preparation pass computes the unique embedding rows a batch touches
(input rows from centers/window words; output rows from targets plus
host-sampled negatives or Huffman path nodes) and remaps batch indices
to compact slots. Then:

- **local mode**: one jitted step gathers those rows from the full
  device tables, trains the compact [R, D] matrices, and scatter-adds
  the updates back — donated buffers, HBM traffic O(batch). (The naive
  formulation differentiates through the full V x D tables and makes
  every step O(vocab) in memory traffic: at 1M+ vocab that is ~GBs per
  batch and dominates wall clock.)
- **PS mode**: the same prepared row sets drive row-sparse table pulls,
  the same compact loss trains the pulled rows, and row deltas
  ``(new - old) / num_workers`` push back (ref: communicator.cpp:
  117-249), pipelined across batches (ref: distributed_wordembedding.
  cpp:203-224).

Negatives sample from the unigram^0.75 distribution via Vose alias
tables — in-jit on the local path, host-side (numpy) on the PS path,
where the row set must be known before the pull. The learning rate
decays linearly in processed words (ref:
distributed_wordembedding.cpp:92-134; in PS mode the global count rides
a KV table)."""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ... import create_kv_table, create_matrix_table
from ...tables import client_cache
from ...util.dashboard import monitor
from .data import CbowBatch, PairBatch
from .dictionary import Dictionary
from .huffman import build_huffman


_MAX_EXP = 6.0  # word2vec.c's sigmoid-table range


class Word2VecConfig:
    """Mirror of the reference's CLI options (ref: WordEmbedding
    src/util.cpp ParseArgs: -size -window -negative -epoch -min_count
    -sample -init_learning_rate -cbow -hs ...)."""

    def __init__(self, embedding_size: int = 100, window: int = 5,
                 negative: int = 5, epochs: int = 1, min_count: int = 5,
                 sample: float = 1e-3, init_learning_rate: float = 0.025,
                 cbow: bool = False, hs: bool = False,
                 batch_size: int = 4096, seed: int = 1,
                 use_ps: bool = False, batch_group: int = 16,
                 neg_block: int = 1, per_pair: bool = False):
        self.embedding_size = embedding_size
        self.window = window
        self.negative = negative
        self.epochs = epochs
        self.min_count = min_count
        self.sample = sample
        self.init_learning_rate = init_learning_rate
        self.cbow = cbow
        self.hs = hs
        self.batch_size = batch_size
        self.seed = seed
        self.use_ps = use_ps
        # Batches per device dispatch in train_batches (local mode): the
        # K-step on-device loop that amortizes per-call dispatch latency.
        # 1 disables grouping.
        self.batch_group = batch_group
        # Device-pipeline negative sharing: one draw of K negatives per
        # block of this many consecutive centers (1 = per-center, the
        # round-3 behavior; expected gradient unchanged, negative row
        # traffic divided by the block factor).
        self.neg_block = neg_block
        # QUALITY mode (skip-gram device pipelines): negatives drawn per
        # (center, offset) PAIR and the 2W window offsets applied as
        # sequential sub-steps — the reference's pair-by-pair update
        # structure. ~8x slower than the banded fast path; reaches the
        # sequential C++ baseline's topic separation at equal epochs.
        self.per_pair = per_pair


def build_alias(probs: np.ndarray):
    """Vose's alias method: O(V) build, O(1) vectorized sampling.
    Returns (prob[V] float32, alias[V] int32): draw ``i`` uniformly, then
    take ``i`` with probability ``prob[i]`` else ``alias[i]``."""
    probs = np.asarray(probs, np.float64)
    n = probs.size
    scaled = probs * (n / probs.sum())
    prob = np.ones(n, np.float32)
    alias = np.arange(n, dtype=np.int32)
    # The pairing sweep is a Python O(n) loop: ~1 s per 1M entries, so
    # ~20 s one-time at the reference's 21M-word vocab — accepted, since
    # it buys O(1) in-jit sampling every batch (the device searchsorted
    # it replaces cost ~26 ms per 160K draws, i.e. seconds per epoch).
    small = list(np.flatnonzero(scaled < 1.0)[::-1])
    large = list(np.flatnonzero(scaled >= 1.0)[::-1])
    while small and large:
        s, g = int(small.pop()), int(large.pop())
        prob[s] = scaled[s]
        alias[s] = g
        scaled[g] = scaled[g] + scaled[s] - 1.0
        (small if scaled[g] < 1.0 else large).append(g)
    return prob, alias


def _alias_draw_np(prob: np.ndarray, alias: np.ndarray,
                   rng: np.random.Generator, shape) -> np.ndarray:
    idx = rng.integers(0, prob.size, size=shape).astype(np.int32)
    keep = rng.random(size=shape) < prob[idx]
    return np.where(keep, idx, alias[idx])


def _unique_rows_and_remap(ids_list, num_rows: int):
    """Sorted unique ids over ``ids_list`` plus a remap array such that
    ``remap[id] = compact slot``. Bitmap-based — O(num_rows + K), ~4x
    faster than sort-based ``np.unique`` + ``searchsorted`` at word2vec
    batch shapes — falling back to the sort path when the table is huge
    relative to the batch (the O(num_rows) sweep would dominate)."""
    total = sum(a.size for a in ids_list)
    if num_rows > max(1 << 22, 32 * total):
        rows = np.unique(np.concatenate(
            [a.reshape(-1) for a in ids_list])).astype(np.int32)
        return rows, None
    mark = np.zeros(num_rows, bool)
    for a in ids_list:
        mark[a.reshape(-1)] = True
    rows = np.flatnonzero(mark).astype(np.int32)
    # Absent ids map to slot 0 (zeros, not empty): CBOW/HS paths look up
    # pad id 0 even when word 0 is not in the batch — the result is
    # masked downstream, but it must still be deterministic memory.
    remap = np.zeros(num_rows, np.int32)
    remap[rows] = np.arange(rows.size, dtype=np.int32)
    return rows, remap


def _slot_map(rows: np.ndarray, remap, ids: np.ndarray) -> np.ndarray:
    """Compact slot of every id: remap gather when available, else
    binary search over the sorted unique rows."""
    if remap is not None:
        return remap[ids]
    return np.searchsorted(rows, ids).astype(np.int32)


def _pad_rows(rows: np.ndarray, minimum: int = 8) -> np.ndarray:
    """Pad a sorted unique row-id set to the next power of two (bounded
    set of jit trace shapes) by repeating the last id. Padded slots are
    never referenced by the compact index maps, so they receive zero
    gradient; local scatter-adds of zero are no-ops and PS delta pushes
    slice them off."""
    n = max(int(rows.size), 1)
    target = max(minimum, 1 << (n - 1).bit_length())
    if rows.size == 0:
        return np.zeros(target, np.int32)
    if rows.size == target:
        return rows
    return np.concatenate(
        [rows, np.full(target - rows.size, rows[-1], np.int32)])


class CompactBatch:
    """Host-prepared batch: unique touched rows + compact index maps.

    ``rows_in``/``rows_out`` are the real (unpadded) sorted unique row
    sets; ``rows_in_p``/``rows_out_p`` the power-of-two padded versions
    the device step uses; ``in_args``/``out_args`` index into the padded
    compact arrays."""

    __slots__ = ("rows_in", "rows_out", "rows_in_p", "rows_out_p",
                 "in_args", "out_args", "count", "words", "size")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class Word2Vec:
    """Local (single-process) trainer; device-resident embeddings,
    compact-row update steps."""

    def __init__(self, config: Word2VecConfig, dictionary: Dictionary):
        self.config = config
        self.dictionary = dictionary
        self._dim = config.embedding_size
        self._out_rows = self._init_output_structures()
        self._rng = np.random.default_rng(config.seed + 13)
        self.trained_words = 0
        self.total_words = dictionary.total_count * config.epochs
        self._multi_step = None  # built on first grouped dispatch
        # Row-set pad minimums (see _pad_rows): the local path lets them
        # float per batch; the PS path freezes them to one bucket per
        # table so exactly ONE jit trace per gather/step/scatter exists.
        self._pad_in_min = 8
        self._pad_out_min = 8
        self._init_embeddings()

    def _init_output_structures(self) -> int:
        """Huffman tables (hs) or the unigram^0.75 CDF (sgns); returns
        the output-embedding row count. All host-side: row-set
        preparation must know the touched output rows before the device
        step runs."""
        config, dictionary = self.config, self.dictionary
        if config.hs:
            tree = build_huffman(dictionary.counts)
            self._codes_host = np.asarray(tree.codes)
            self._points_host = np.asarray(tree.points)
            return max(tree.num_inner_nodes, 1)
        # Alias-method tables (Vose) over the unigram^0.75 distribution:
        # a draw is (randint, uniform, two table lookups) — O(1) and fully
        # vectorized. The inverse-CDF searchsorted it replaces costs
        # ~26 ms per 160K draws inside the jitted step on TPU (binary
        # search lowers badly); alias sampling is ~0.1 ms.
        self._neg_prob_host, self._neg_alias_host = build_alias(
            dictionary.negative_table())
        return dictionary.size

    def _init_embeddings(self) -> None:
        """Local mode: full device-resident matrices. ref init: uniform
        (-0.5/dim, 0.5/dim) input, zeros output. Initialized ON device
        (jax.random) — a host-side init means uploading the whole V x D
        table, ~0.5 GB at reference scale, over a possibly-slow
        host->device link. The PS subclass overrides this with table
        creation (no full local copies)."""
        vocab, dim = self.dictionary.size, self.config.embedding_size
        init_key = jax.random.PRNGKey(self.config.seed ^ 0x5EED)
        self._emb_in = (jax.random.uniform(init_key, (vocab, dim),
                                           jnp.float32) - 0.5) / dim
        self._emb_out = jnp.zeros((self._out_rows, dim), jnp.float32)
        if self.config.hs:
            self._codes_dev = jnp.asarray(self._codes_host)
            self._points_dev = jnp.asarray(self._points_host)
        else:
            self._neg_prob_dev = jnp.asarray(self._neg_prob_host)
            self._neg_alias_dev = jnp.asarray(self._neg_alias_host)
        # Per-batch PRNG keys derive as fold_in(base, batch_counter):
        # the counter advances once per REAL batch, so the grouped scan
        # (whose padded tail slots are masked no-ops) and the sequential
        # path consume identical key streams — bit-identical training.
        self._key = jax.random.PRNGKey(self.config.seed)
        self._batch_counter = 0
        self._step = self._build_step()

    # -- learning rate schedule --
    def learning_rate(self) -> float:
        remain = max(1.0 - self.trained_words / max(self.total_words, 1),
                     1e-4)
        return self.config.init_learning_rate * remain

    # -- host preparation: batch -> compact row sets + index maps --
    def prepare(self, batch) -> CompactBatch:
        """Compute the rows this batch touches and remap its indices to
        compact slots (the reference's per-block row collection,
        ref: communicator.cpp:117-155). Pure numpy — run it in the
        loader thread to overlap with device steps."""
        config = self.config
        vocab = self.dictionary.size
        if isinstance(batch, CbowBatch):
            win, targets = batch.window, batch.centers
            real = win[win >= 0]
            if real.size:
                rows_in, remap = _unique_rows_and_remap([real], vocab)
            else:
                rows_in, remap = np.zeros(1, np.int32), None
            win_l = np.clip(_slot_map(rows_in, remap, np.maximum(win, 0)),
                            0, rows_in.size - 1).astype(np.int32)
            in_args = (win_l, (win >= 0).astype(np.float32))
            size = batch.centers.shape[0]
        else:
            centers, targets = batch.centers, batch.contexts
            rows_in, remap = _unique_rows_and_remap([centers], vocab)
            in_args = (_slot_map(rows_in, remap, centers),)
            size = centers.shape[0]

        if config.hs:
            points = self._points_host[targets]  # [B, L], -1 padded
            real = points[points >= 0]
            if real.size:
                rows_out, remap = _unique_rows_and_remap(
                    [real], self._out_rows)
            else:
                rows_out, remap = np.zeros(1, np.int32), None
            points_l = np.clip(
                _slot_map(rows_out, remap, np.maximum(points, 0)),
                0, rows_out.size - 1).astype(np.int32)
            out_args = (points_l, self._codes_host[targets])
        else:
            k = config.negative
            # neg_block pairs share one K-draw (expected gradient
            # unchanged): divides the negative row volume — which
            # dominates the block's row set and therefore the id/delta
            # bytes every pull/push ships — by the block factor. The
            # wire (or tunnel) bytes are what bind the host-batch path.
            nb = max(int(getattr(config, "neg_block", 1)), 1)
            # The shipped batch iterators emit FIXED-size batches (tail
            # padded, count < size), so nb divides in practice; an odd
            # caller-supplied size falls back to the nearest divisor so
            # the unique-row count stays within the frozen _pad_out_min
            # bucket (nb=1 could overflow it and compile a new shape).
            while targets.size % nb:
                nb //= 2
            neg = _alias_draw_np(self._neg_prob_host,
                                 self._neg_alias_host, self._rng,
                                 (targets.size // nb, k)).astype(np.int32)
            rows_out, remap = _unique_rows_and_remap([targets, neg], vocab)
            out_args = (_slot_map(rows_out, remap, targets),
                        _slot_map(rows_out, remap, neg))

        rows_in_p = _pad_rows(rows_in, self._pad_in_min)
        rows_out_p = _pad_rows(rows_out, self._pad_out_min)
        # Slot maps index the padded pulled buffers; when a buffer has
        # <= 65536 slots they fit uint16 — halves the per-batch id
        # upload (the frozen buckets keep the dtype stable per config,
        # so the jit signature does not churn).
        if rows_in_p.size <= 65536 and not config.cbow:
            in_args = tuple(a.astype(np.uint16) for a in in_args)
        if rows_out_p.size <= 65536 and not config.hs:
            out_args = tuple(a.astype(np.uint16) for a in out_args)
        return CompactBatch(
            rows_in=rows_in, rows_out=rows_out,
            rows_in_p=rows_in_p, rows_out_p=rows_out_p,
            in_args=in_args, out_args=out_args,
            count=batch.count, words=batch.words, size=size)

    # -- the shared compact loss --
    def _compact_loss(self):
        config = self.config

        def input_vec(ein, in_args):
            if config.cbow:
                win_l, win_mask = in_args
                vecs = ein[win_l] * win_mask[..., None]
                denom = jnp.maximum(win_mask.sum(axis=1, keepdims=True),
                                    1.0)
                return vecs.sum(axis=1) / denom
            (centers_l,) = in_args
            return ein[centers_l]

        if config.hs:
            def loss_fn(ein, eout, in_args, out_args, pair_mask):
                """Hierarchical softmax over the target's Huffman path;
                code 0 = positive class — the word2vec convention
                (ref: wordembedding.cpp HS branch)."""
                v = input_vec(ein, in_args)
                points_l, codes = out_args
                mask = (codes >= 0).astype(jnp.float32) * pair_mask[:, None]
                u = eout[points_l]  # [B, L, D]
                logits = jnp.clip(jnp.einsum("bd,bld->bl", v, u),
                                  -_MAX_EXP, _MAX_EXP)
                labels = 1.0 - codes.astype(jnp.float32)
                return jnp.sum(_sigmoid_xent(logits, labels * mask) * mask)
        else:
            def loss_fn(ein, eout, in_args, out_args, pair_mask):
                """SGNS. The MAX_EXP clamp is word2vec's sigmoid table:
                saturated pairs get ZERO gradient. SUM over the batch:
                word2vec applies the learning rate per pair; a mean
                would shrink the per-pair step by the batch size.
                ``negs_l`` is [B // neg_block, K]: each block of
                consecutive pairs shares one K-draw."""
                v = input_vec(ein, in_args)
                targets_l, negs_l = out_args
                pos = jnp.clip(jnp.sum(v * eout[targets_l], axis=-1),
                               -_MAX_EXP, _MAX_EXP)
                u_neg = eout[negs_l]                   # [B//nb, K, D]
                vb = v.reshape(u_neg.shape[0], -1, v.shape[-1])
                neg = jnp.clip(jnp.einsum("nbd,nkd->nbk", vb, u_neg),
                               -_MAX_EXP, _MAX_EXP)
                mb = pair_mask.reshape(u_neg.shape[0], -1)
                return (jnp.sum(_sigmoid_xent(pos, 1.0) * pair_mask)
                        + jnp.sum(_sigmoid_xent(neg, 0.0)
                                  * mb[:, :, None]))

        return loss_fn

    # -- the fused local train step: gather -> train -> scatter-add.
    #
    # The batch only ships its (center, context) ids (negatives sample
    # in-jit); gradients are taken w.r.t. the GATHERED rows and
    # scatter-added back at the global ids — duplicate ids sum, which is
    # exactly the dense-gradient semantics — so HBM traffic per step is
    # O(batch), not O(vocab). (Differentiating through the full V x D
    # tables rewrites both tables every step: ~GBs of traffic per batch
    # at 1M+ vocab, which is what capped round-1 scaling.)
    def _make_step_core(self):
        """The per-batch update: gather -> grad -> scatter-add, taking an
        already-split PRNG key. Shared by the single-step jit and the
        grouped ``lax.scan`` multi-step."""
        config = self.config
        k = config.negative

        def core(emb_in, emb_out, lr, key, pair_mask, in_ids, targets):
            if config.hs:
                points = self._points_dev[targets]  # [B, L]
                codes = self._codes_dev[targets]
                out_ids = jnp.maximum(points, 0)
                out_mask = (codes >= 0).astype(jnp.float32) \
                    * pair_mask[:, None]
                labels = (1.0 - codes.astype(jnp.float32)) * out_mask
            else:
                batch = targets.shape[0]
                k_idx, k_keep = jax.random.split(key)
                idx = jax.random.randint(
                    k_idx, (batch, k), 0, self._neg_prob_dev.shape[0])
                keep = jax.random.uniform(k_keep, (batch, k)) \
                    < self._neg_prob_dev[idx]
                negs = jnp.where(keep, idx, self._neg_alias_dev[idx])
                out_ids = jnp.concatenate([targets[:, None], negs], axis=1)
                out_mask = pair_mask[:, None] * jnp.ones((1, 1 + k))
                labels = jnp.concatenate(
                    [jnp.ones((batch, 1)), jnp.zeros((batch, k))], axis=1)

            if config.cbow:
                window = in_ids
                in_mask = (window >= 0).astype(jnp.float32)
                in_gather = jnp.maximum(window, 0)
                vecs = emb_in[in_gather]  # [B, 2W, D]
            else:
                in_gather = in_ids
                vecs = emb_in[in_ids]  # [B, D]
            u = emb_out[out_ids]  # [B, S, D]

            def loss_fn(vecs, u):
                if config.cbow:
                    masked = vecs * in_mask[..., None]
                    denom = jnp.maximum(
                        in_mask.sum(axis=1, keepdims=True), 1.0)
                    v = masked.sum(axis=1) / denom
                else:
                    v = vecs
                logits = jnp.clip(jnp.einsum("bd,bsd->bs", v, u),
                                  -_MAX_EXP, _MAX_EXP)
                if config.hs:
                    losses = _sigmoid_xent(logits, labels) * out_mask
                else:
                    losses = _sigmoid_xent(logits, labels) \
                        * pair_mask[:, None]
                return jnp.sum(losses)

            loss, (g_vecs, g_u) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(vecs, u)
            new_in = emb_in.at[in_gather].add(-lr * g_vecs)
            new_out = emb_out.at[out_ids].add(-lr * g_u)
            return new_in, new_out, loss

        return core

    def _build_step(self):
        core = self._make_step_core()

        def step(emb_in, emb_out, lr, base_key, counter, pair_mask,
                 in_ids, targets):
            # The per-batch key folds in-jit (a host-side fold would be
            # one more device call per batch, and each call pays the
            # transport's dispatch latency).
            key = jax.random.fold_in(base_key, counter)
            return core(emb_in, emb_out, lr, key, pair_mask, in_ids,
                        targets)

        return jax.jit(step, donate_argnums=(0, 1))

    def _build_multi_step(self):
        """K batches per dispatch: ``lax.scan`` of the step core over
        stacked batch tensors. One host->device dispatch then drives K
        sequential SGD steps entirely in HBM — each slot's key folds
        from the SAME per-batch counter the sequential path uses (and
        masked padding slots carry counter -1, consuming nothing), so
        grouped and ungrouped training are bit-identical; only the
        dispatch count changes. This is what amortizes the per-call
        dispatch latency (~100ms on a tunneled device) that otherwise
        bounds words/sec."""
        core = self._make_step_core()

        def multi(emb_in, emb_out, base_key, lrs, counts, counters,
                  in_ids, targets):
            def body(carry, xs):
                emb_in, emb_out = carry
                lr, count, counter, ii, tt = xs
                key = jax.random.fold_in(base_key, counter)
                # Mask from the scalar count (shipping [K, B] float masks
                # would triple the per-group host->device transfer).
                pm = (jnp.arange(tt.shape[0]) < count).astype(jnp.float32)
                emb_in, emb_out, loss = core(emb_in, emb_out, lr, key,
                                             pm, ii, tt)
                return (emb_in, emb_out), loss

            (emb_in, emb_out), losses = jax.lax.scan(
                body, (emb_in, emb_out),
                (lrs, counts, counters, in_ids, targets))
            return emb_in, emb_out, losses.sum()

        return jax.jit(multi, donate_argnums=(0, 1))

    def _pair_mask_for(self, count: int, size: int):
        if count == size:
            return _full_mask(size)
        return jnp.asarray((np.arange(size) < count).astype(np.float32))

    # -- public API --
    def train_batch_async(self, batch):
        """Dispatch one training step WITHOUT synchronizing; returns the
        device scalar loss. The hot loop must not materialize per-batch
        scalars — a host fetch per step serializes on device/tunnel
        latency and caps words/sec."""
        if isinstance(batch, CbowBatch):
            in_ids, targets = batch.window, batch.centers
        else:
            in_ids, targets = batch.centers, batch.contexts
        size = batch.centers.shape[0]
        counter = self._batch_counter
        self._batch_counter += 1
        self._emb_in, self._emb_out, loss = self._step(
            self._emb_in, self._emb_out,
            jnp.float32(self.learning_rate()), self._key,
            np.int32(counter),
            self._pair_mask_for(batch.count, size),
            jnp.asarray(in_ids), jnp.asarray(targets))
        self.trained_words += batch.words
        return loss

    def train_batch(self, batch) -> float:
        loss = self.train_batch_async(batch)
        return float(loss) / max(batch.count, 1)  # display per-pair loss

    def _train_group(self, batches) -> object:
        """Stack up to ``batch_group`` prepared batches and dispatch ONE
        scanned device step over them. Short groups (the stream tail) pad
        with count=0 slots — masked to zero loss and zero gradient — so
        exactly one trace shape exists. Returns the group's device-scalar
        loss sum."""
        group = max(int(self.config.batch_group), 1)
        first = batches[0]
        cbow = isinstance(first, CbowBatch)
        in_shape = first.window.shape if cbow else first.centers.shape
        bsz = first.centers.shape[0]
        in_ids = np.zeros((group,) + in_shape, np.int32)
        targets = np.zeros((group, bsz), np.int32)
        counts = np.zeros(group, np.int32)
        counters = np.full(group, -1, np.int32)  # -1 = padded no-op slot
        lrs = np.zeros(group, np.float32)
        for i, b in enumerate(batches):
            if cbow:
                in_ids[i], targets[i] = b.window, b.centers
            else:
                in_ids[i], targets[i] = b.centers, b.contexts
            counts[i] = b.count
            counters[i] = self._batch_counter
            self._batch_counter += 1
            # Per-batch lr follows the word schedule exactly as the
            # sequential path would have computed it.
            lrs[i] = self.learning_rate()
            self.trained_words += b.words
        if self._multi_step is None:
            self._multi_step = self._build_multi_step()
        self._emb_in, self._emb_out, loss = self._multi_step(
            self._emb_in, self._emb_out, self._key,
            jnp.asarray(lrs), jnp.asarray(counts), jnp.asarray(counters),
            jnp.asarray(in_ids), jnp.asarray(targets))
        return loss

    def train_batches(self, iterator) -> Tuple[float, int]:
        """Drive a whole batch stream; returns (loss_sum, pair_count).

        Batches dispatch in groups of ``batch_group`` through the scanned
        multi-step — one host->device call per group (the reference's
        block granularity, ref: distributed_wordembedding.cpp:147-236,
        where a data block also carries many sentences per
        request/train/push cycle). Device losses accumulate into ONE
        device scalar (a lazy ``+`` per group) and materialize once at
        the end. Any per-batch host read of a device scalar is a full
        round-trip — tens of ms over a tunneled device — and so is each
        element of a deferred ``jnp.stack``; the running add keeps
        exactly one buffer and one final transfer."""
        group = max(int(self.config.batch_group), 1)
        acc = None
        pairs = 0
        if group == 1:
            for batch in iterator:
                loss = self.train_batch_async(batch)
                acc = loss if acc is None else acc + loss
                pairs += batch.count
            return 0.0 if acc is None else float(acc), pairs
        buf = []
        for batch in iterator:
            buf.append(batch)
            if len(buf) == group:
                loss = self._train_group(buf)
                acc = loss if acc is None else acc + loss
                pairs += sum(b.count for b in buf)
                buf = []
        if buf:
            loss = self._train_group(buf)
            acc = loss if acc is None else acc + loss
            pairs += sum(b.count for b in buf)
        return 0.0 if acc is None else float(acc), pairs

    def prepared(self, batches):
        """Adapter for the loader thread. Local mode needs no host
        preparation (negatives sample in-jit) — identity; the PS
        subclass overrides with CompactBatch preparation."""
        return batches

    @property
    def embeddings(self) -> np.ndarray:
        return np.asarray(self._emb_in)

    def save_embeddings(self, path: str) -> None:
        """word2vec text format (ref rank-0 save,
        distributed_wordembedding.cpp:231-236)."""
        from ...io import StreamFactory
        emb = self.embeddings
        with StreamFactory.get_stream(path, "w") as stream:
            stream.write(f"{emb.shape[0]} {emb.shape[1]}\n".encode())
            for word, row in zip(self.dictionary.words, emb):
                vec = " ".join(f"{x:.6f}" for x in row)
                stream.write(f"{word} {vec}\n".encode())


@functools.lru_cache(maxsize=None)
def _full_mask(size: int):
    return jnp.ones((size,), jnp.float32)


def _sigmoid_xent(logits, labels):
    """Numerically stable sigmoid cross-entropy."""
    return jnp.maximum(logits, 0) - logits * labels \
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))


class _Prep:
    """One batch's prepared pull: the CompactBatch plus the in-flight
    async Get requests and their destination buffers."""

    __slots__ = ("compact", "buf_in", "buf_out", "mid_in", "mid_out")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class _Launched:
    __slots__ = ("prep", "delta_in", "delta_out", "loss")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class PSWord2Vec(Word2Vec):
    """Distributed trainer over row-sharded matrix tables.

    Redesigned around the reference's block protocol
    (ref: Applications/WordEmbedding/src/communicator.cpp:117-249,
    distributed_wordembedding.cpp:203-224):

    - each batch pulls ONLY the rows its CompactBatch names — never the
      full V x D tables;
    - the shared compact loss trains the pulled [R, D] row matrices;
    - row deltas ``(new - old) / num_workers`` push back asynchronously,
      acks drained before any barrier or full-table read;
    - ``train_batches`` pipelines: batch i+1's pull is serviced by the
      server actors while batch i's step runs on device;
    - word-count KV traffic for the lr schedule is async and amortized
      over ``_WC_SYNC`` batches (ref: communicator.cpp:251-259 runs it
      on a side thread)."""

    _WC_SYNC = 16  # batches between global word-count syncs

    def __init__(self, config: Word2VecConfig, dictionary: Dictionary,
                 num_workers: Optional[int] = None):
        self._num_workers_override = num_workers
        super().__init__(config, dictionary)
        if self._in_table is None:  # server-only rank: tables hosted
            return
        zoo = self._in_table.zoo
        self._rng = np.random.default_rng(
            config.seed + 97 * max(zoo.worker_id, 0))
        self._wc_pending = 0.0
        self._batches_done = 0
        self._pending_pushes: List = []
        # Pipelined Get prefetch (-max_get_staleness > 0, host path
        # only): while the device computes step i, step i+1's rows are
        # prefetched into the client cache, so _prepare's real pull
        # hits locally or joins the in-flight fetch instead of paying a
        # fresh wire roundtrip. The device path already keeps the whole
        # loop in HBM — nothing to hide there.
        self._use_prefetch = (client_cache.cache_enabled()
                              and not self._device_path)

    def _init_embeddings(self) -> None:
        """No full local matrices: the input table is random-initialized
        SERVER-side (the reference's random-init server ctor,
        ref: matrix_table.cpp:372-384), so no V x D array ever
        materializes on a worker — at reference scale (21M x D) it could
        not."""
        config = self.config
        vocab, dim = self.dictionary.size, config.embedding_size
        bound = 0.5 / dim
        self._in_table = create_matrix_table(
            vocab, dim, updater_type="default",
            random_init=(-bound, bound), seed=config.seed)
        self._out_table = create_matrix_table(self._out_rows, dim,
                                              updater_type="default")
        self._wc_table = create_kv_table()
        if self._in_table is None:
            # Server-only rank (-ps_role=server): it hosts its table
            # shards and idles — the reference runs the same binary on
            # every rank and lets role decide (src/zoo.cpp:29-35). No
            # worker-side step/bucket state to build.
            from ...runtime.zoo import current_zoo
            self._device_path = current_zoo().servers_in_process
            self._num_workers = max(current_zoo().num_workers, 1)
            return
        zoo = self._in_table.zoo
        self._num_workers = max(
            zoo.num_workers if self._num_workers_override is None
            else self._num_workers_override, 1)
        # When every server shard lives in THIS process the whole
        # pull->step->push loop stays in HBM: device row gathers, device
        # delta scatters — no host round-trips (critical when the
        # host<->device link is slow relative to HBM). That covers both
        # the single-process cluster AND a co-located worker+server rank
        # in a multi-process -ps_role deployment; workers whose server
        # traffic crosses the wire take the host-buffer path.
        self._device_path = zoo.servers_in_process
        # FROZEN row buckets: each batch's unique row count is bounded
        # by what the batch can touch, so padding every request to that
        # one bound gives exactly one compiled gather/step/scatter shape
        # per table — warming 2 batches covers the whole compile set.
        # (A floating power-of-two ladder compiles a program PER
        # distinct size combination, serially, on first touch — the
        # round-2 "warmup tax" that cost ~300s per run.)
        from ...updater.engine import bucket_size
        batch = config.batch_size
        in_cap = batch * (2 * config.window if config.cbow else 1)
        if config.hs:
            out_cap = batch * int(self._points_host.shape[1])
        else:
            nb = max(int(getattr(config, "neg_block", 1)), 1)
            out_cap = batch + batch * config.negative // nb
        self._pad_in_min = bucket_size(min(in_cap, vocab))
        self._pad_out_min = bucket_size(min(out_cap, self._out_rows))
        self._step = self._build_ps_step()

    def _build_ps_step(self):
        loss_fn = self._compact_loss()

        def step(ein, eout, lr_scaled, in_args, out_args, pair_mask):
            """One fused jitted step returning the PUSH deltas directly:
            ``-lr * grad / num_workers`` (the reference's
            ``(new - old) / num_workers`` with one local step,
            ref: communicator.cpp:157-249) plus the batch loss."""
            loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
                ein, eout, in_args, out_args, pair_mask)
            return -lr_scaled * grads[0], -lr_scaled * grads[1], loss

        return jax.jit(step)

    # -- phase 1: row-set preparation + async pull --
    def _prepare(self, batch) -> _Prep:
        compact = batch if isinstance(batch, CompactBatch) \
            else self.prepare(batch)
        if self._device_path:
            # Device pull of the PADDED row sets (gather duplicates are
            # free; the result is already step-shaped in HBM).
            return _Prep(
                compact=compact, buf_in=None, buf_out=None,
                mid_in=self._in_table.get_rows_device_async(
                    compact.rows_in_p),
                mid_out=self._out_table.get_rows_device_async(
                    compact.rows_out_p))
        # Host path: pull only the REAL unique rows into the head of the
        # padded buffer (the padded tail is never referenced by the
        # compact index maps and its deltas are sliced off before the
        # push, so it only needs to be NaN-free). Requesting the padded
        # vector instead would ship thousands of duplicates of the last
        # row over the wire in both directions.
        n_in, n_out = compact.rows_in.size, compact.rows_out.size
        buf_in = np.empty((compact.rows_in_p.size, self._dim), np.float32)
        buf_out = np.empty((compact.rows_out_p.size, self._dim),
                           np.float32)
        buf_in[n_in:] = 0.0
        buf_out[n_out:] = 0.0
        return _Prep(
            compact=compact, buf_in=buf_in, buf_out=buf_out,
            mid_in=self._in_table.get_rows_async(compact.rows_in,
                                                 out=buf_in[:n_in]),
            mid_out=self._out_table.get_rows_async(compact.rows_out,
                                                   out=buf_out[:n_out]))

    # -- phase 2: wait the pull, dispatch the device step (async) --
    def _launch(self, prep: _Prep) -> _Launched:
        compact = prep.compact
        with monitor("PS_GET_STALL"):
            # The trainer's pull-stall: wire latency NOT hidden by the
            # pipeline (cache hits and completed prefetches make this
            # ~zero; the bench's client_cache phase reads it).
            self._in_table.wait(prep.mid_in)
            self._out_table.wait(prep.mid_out)
        if self._device_path:
            old_in = self._in_table.take_device_rows()
            old_out = self._out_table.take_device_rows()
        else:
            old_in = jnp.asarray(prep.buf_in)
            old_out = jnp.asarray(prep.buf_out)
        lr_scaled = jnp.float32(self.learning_rate() / self._num_workers)
        delta_in, delta_out, loss = self._step(
            old_in, old_out, lr_scaled,
            tuple(jnp.asarray(a) for a in compact.in_args),
            tuple(jnp.asarray(a) for a in compact.out_args),
            self._pair_mask_for(compact.count, compact.size))
        return _Launched(prep=prep, delta_in=delta_in,
                         delta_out=delta_out, loss=loss)

    # -- phase 3: push deltas, account words --
    def _finish(self, launched: _Launched):
        """Push this batch's deltas (device arrays stay in HBM on the
        device path) and return the batch loss as a DEVICE scalar — the
        hot loop must not synchronize on it."""
        compact = launched.prep.compact
        if self._device_path:
            # Padded device push: padded slots carry exactly-zero deltas
            # (their rows got no gradient), so the duplicate trailing ids
            # scatter-add zeros — a no-op.
            push_in, rows_in = launched.delta_in, compact.rows_in_p
            push_out, rows_out = launched.delta_out, compact.rows_out_p
        else:
            push_in = np.asarray(launched.delta_in)[:compact.rows_in.size]
            rows_in = compact.rows_in
            push_out = np.asarray(
                launched.delta_out)[:compact.rows_out.size]
            rows_out = compact.rows_out
        self._pending_pushes.append(
            (self._in_table,
             self._in_table.add_rows_async(rows_in, push_in)))
        self._pending_pushes.append(
            (self._out_table,
             self._out_table.add_rows_async(rows_out, push_out)))
        self._account_words(compact.words)
        return launched.loss

    def _drain_pushes(self) -> None:
        """Wait every outstanding Add ack: a barrier alone orders only
        controller traffic, not worker->server adds still in TCP flight —
        peers reading after the barrier would nondeterministically miss
        them."""
        for table, msg_id in self._pending_pushes:
            table.wait(msg_id)
        self._pending_pushes.clear()

    def _flush_word_count(self) -> None:
        if self._wc_pending:
            self._wc_table.add_async([0], [self._wc_pending])
            self._wc_pending = 0.0

    def _account_words(self, words: float) -> None:
        """Global word count for the lr schedule via the KV table, synced
        every _WC_SYNC batches (the reference keeps it off the hot path on
        a side thread, ref: distributed_wordembedding.cpp:92-134)."""
        self.trained_words += words
        self._wc_pending += words
        self._batches_done += 1
        if self._batches_done % self._WC_SYNC == 0:
            self._flush_word_count()
            global_words = self._wc_table.get([0])[0]
            # Take the max: the global clock includes our own pushes and
            # every peer's; between syncs we advance locally.
            self.trained_words = max(self.trained_words, int(global_words))

    # -- public API --
    def prepared(self, batches):
        """Generator adapter: raw batches -> CompactBatch (run inside a
        BlockLoader so host row preparation overlaps device steps)."""
        for batch in batches:
            yield self.prepare(batch)

    def train_batch(self, batch) -> float:
        launched = self._launch(self._prepare(batch))
        loss = self._finish(launched)
        self._drain_pushes()
        return float(loss) / max(launched.prep.compact.count, 1)

    def train_batch_async(self, batch):
        return jnp.float32(self.train_batch(batch))

    def _prefetched(self, batches):
        """Double-buffer adapter: prepare batch i+1 and PREFETCH its row
        sets into the client cache before yielding batch i, so the real
        pull in ``_prepare`` overlaps the device step instead of
        serializing behind it (the async twin of the reference's
        pipelined block protocol, distributed_wordembedding.cpp:203-224
        — there via double server-side consumer slots, here via the
        versioned worker cache)."""
        held = None
        for batch in batches:
            compact = batch if isinstance(batch, CompactBatch) \
                else self.prepare(batch)
            self._in_table.prefetch_rows_async(compact.rows_in)
            self._out_table.prefetch_rows_async(compact.rows_out)
            if held is not None:
                yield held
            held = compact
        if held is not None:
            yield held

    def train_batches(self, iterator) -> Tuple[float, int]:
        """Pipelined loop: batch i+1's row pull is serviced by the server
        actors while batch i's step runs on device and its deltas push
        (ref overlap: distributed_wordembedding.cpp:203-224). Losses
        accumulate as device scalars — one host materialization at the
        end, no per-batch syncs. With the client cache enabled the loop
        additionally prefetches batch i+1's rows during batch i's step
        (see ``_prefetched``)."""
        acc = None
        pairs = 0
        launched: Optional[_Launched] = None
        if self._use_prefetch:
            iterator = self._prefetched(iterator)
        for batch in iterator:
            prep = self._prepare(batch)  # async pull in flight
            if launched is not None:
                loss = self._finish(launched)
                acc = loss if acc is None else acc + loss
                pairs += launched.prep.compact.count
            launched = self._launch(prep)
        if launched is not None:
            loss = self._finish(launched)
            acc = loss if acc is None else acc + loss
            pairs += launched.prep.compact.count
        # Every push acked, trailing word count published, then the
        # barrier: a peer's post-barrier read sees all of our updates.
        self._drain_pushes()
        self._flush_word_count()
        self._in_table.zoo.barrier()
        return 0.0 if acc is None else float(acc), pairs

    @property
    def embeddings(self) -> np.ndarray:
        self._drain_pushes()
        return self._in_table.get()
