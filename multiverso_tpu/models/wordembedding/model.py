"""Word2vec models: SGNS + hierarchical softmax, skip-gram + CBOW.

TPU-native re-design of the reference's WordEmbedding compute core
(ref: Applications/WordEmbedding/src/wordembedding.cpp — per-window scalar
FeedForward/BPOutputLayer loops): here one jitted step trains a whole
batch of (center, context) pairs on the MXU —

- negative sampling (SGNS): negatives are drawn inside the jit by
  inverse-CDF over the unigram^0.75 distribution; logits are a gathered
  batched dot product ``einsum('bd,bkd->bk')`` over [positive, K
  negatives]; gradients scatter-add into both embedding matrices;
- hierarchical softmax: each pair trains the Huffman path of the context
  word — codes/points are gathered from device-resident [V, L] tables
  (built by huffman.py) and padded path slots are masked;
- CBOW averages the (padded, masked) context window into the input vector
  and scatters the input gradient back to every window word.

Embeddings are plain device arrays locally; the PS variant keeps them in
row-sharded matrix tables and trains blocks on pulled rows, pushing
``(new - old) / num_workers`` exactly like the reference's
AddDeltaParameter (ref: communicator.cpp:157-249).

The learning rate decays linearly in processed words:
``lr = initial * max(1 - done/total, 1e-4)`` (ref:
distributed_wordembedding.cpp:92-134 recomputes it from the global word
count; in distributed mode that count lives in a KV table).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ... import create_kv_table, create_matrix_table
from ...updater.engine import pad_ids
from .data import CbowBatch, PairBatch
from .dictionary import Dictionary
from .huffman import build_huffman


_MAX_EXP = 6.0  # word2vec.c's sigmoid-table range


class Word2VecConfig:
    """Mirror of the reference's CLI options (ref: WordEmbedding
    src/util.cpp ParseArgs: -size -window -negative -epoch -min_count
    -sample -init_learning_rate -cbow -hs ...)."""

    def __init__(self, embedding_size: int = 100, window: int = 5,
                 negative: int = 5, epochs: int = 1, min_count: int = 5,
                 sample: float = 1e-3, init_learning_rate: float = 0.025,
                 cbow: bool = False, hs: bool = False,
                 batch_size: int = 4096, seed: int = 1,
                 use_ps: bool = False):
        self.embedding_size = embedding_size
        self.window = window
        self.negative = negative
        self.epochs = epochs
        self.min_count = min_count
        self.sample = sample
        self.init_learning_rate = init_learning_rate
        self.cbow = cbow
        self.hs = hs
        self.batch_size = batch_size
        self.seed = seed
        self.use_ps = use_ps


class Word2Vec:
    """Local (single-process) trainer; device-resident embeddings."""

    _DONATE = True  # PS subclass keeps old params to form wire deltas

    def __init__(self, config: Word2VecConfig, dictionary: Dictionary):
        self.config = config
        self.dictionary = dictionary
        self._out_rows = self._init_output_structures()
        self._key = jax.random.PRNGKey(config.seed)
        self.trained_words = 0
        self.total_words = dictionary.total_count * config.epochs
        self._init_embeddings()

    def _init_output_structures(self) -> int:
        """Huffman tables (hs) or the unigram^0.75 CDF (sgns); returns the
        output-embedding row count. Host copies back the PS row-set
        preparation (which must know the touched output rows before the
        device step runs)."""
        config, dictionary = self.config, self.dictionary
        if config.hs:
            tree = build_huffman(dictionary.counts)
            self._codes_host = np.asarray(tree.codes)
            self._points_host = np.asarray(tree.points)
            self._codes = jnp.asarray(tree.codes)
            self._points = jnp.asarray(tree.points)
            return max(tree.num_inner_nodes, 1)
        neg = dictionary.negative_table()
        # float64 accumulation: a float32 cumsum's last entry lands
        # measurably below 1.0 and uniform draws above it would index one
        # past the last word.
        self._neg_cdf_host = np.cumsum(neg, dtype=np.float64)
        self._neg_cdf = jnp.asarray(self._neg_cdf_host)
        return dictionary.size

    def _init_embeddings(self) -> None:
        """Local mode: full device-resident matrices. ref init: uniform
        (-0.5/dim, 0.5/dim) input, zeros output. The PS subclass overrides
        this with table creation (no full local copies)."""
        vocab, dim = self.dictionary.size, self.config.embedding_size
        rng = np.random.default_rng(self.config.seed)
        self._emb_in = jnp.asarray(
            (rng.random((vocab, dim)) - 0.5) / dim, jnp.float32)
        self._emb_out = jnp.zeros((self._out_rows, dim), jnp.float32)
        self._step = self._build_step()

    # -- learning rate schedule --
    def learning_rate(self) -> float:
        remain = max(1.0 - self.trained_words / max(self.total_words, 1),
                     1e-4)
        return self.config.init_learning_rate * remain

    # -- the fused train step --
    def _build_step(self):
        config = self.config
        if config.hs:
            pair_loss = self._hs_pair_loss
        else:
            pair_loss = self._neg_pair_loss

        # ``pair_mask`` zeroes the tail-batch padding rows — without it the
        # padded (0, 0) pairs would train the most frequent word against
        # itself as a positive example.
        if config.cbow:
            def loss_fn(params, window, centers, pair_mask, key):
                emb_in, emb_out = params
                mask = (window >= 0).astype(jnp.float32)
                safe = jnp.maximum(window, 0)
                vecs = emb_in[safe] * mask[..., None]
                denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1.0)
                v = vecs.sum(axis=1) / denom  # [B, D] averaged window
                return pair_loss(v, centers, emb_out, pair_mask, key)
        else:
            def loss_fn(params, centers, contexts, pair_mask, key):
                emb_in, emb_out = params
                v = emb_in[centers]
                return pair_loss(v, contexts, emb_out, pair_mask, key)

        def step(params, lr, key, pair_mask, *batch):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(p, *batch, pair_mask, key))(params)
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads)
            return new_params, loss

        return jax.jit(step,
                       donate_argnums=(0,) if self._DONATE else ())

    def _neg_pair_loss(self, v, targets, emb_out, pair_mask, key,
                       negatives=None):
        """SGNS: positive target + K negatives — sampled in-jit locally,
        or host-provided in PS mode (the PS pull needs to know the rows
        before the step runs, like the reference's block preparation,
        ref: communicator.cpp:117-155)."""
        k = self.config.negative
        batch = v.shape[0]
        if negatives is None:
            uniform = jax.random.uniform(key, (batch, k))
            negatives = jnp.searchsorted(self._neg_cdf, uniform)
        cols = jnp.concatenate([targets[:, None], negatives], axis=1)
        u = emb_out[cols]  # [B, 1+K, D]
        # MAX_EXP clamp, exactly word2vec's sigmoid table: saturated pairs
        # get ZERO gradient (clip has zero derivative outside the range),
        # which is what keeps hot rows from diverging under batched sums.
        logits = jnp.clip(jnp.einsum("bd,bkd->bk", v, u),
                          -_MAX_EXP, _MAX_EXP)
        labels = jnp.concatenate(
            [jnp.ones((batch, 1)), jnp.zeros((batch, k))], axis=1)
        losses = _sigmoid_xent(logits, labels) * pair_mask[:, None]
        # SUM over the batch: word2vec applies the learning rate per pair
        # (ref trains pair-by-pair); a mean would shrink the per-pair step
        # by the batch size.
        return jnp.sum(losses)

    def _hs_pair_loss(self, v, targets, emb_out, pair_mask, key):
        """Hierarchical softmax over the target's Huffman path."""
        points = self._points[targets]  # [B, L]
        codes = self._codes[targets]
        mask = (codes >= 0).astype(jnp.float32) * pair_mask[:, None]
        u = emb_out[jnp.maximum(points, 0)]  # [B, L, D]
        logits = jnp.clip(jnp.einsum("bd,bld->bl", v, u),
                          -_MAX_EXP, _MAX_EXP)  # word2vec MAX_EXP clamp
        # code 0 = positive class (sigmoid(logit)), 1 = negative — the
        # word2vec convention (ref: wordembedding.cpp HS branch).
        labels = 1.0 - codes.astype(jnp.float32)
        losses = _sigmoid_xent(logits, labels * mask) * mask
        return jnp.sum(losses)  # per-pair lr semantics, as in SGNS

    # -- public API --
    def train_batch_async(self, batch):
        """Dispatch one training step WITHOUT synchronizing; returns the
        device scalar loss. The hot loop must not materialize per-batch
        scalars — a host fetch per step serializes on device/tunnel
        latency and caps words/sec."""
        lr = jnp.float32(self.learning_rate())
        self._key, subkey = jax.random.split(self._key)
        params = (self._emb_in, self._emb_out)
        if isinstance(batch, CbowBatch):
            args = (jnp.asarray(batch.window), jnp.asarray(batch.centers))
            size = batch.centers.shape[0]
        else:
            args = (jnp.asarray(batch.centers), jnp.asarray(batch.contexts))
            size = batch.centers.shape[0]
        pair_mask = _full_mask(size) if batch.count == size \
            else jnp.asarray((np.arange(size) < batch.count)
                             .astype(np.float32))
        (self._emb_in, self._emb_out), loss = self._step(
            params, lr, subkey, pair_mask, *args)
        self.trained_words += batch.words
        return loss

    def train_batch(self, batch) -> float:
        loss = self.train_batch_async(batch)
        return float(loss) / max(batch.count, 1)  # display per-pair loss

    def train_batches(self, iterator) -> Tuple[float, int]:
        """Drive a whole batch stream; returns (loss_sum, pair_count).
        Device losses accumulate without host syncs (one materialization
        at the end). The PS subclass overrides this with a pipelined
        pull/train/push loop."""
        losses = []
        pairs = 0
        for batch in iterator:
            losses.append(self.train_batch_async(batch))
            pairs += batch.count
        return float(sum(float(x) for x in losses)), pairs

    @property
    def embeddings(self) -> np.ndarray:
        return np.asarray(self._emb_in)

    def save_embeddings(self, path: str) -> None:
        """word2vec text format (ref rank-0 save,
        distributed_wordembedding.cpp:231-236)."""
        from ...io import StreamFactory
        emb = self.embeddings
        with StreamFactory.get_stream(path, "w") as stream:
            stream.write(f"{emb.shape[0]} {emb.shape[1]}\n".encode())
            for word, row in zip(self.dictionary.words, emb):
                vec = " ".join(f"{x:.6f}" for x in row)
                stream.write(f"{word} {vec}\n".encode())


@functools.lru_cache(maxsize=None)
def _full_mask(size: int):
    return jnp.ones((size,), jnp.float32)


def _sigmoid_xent(logits, labels):
    """Numerically stable sigmoid cross-entropy."""
    return jnp.maximum(logits, 0) - logits * labels \
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))


def _pad_rows(rows: np.ndarray, minimum: int = 8) -> np.ndarray:
    """Pad a sorted unique row-id set to the next power of two (bounded
    set of jit trace shapes) by repeating the last id. Padded slots are
    never referenced by the compact index maps, so their pulled contents
    and deltas are irrelevant (deltas are sliced off before the push)."""
    n = max(int(rows.size), 1)
    target = max(minimum, 1 << (n - 1).bit_length())
    if rows.size == 0:
        return np.zeros(target, np.int32)
    if rows.size == target:
        return rows
    return np.concatenate(
        [rows, np.full(target - rows.size, rows[-1], np.int32)])


class _Prep:
    """One batch's prepared pull: row sets, compact index maps, and the
    in-flight async Get requests."""

    __slots__ = ("batch", "rows_in", "rows_out", "in_args", "out_args",
                 "buf_in", "buf_out", "mid_in", "mid_out")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class _Launched:
    __slots__ = ("prep", "new_in", "new_out", "old_in", "old_out", "loss")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class PSWord2Vec(Word2Vec):
    """Distributed trainer over row-sharded matrix tables.

    Redesigned around the reference's block protocol
    (ref: Applications/WordEmbedding/src/communicator.cpp:117-249,
    distributed_wordembedding.cpp:203-224):

    - each batch pulls ONLY the embedding rows it touches (input rows =
      its centers/window words; output rows = its targets plus host-
      sampled negatives or Huffman path nodes), never the full V x D
      tables;
    - the jitted step trains on the compact [R, D] row matrices (batch
      indices are remapped host-side to compact slots), so step FLOPs and
      HBM traffic scale with the batch, not the vocabulary;
    - it pushes ``(new - old) / num_workers`` for exactly those rows;
    - ``train_batches`` pipelines: while the device runs step i, the next
      batch's row pull is already in flight through the server actors
      (the reference's ``-is_pipeline`` prefetch overlap), and the word-
      count KV traffic is async and amortized over ``_WC_SYNC`` batches
      (ref: communicator.cpp:251-259 runs it on a side thread).
    """

    _DONATE = False
    _WC_SYNC = 16  # batches between global word-count syncs

    def __init__(self, config: Word2VecConfig, dictionary: Dictionary,
                 num_workers: Optional[int] = None):
        self._num_workers_override = num_workers
        super().__init__(config, dictionary)
        zoo = self._in_table.zoo
        self._rng = np.random.default_rng(
            config.seed + 97 * max(zoo.worker_id, 0))
        self._compact_step = self._build_compact_step()
        self._wc_pending = 0.0
        self._batches_done = 0
        self._pending_pushes: list = []

    def _init_embeddings(self) -> None:
        """No full local matrices: the input table is random-initialized
        SERVER-side (the reference's random-init server ctor,
        ref: matrix_table.cpp:372-384), so no V x D array ever
        materializes on a worker — at reference scale (21M x D) it could
        not."""
        config = self.config
        vocab, dim = self.dictionary.size, config.embedding_size
        self._dim = dim
        bound = 0.5 / dim
        self._in_table = create_matrix_table(
            vocab, dim, updater_type="default",
            random_init=(-bound, bound), seed=config.seed)
        self._out_table = create_matrix_table(self._out_rows, dim,
                                              updater_type="default")
        self._wc_table = create_kv_table()
        zoo = self._in_table.zoo
        self._num_workers = max(
            zoo.num_workers if self._num_workers_override is None
            else self._num_workers_override, 1)

    # -- compact jitted step over pulled rows --
    def _build_compact_step(self):
        config = self.config

        def input_vec(ein, in_args):
            if config.cbow:
                win_l, win_mask = in_args
                vecs = ein[win_l] * win_mask[..., None]
                denom = jnp.maximum(win_mask.sum(axis=1, keepdims=True),
                                    1.0)
                return vecs.sum(axis=1) / denom
            (centers_l,) = in_args
            return ein[centers_l]

        if config.hs:
            def loss_fn(ein, eout, in_args, out_args, pair_mask):
                v = input_vec(ein, in_args)
                points_l, codes = out_args
                mask = (codes >= 0).astype(jnp.float32) * pair_mask[:, None]
                u = eout[points_l]  # [B, L, D]
                logits = jnp.clip(jnp.einsum("bd,bld->bl", v, u),
                                  -_MAX_EXP, _MAX_EXP)
                labels = 1.0 - codes.astype(jnp.float32)
                return jnp.sum(_sigmoid_xent(logits, labels * mask) * mask)
        else:
            k = config.negative

            def loss_fn(ein, eout, in_args, out_args, pair_mask):
                v = input_vec(ein, in_args)
                targets_l, negs_l = out_args
                cols = jnp.concatenate([targets_l[:, None], negs_l], axis=1)
                u = eout[cols]  # [B, 1+K, D]
                logits = jnp.clip(jnp.einsum("bd,bkd->bk", v, u),
                                  -_MAX_EXP, _MAX_EXP)
                batch = v.shape[0]
                labels = jnp.concatenate(
                    [jnp.ones((batch, 1)), jnp.zeros((batch, k))], axis=1)
                return jnp.sum(_sigmoid_xent(logits, labels)
                               * pair_mask[:, None])

        def step(ein, eout, lr, in_args, out_args, pair_mask):
            loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
                ein, eout, in_args, out_args, pair_mask)
            return ein - lr * grads[0], eout - lr * grads[1], loss

        return jax.jit(step)

    # -- phase 1: row-set preparation + async pull --
    def _prepare(self, batch) -> _Prep:
        config = self.config
        if isinstance(batch, CbowBatch):
            win, targets = batch.window, batch.centers
            real = win[win >= 0]
            rows_in = np.unique(real).astype(np.int32) if real.size \
                else np.zeros(1, np.int32)
            win_l = np.clip(np.searchsorted(rows_in, np.maximum(win, 0)),
                            0, rows_in.size - 1).astype(np.int32)
            in_args = (win_l, (win >= 0).astype(np.float32))
        else:
            centers, targets = batch.centers, batch.contexts
            rows_in = np.unique(centers).astype(np.int32)
            in_args = (np.searchsorted(rows_in, centers).astype(np.int32),)

        if config.hs:
            points = self._points_host[targets]  # [B, L], -1 padded
            real = points[points >= 0]
            rows_out = np.unique(real).astype(np.int32) if real.size \
                else np.zeros(1, np.int32)
            points_l = np.clip(
                np.searchsorted(rows_out, np.maximum(points, 0)),
                0, rows_out.size - 1).astype(np.int32)
            out_args = (points_l, self._codes_host[targets])
        else:
            k = config.negative
            # Clip: a draw above cdf[-1] (float rounding) must not index
            # one past the last word.
            neg = np.minimum(
                np.searchsorted(self._neg_cdf_host,
                                self._rng.random((targets.size, k))),
                self.dictionary.size - 1).astype(np.int32)
            rows_out = np.unique(
                np.concatenate([targets, neg.reshape(-1)])).astype(np.int32)
            out_args = (np.searchsorted(rows_out, targets).astype(np.int32),
                        np.searchsorted(rows_out, neg).astype(np.int32))

        rows_in_p = _pad_rows(rows_in)
        rows_out_p = _pad_rows(rows_out)
        buf_in = np.empty((rows_in_p.size, self._dim), np.float32)
        buf_out = np.empty((rows_out_p.size, self._dim), np.float32)
        return _Prep(
            batch=batch, rows_in=rows_in, rows_out=rows_out,
            in_args=in_args, out_args=out_args,
            buf_in=buf_in, buf_out=buf_out,
            mid_in=self._in_table.get_rows_async(rows_in_p, out=buf_in),
            mid_out=self._out_table.get_rows_async(rows_out_p, out=buf_out))

    # -- phase 2: wait the pull, dispatch the device step (async) --
    def _launch(self, prep: _Prep) -> _Launched:
        self._in_table.wait(prep.mid_in)
        self._out_table.wait(prep.mid_out)
        old_in = jnp.asarray(prep.buf_in)
        old_out = jnp.asarray(prep.buf_out)
        size = prep.batch.centers.shape[0]
        pair_mask = _full_mask(size) if prep.batch.count == size \
            else jnp.asarray((np.arange(size) < prep.batch.count)
                             .astype(np.float32))
        new_in, new_out, loss = self._compact_step(
            old_in, old_out, jnp.float32(self.learning_rate()),
            tuple(jnp.asarray(a) for a in prep.in_args),
            tuple(jnp.asarray(a) for a in prep.out_args), pair_mask)
        return _Launched(prep=prep, new_in=new_in, new_out=new_out,
                         old_in=old_in, old_out=old_out, loss=loss)

    # -- phase 3: materialize deltas, push, account words --
    def _finish(self, launched: _Launched) -> float:
        prep = launched.prep
        scale = 1.0 / self._num_workers
        delta_in = np.asarray((launched.new_in - launched.old_in) * scale)
        delta_out = np.asarray((launched.new_out - launched.old_out)
                               * scale)
        self._pending_pushes.append((self._in_table,
                                     self._in_table.add_rows_async(
                                         prep.rows_in,
                                         delta_in[:prep.rows_in.size])))
        self._pending_pushes.append((self._out_table,
                                     self._out_table.add_rows_async(
                                         prep.rows_out,
                                         delta_out[:prep.rows_out.size])))
        self._account_words(prep.batch.words)
        return float(launched.loss) / max(prep.batch.count, 1)

    def _drain_pushes(self) -> None:
        """Wait every outstanding Add ack: a barrier alone orders only
        controller traffic, not worker->server adds still in TCP flight —
        peers reading after the barrier would nondeterministically miss
        them."""
        for table, msg_id in self._pending_pushes:
            table.wait(msg_id)
        self._pending_pushes.clear()

    def _flush_word_count(self) -> None:
        if self._wc_pending:
            self._wc_table.add_async([0], [self._wc_pending])
            self._wc_pending = 0.0

    def _account_words(self, words: float) -> None:
        """Global word count for the lr schedule via the KV table, synced
        every _WC_SYNC batches (the reference keeps it off the hot path on
        a side thread, ref: distributed_wordembedding.cpp:92-134)."""
        self.trained_words += words
        self._wc_pending += words
        self._batches_done += 1
        if self._batches_done % self._WC_SYNC == 0:
            self._flush_word_count()
            global_words = self._wc_table.get([0])[0]
            # Take the max: the global clock includes our own pushes and
            # every peer's; between syncs we advance locally.
            self.trained_words = max(self.trained_words, int(global_words))

    # -- public API --
    def train_batch(self, batch) -> float:
        loss = self._finish(self._launch(self._prepare(batch)))
        self._drain_pushes()
        return loss

    def train_batch_async(self, batch):
        return jnp.float32(self.train_batch(batch))

    def train_batches(self, iterator) -> Tuple[float, int]:
        """Pipelined loop: batch i+1's row pull is serviced by the server
        actors while batch i's step runs on device and its deltas push
        (ref overlap: distributed_wordembedding.cpp:203-224)."""
        loss_sum = 0.0
        pairs = 0
        launched: Optional[_Launched] = None
        for batch in iterator:
            prep = self._prepare(batch)  # async pull in flight
            if launched is not None:
                loss_sum += self._finish(launched) \
                    * max(launched.prep.batch.count, 1)
                pairs += launched.prep.batch.count
            launched = self._launch(prep)
        if launched is not None:
            loss_sum += self._finish(launched) \
                * max(launched.prep.batch.count, 1)
            pairs += launched.prep.batch.count
        # Every push acked, trailing word count published, then the
        # barrier: a peer's post-barrier read sees all of our updates.
        self._drain_pushes()
        self._flush_word_count()
        self._in_table.zoo.barrier()
        return loss_sum, pairs

    @property
    def embeddings(self) -> np.ndarray:
        self._drain_pushes()
        return self._in_table.get()
