"""Device-resident corpus training: the word2vec data pipeline in HBM.

The round-2 hot loop shipped every batch's (center, context) ids from the
host; on a tunneled device that transfer (plus one dispatch per batch)
bounds words/sec long before the chip works. This module is the
TPU-native fix: the TOKENIZED CORPUS is uploaded once (~4 bytes/token)
and everything the reference's reader/trainer pipeline does per pass —
subsampling, sentence-bounded dynamic windows, negative sampling, the
SGNS update — happens inside jitted device programs
(ref: Applications/WordEmbedding/src/reader.cpp — subsample-as-you-read;
wordembedding.cpp — per-center shrunk window + SGNS FeedForward/
BPOutputLayer). The host's only per-epoch work is the learning-rate
schedule (a handful of scalars per dispatch group) and one scalar fetch
of the post-subsampling length.

Per epoch, one jitted ``_prep`` pass draws the subsample mask and
stably compacts kept tokens to the front (word2vec subsamples BEFORE
windowing, so windows must span the kept sequence); training then scans
``steps_per_dispatch`` windowed steps per dispatch.

The SGNS/CBOW steps use a BANDED formulation that exploits window
overlap: the contexts of C consecutive centers all lie in the
contiguous band ``kept[base-W : base+C+W]``, so the step gathers those
C+2W rows ONCE and forms the 2W context logits as shifted slices of the
band — 2W-fold less gather AND scatter row traffic than materializing
the [C, 2W] context row matrix, which round-3 profiling showed was the
step's dominant cost (scatter of C*(2W+K) ≈ 0.5M random rows per step).
The per-center shrunk window and sentence bounds survive as masks on
the shifted slices; the update math is bit-identical to the row-matrix
form (duplicates in the band sum, exactly as duplicate scatter ids
did). Negatives come from the unigram^0.75 alias tables, drawn per
center by default; ``neg_block`` > 1 shares one draw of K negatives
across each block of that many consecutive centers (expected gradient
unchanged — every center still sees K ^0.75-unigram negatives — but the
random-row traffic for negatives drops by the block factor; measured
~1.8x words/s at block 32 on v5e).

Measured v5e cost model (see PROGRESS notes, round 4): full-table
sweeps run near peak (~680 GB/s), row gathers ~50-100 GB/s, random-row
scatter-adds are the slowest path (~13 GB/s at 32K rows) — so the
design minimizes SCATTERED ROWS first, gathered rows second, and
never sweeps.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...runtime import device_lock
from .data import TokenizedCorpus
from .model import _MAX_EXP, _sigmoid_xent


# -- per-epoch subsample + stable compaction (shape-polymorphic jit) --
@jax.jit
def _prep(flat, sent, keep, key):
    mask = jax.random.uniform(key, flat.shape) < keep[flat]
    # Stable: kept tokens keep corpus order, so positional distance in
    # the compacted array IS the word2vec window distance over the
    # subsampled sentence.
    order = jnp.argsort(jnp.where(mask, 0, 1).astype(jnp.int8),
                        stable=True)
    kept = flat[order]
    # Dropped tail gets sentence -1: it can never match a real sentence
    # id, so windows cannot cross into it.
    ksent = jnp.where(mask[order], sent[order], -1)
    return kept, ksent, mask.sum(dtype=jnp.int32)


def _pad_stream(C, W, kept, ksent):
    """Pad the compacted stream so banded slices never clamp: W on the
    left, C+W on the right (a clamped ``dynamic_slice`` would shift the
    whole band and misalign valid centers on the epoch's tail step).
    Padding carries sentence -2, which never matches a real sentence,
    so every padded position is masked out."""
    return (jnp.pad(kept, (W, C + W)),
            jnp.pad(ksent, (W, C + W), constant_values=-2))


def _band_former(C, W, n_kept, kept_pad, ksent_pad, k_shrink, base):
    """The banded window former: C consecutive kept positions as
    centers; their contexts all lie in the contiguous band
    ``kept[base-W : base+C+W]`` (C+2W tokens), and the per-(center,
    offset) validity — in-stream, same sentence, within the per-center
    shrunk window (the word2vec trick, ref: wordembedding.cpp Train
    window sampling) — is a mask over shifted slices of the band.
    Returns (centers[C], band[C+2W], pmask[C,2W])."""
    offs = [o for o in range(-W, W + 1) if o != 0]
    idx = base + jnp.arange(C, dtype=jnp.int32)
    centers = jax.lax.dynamic_slice_in_dim(kept_pad, base + W, C)
    csent = jax.lax.dynamic_slice_in_dim(ksent_pad, base + W, C)
    center_ok = (idx < n_kept) & (csent >= 0)
    shrink = jax.random.randint(k_shrink, (C,), 1, W + 1)
    band = jax.lax.dynamic_slice_in_dim(kept_pad, base, C + 2 * W)
    band_sent = jax.lax.dynamic_slice_in_dim(ksent_pad, base, C + 2 * W)
    masks = []
    for off in offs:
        p = idx + off
        inb = (p >= 0) & (p < n_kept)
        s = jax.lax.dynamic_slice_in_dim(band_sent, W + off, C)
        masks.append(inb & (s == csent) & (abs(off) <= shrink)
                     & center_ok)
    pmask = jnp.stack(masks, axis=1).astype(jnp.float32)
    return centers, band, pmask


def _draw_negs(C, K, B, neg_prob, neg_alias, k_idx, k_keep):
    """K negatives per block of B consecutive centers via the alias
    tables — B=1 is the per-center draw (and reproduces the round-3
    draws bit-exactly). Returns negs[C//B, K]."""
    nb = C // B
    draw = jax.random.randint(k_idx, (nb, K), 0, neg_prob.shape[0])
    keep_draw = jax.random.uniform(k_keep, (nb, K)) < neg_prob[draw]
    return jnp.where(keep_draw, draw, neg_alias[draw])


def _hs_center_cap(path_len: int, dim: int) -> int:
    """Centers-per-step bound for the HS pipelines: the banded path
    activations are [C+2W, L, D] plus their grad — cap C so they stay
    within ~1.5 GB of HBM. Shared by the local and PS trainers so the
    budget cannot drift between them."""
    return max((3 << 29) // (3 * max(path_len, 1) * dim * 4), 64)


def _banded_sgns_loss_and_grads(v, u_band, u_neg, pmask):
    """SGNS objective in banded form: context logits are dot products
    of each center row against 2W shifted slices of the band's OUTPUT
    rows; sigmoid xent at label 1 (masked) plus label 0 for the
    block-shared negatives (weighted by the center's valid-pair count).
    Returns (loss, g_v, g_band, g_neg)."""
    C, W = pmask.shape[0], pmask.shape[1] // 2
    nb, B = u_neg.shape[0], C // u_neg.shape[0]
    offs = [o for o in range(-W, W + 1) if o != 0]
    nvalid = pmask.sum(axis=1)

    def loss_fn(v, u_band, u_neg):
        pos = jnp.stack(
            [jnp.sum(v * jax.lax.dynamic_slice_in_dim(u_band, W + off, C),
                     axis=-1) for off in offs], axis=1)
        pos = jnp.clip(pos, -_MAX_EXP, _MAX_EXP)
        vb = v.reshape(nb, B, v.shape[-1])
        neg = jnp.clip(jnp.einsum("nbd,nkd->nbk", vb, u_neg),
                       -_MAX_EXP, _MAX_EXP)
        xp = _sigmoid_xent(pos, 1.0) * pmask
        xn = _sigmoid_xent(neg, 0.0) * nvalid.reshape(nb, B)[:, :, None]
        return xp.sum() + xn.sum()

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
        v, u_band, u_neg)
    return (loss,) + grads


def _banded_cbow_loss_and_grads(u_band, u_center, u_neg, pmask):
    """CBOW objective in banded form: the masked mean of the window's
    INPUT rows (shifted band slices) predicts the center and the
    block-shared negatives from the OUTPUT table — one example per
    center (ref: wordembedding.cpp CBOW branch; gradient through the
    mean is the 1/|window| form, as on the host-batch path).
    ``u_band`` [C+2W, D] INPUT rows, ``u_center`` [C, D] and ``u_neg``
    [C//B, K, D] OUTPUT rows. Returns
    (loss, g_band, g_center, g_neg, examples)."""
    C, W = pmask.shape[0], pmask.shape[1] // 2
    nb, B = u_neg.shape[0], C // u_neg.shape[0]
    offs = [o for o in range(-W, W + 1) if o != 0]
    nvalid = pmask.sum(axis=1)
    has_ctx = (nvalid > 0).astype(jnp.float32)

    def loss_fn(u_band, u_center, u_neg):
        denom = jnp.maximum(nvalid, 1.0)
        acc = 0.0
        for w, off in enumerate(offs):
            acc = acc + pmask[:, w:w + 1] * \
                jax.lax.dynamic_slice_in_dim(u_band, W + off, C)
        vmean = acc / denom[:, None]
        pos = jnp.clip(jnp.sum(vmean * u_center, axis=-1),
                       -_MAX_EXP, _MAX_EXP)
        vb = vmean.reshape(nb, B, vmean.shape[-1])
        neg = jnp.clip(jnp.einsum("nbd,nkd->nbk", vb, u_neg),
                       -_MAX_EXP, _MAX_EXP)
        xp = _sigmoid_xent(pos, 1.0) * has_ctx
        xn = _sigmoid_xent(neg, 0.0) \
            * has_ctx.reshape(nb, B)[:, :, None]
        return xp.sum() + xn.sum()

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
        u_band, u_center, u_neg)
    return (loss,) + grads + (has_ctx.sum(),)


def _apply_step(C, W, K, cbow, emb_in, emb_out, kept_pad, ksent_pad,
                neg_prob, neg_alias, key, base, lr, n_kept,
                neg_block: int = 1):
    """One full in-jit banded training step against local table arrays
    — band former + objective + scatter-add updates of C+2W band rows,
    C center rows and C//B negative rows (vs the C*(2W+K) scattered
    rows of the row-matrix form). Shared by the single-device group
    scan and the MA mesh path so the update math cannot diverge.
    ``kept_pad``/``ksent_pad`` must come from ``_pad_stream``. Returns
    (emb_in, emb_out, loss, examples)."""
    k_shrink, k_idx, k_keep = jax.random.split(key, 3)
    centers, band, pmask = _band_former(C, W, n_kept, kept_pad,
                                        ksent_pad, k_shrink, base)
    negs = _draw_negs(C, K, neg_block, neg_prob, neg_alias,
                      k_idx, k_keep)
    if cbow:
        # window (input table) -> [center | negs] (output table)
        u_band = emb_in[band]                 # [C+2W, D]
        u_center = emb_out[centers]           # [C, D]
        u_neg = emb_out[negs]                 # [C//B, K, D]
        loss, g_band, g_center, g_neg, examples = \
            _banded_cbow_loss_and_grads(u_band, u_center, u_neg, pmask)
        emb_in = emb_in.at[band].add(-lr * g_band)
        emb_out = emb_out.at[centers].add(-lr * g_center)
        emb_out = emb_out.at[negs].add(-lr * g_neg)
        return emb_in, emb_out, loss, examples
    v = emb_in[centers]              # [C, D]
    u_band = emb_out[band]           # [C+2W, D]
    u_neg = emb_out[negs]            # [C//B, K, D]
    loss, g_v, g_band, g_neg = _banded_sgns_loss_and_grads(
        v, u_band, u_neg, pmask)
    emb_in = emb_in.at[centers].add(-lr * g_v)
    emb_out = emb_out.at[band].add(-lr * g_band)
    emb_out = emb_out.at[negs].add(-lr * g_neg)
    return emb_in, emb_out, loss, pmask.sum()


def _pair_offset_loss_and_grads(v, u_pos, u_neg, m):
    """One offset's C pairs of the quality mode: label-1 xent against
    the positive rows, label-0 against that offset's per-pair
    negatives, masked by the pair validity. Shared by the local
    sequential sub-steps and the PS block's local-copy sub-steps so the
    quality-mode objective cannot diverge between pipelines. Returns
    (loss, g_v, g_pos, g_neg)."""

    def loss_fn(v, u_pos, u_neg):
        pos = jnp.clip(jnp.sum(v * u_pos, axis=-1), -_MAX_EXP, _MAX_EXP)
        neg = jnp.clip(jnp.einsum("cd,ckd->ck", v, u_neg),
                       -_MAX_EXP, _MAX_EXP)
        return (jnp.sum(_sigmoid_xent(pos, 1.0) * m)
                + jnp.sum(_sigmoid_xent(neg, 0.0) * m[:, None]))

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
        v, u_pos, u_neg)
    return (loss,) + grads


def _seq_pair_step(C, W, K, emb_in, emb_out, kept_pad, ksent_pad,
                   neg_prob, neg_alias, key, base, lr, n_kept):
    """QUALITY-mode skip-gram step: per-PAIR negatives and per-offset
    SEQUENTIAL updates — the closest in-jit approximation of the
    reference's pair-by-pair SGD (ref: wordembedding.cpp Train: each
    (center, context) pair draws its own K negatives and applies its
    update before the next pair trains). The 2W offsets run as
    sequential sub-steps against the LIVE tables, so each offset's C
    pairs see every earlier offset's updates. ~8x the row traffic of
    the shared-negative banded step — measured on the bench corpus it
    is what closes the last topic-separation gap to the sequential C++
    baseline (0.79 -> 1.03 at equal epochs), so the bench uses it for
    the time-to-quality record and the banded step for raw words/s."""
    k_shrink, k_idx, k_keep = jax.random.split(key, 3)
    centers, band, pmask = _band_former(C, W, n_kept, kept_pad,
                                        ksent_pad, k_shrink, base)
    draw = jax.random.randint(k_idx, (2 * W, C, K), 0,
                              neg_prob.shape[0])
    keep_draw = jax.random.uniform(k_keep, (2 * W, C, K)) \
        < neg_prob[draw]
    negs_all = jnp.where(keep_draw, draw, neg_alias[draw])
    offs = [o for o in range(-W, W + 1) if o != 0]
    loss_acc = 0.0
    for w, off in enumerate(offs):
        ctx = jax.lax.dynamic_slice_in_dim(band, W + off, C)
        negs = negs_all[w]                       # [C, K]
        loss, g_v, g_pos, g_neg = _pair_offset_loss_and_grads(
            emb_in[centers], emb_out[ctx], emb_out[negs], pmask[:, w])
        emb_in = emb_in.at[centers].add(-lr * g_v)
        emb_out = emb_out.at[ctx].add(-lr * g_pos)
        emb_out = emb_out.at[negs].add(-lr * g_neg)
        loss_acc = loss_acc + loss
    return emb_in, emb_out, loss_acc, pmask.sum()


def _make_group(step, pad):
    """The scan driver shared by every device group program: carry the
    tables + PRNG key through G steps, sum losses/examples, return the
    advanced key, donate the table buffers. ``pad=(C, W)`` pads the
    kept stream for the banded steps at group entry (one ~24 MB fused
    copy per dispatch — the per-step slices then never clamp); every
    step formulation is banded now, so padding is unconditional."""

    def group(emb_in, emb_out, kept, ksent, aux1, aux2,
              key, bases, lrs, n_kept):
        kept, ksent = _pad_stream(pad[0], pad[1], kept, ksent)

        def body(carry, xs):
            emb_in, emb_out, key = carry
            base, lr = xs
            key, sub = jax.random.split(key)
            emb_in, emb_out, loss, pairs = step(
                emb_in, emb_out, kept, ksent, aux1, aux2, sub, base,
                lr, n_kept)
            return (emb_in, emb_out, key), (loss, pairs)

        (emb_in, emb_out, key), (losses, pairs) = jax.lax.scan(
            body, (emb_in, emb_out, key), (bases, lrs))
        return emb_in, emb_out, losses.sum(), pairs.sum(), key

    return jax.jit(group, donate_argnums=(0, 1))


def _hs_sg_loss_and_grads(v, u_band_path, path_band, code_band, pmask):
    """Banded skip-gram HS objective: the center row against the
    Huffman-path rows of each context word, labels ``1 - code`` (code 0
    = positive, the word2vec convention; ref: wordembedding.cpp HS
    branch). Path rows are gathered ONCE per band position
    (``u_band_path`` [C+2W, L, D]) and the 2W context logits come from
    shifted slices — the same overlap trick as the SGNS band, 2W-fold
    less gather/scatter than the [C, 2W, L, D] row-matrix form.
    Returns (loss, g_v, g_band_path)."""
    C, W = pmask.shape[0], pmask.shape[1] // 2
    offs = [o for o in range(-W, W + 1) if o != 0]
    node_ok = ((path_band >= 0) & (code_band >= 0)).astype(jnp.float32)
    labels_band = (1.0 - code_band.astype(jnp.float32))

    def loss_fn(v, u_band_path):
        total = 0.0
        for w, off in enumerate(offs):
            u_off = jax.lax.dynamic_slice_in_dim(
                u_band_path, W + off, C)                  # [C, L, D]
            mask = jax.lax.dynamic_slice_in_dim(
                node_ok, W + off, C) * pmask[:, w:w + 1]
            labels = jax.lax.dynamic_slice_in_dim(
                labels_band, W + off, C) * mask
            logits = jnp.clip(jnp.einsum("cd,cld->cl", v, u_off),
                              -_MAX_EXP, _MAX_EXP)
            total = total + jnp.sum(_sigmoid_xent(logits, labels)
                                    * mask)
        return total

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
        v, u_band_path)
    return (loss,) + grads


def _hs_cbow_loss_and_grads(u_band_in, u_path, path, code, pmask):
    """CBOW + HS objective: the masked mean of the window's INPUT rows
    (shifted band slices) against the CENTER's Huffman path — one
    example per center (ref: wordembedding.cpp CBOW+HS combination).
    ``u_band_in`` [C+2W, D] INPUT rows, ``u_path`` [C, L, D] the
    center-path OUTPUT rows. Returns (loss, g_band, g_path, examples)."""
    C, W = pmask.shape[0], pmask.shape[1] // 2
    offs = [o for o in range(-W, W + 1) if o != 0]
    nvalid = pmask.sum(axis=1)
    has_ctx = (nvalid > 0).astype(jnp.float32)
    mask = ((path >= 0) & (code >= 0)).astype(jnp.float32) \
        * has_ctx[:, None]
    labels = (1.0 - code.astype(jnp.float32)) * mask

    def loss_fn(u_band_in, u_path):
        denom = jnp.maximum(nvalid, 1.0)
        acc = 0.0
        for w, off in enumerate(offs):
            acc = acc + pmask[:, w:w + 1] * \
                jax.lax.dynamic_slice_in_dim(u_band_in, W + off, C)
        vmean = acc / denom[:, None]
        logits = jnp.clip(jnp.einsum("cd,cld->cl", vmean, u_path),
                          -_MAX_EXP, _MAX_EXP)
        return jnp.sum(_sigmoid_xent(logits, labels) * mask)

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
        u_band_in, u_path)
    return (loss,) + grads + (has_ctx.sum(),)


@functools.lru_cache(maxsize=None)
def _group_fn_hs(C: int, W: int, cbow: bool = False):
    """Hierarchical-softmax group in banded form, covering skip-gram
    (center row vs the context words' Huffman paths) and CBOW (window
    mean vs the center's path). The aux argument slots carry
    (points, codes) [V, L] (-1 padded) instead of the SGNS alias
    tables — same arity as ``_group_fn``, so the trainer drives either
    interchangeably."""

    def step(emb_in, emb_out, kept_pad, ksent_pad, points, codes,
             key, base, lr, n_kept):
        k_shrink, _ = jax.random.split(key)
        centers, band, pmask = _band_former(C, W, n_kept, kept_pad,
                                            ksent_pad, k_shrink, base)
        if cbow:
            path = points[centers]                # [C, L]
            code = codes[centers]
            out_ids = jnp.maximum(path, 0)
            u_band = emb_in[band]
            u_path = emb_out[out_ids]             # [C, L, D]
            loss, g_band, g_path, examples = _hs_cbow_loss_and_grads(
                u_band, u_path, path, code, pmask)
            emb_in = emb_in.at[band].add(-lr * g_band)
            emb_out = emb_out.at[out_ids].add(-lr * g_path)
            return emb_in, emb_out, loss, examples
        path_band = points[band]                  # [C+2W, L]
        code_band = codes[band]
        out_ids = jnp.maximum(path_band, 0)
        v = emb_in[centers]
        u_band_path = emb_out[out_ids]            # [C+2W, L, D]
        loss, g_v, g_band_path = _hs_sg_loss_and_grads(
            v, u_band_path, path_band, code_band, pmask)
        emb_in = emb_in.at[centers].add(-lr * g_v)
        emb_out = emb_out.at[out_ids].add(-lr * g_band_path)
        return emb_in, emb_out, loss, pmask.sum()

    return _make_group(step, pad=(C, W))


# Module-level cache so every trainer instance with the same static
# shape (C, window, negative, corpus length, mode) shares one compiled
# group program — a warmup trainer's compile pays for the timed one.
@functools.lru_cache(maxsize=None)
def _group_fn(C: int, W: int, K: int, cbow: bool = False,
              neg_block: int = 1, per_pair: bool = False):
    def step(emb_in, emb_out, kept_pad, ksent_pad, neg_prob, neg_alias,
             key, base, lr, n_kept):
        if per_pair:
            return _seq_pair_step(C, W, K, emb_in, emb_out, kept_pad,
                                  ksent_pad, neg_prob, neg_alias, key,
                                  base, lr, n_kept)
        return _apply_step(C, W, K, cbow, emb_in, emb_out, kept_pad,
                           ksent_pad, neg_prob, neg_alias, key, base,
                           lr, n_kept, neg_block=neg_block)

    return _make_group(step, pad=(C, W))


@functools.lru_cache(maxsize=None)
def _ma_group_fn(mesh, C: int, W: int, K: int, neg_block: int = 1):
    """Model-average (``-ma``) word2vec over a device mesh: each device
    scans G local SGNS steps against its own REPLICA of the embedding
    tables on its own CORPUS SHARD, then the replicas average with
    ``lax.pmean`` over ICI — the reference's MA mode (train locally,
    MV_Aggregate; ref: src/zoo.cpp:24,49, src/multiverso.cpp:53-56)
    with the aggregate riding XLA collectives inside one jitted step.

    Arguments of the returned jit (all as ONE global call):
    ``emb_in/emb_out`` replicated [V, D]; ``kept/ksent`` sharded
    [n_devices * n_local]; ``keys`` one PRNG key per device
    [n_devices, 2]; ``bases/lrs`` [G]; ``n_kept_local`` per-device kept
    counts [n_devices]. Returns (averaged tables, summed loss, summed
    pairs, advanced per-device keys) — feed the keys back when chaining
    dispatches or every group replays the same draws."""
    try:  # jax >= 0.4.31 top-level export; older: experimental
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]

    def device_group(emb_in, emb_out, kept, ksent, neg_prob, neg_alias,
                     keys, bases, lrs, n_kept_local):
        key = keys[0]
        n_kept = n_kept_local[0]
        # The replicated tables DIVERGE per device once local training
        # starts — annotate them device-varying so the scan carry types
        # line up (pmean at the end collapses them back).
        try:
            pcast = functools.partial(jax.lax.pcast, to="varying")
        except AttributeError:  # older jax spells it pvary
            pcast = getattr(jax.lax, "pvary", None)
        if pcast is None:  # pre-0.5 jax: no varying-type system in
            # shard_map, so the annotation is correctly a no-op
            def pcast(x, _axis):
                return x
        emb_in = pcast(emb_in, axis)
        emb_out = pcast(emb_out, axis)
        # Pad each device's LOCAL stream for the banded slices (inside
        # shard_map, so this is a per-shard local op).
        kept_pad, ksent_pad = _pad_stream(C, W, kept, ksent)

        def body(carry, xs):
            emb_in, emb_out, key = carry
            base, lr = xs
            key, sub = jax.random.split(key)
            emb_in, emb_out, loss, pairs = _apply_step(
                C, W, K, False, emb_in, emb_out, kept_pad,
                ksent_pad, neg_prob, neg_alias, sub, base, lr, n_kept,
                neg_block=neg_block)
            return (emb_in, emb_out, key), (loss, pairs)

        (emb_in, emb_out, key), (losses, pairs) = jax.lax.scan(
            body, (emb_in, emb_out, key), (bases, lrs))
        # MV_Aggregate: average the trained replicas over the mesh.
        emb_in = jax.lax.pmean(emb_in, axis)
        emb_out = jax.lax.pmean(emb_out, axis)
        return (emb_in, emb_out, jax.lax.psum(losses.sum(), axis),
                jax.lax.psum(pairs.sum(), axis), key[None])

    mapped = shard_map(
        device_group, mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis), P(), P(),
                  P(axis), P(), P(), P(axis)),
        out_specs=(P(), P(), P(), P(), P(axis)))
    return jax.jit(mapped, donate_argnums=(0, 1))


class _CorpusOnDevice:
    """Shared upload of a ``TokenizedCorpus``: the flat id stream, its
    per-token sentence ids, and the subsample keep probabilities — one
    transfer, reused every epoch by both the local and the PS device
    trainers."""

    def __init__(self, model, tokenized: TokenizedCorpus):
        config = model.config
        flat = np.asarray(tokenized.flat, np.int32)
        lengths = np.diff(tokenized.offsets).astype(np.int64)
        sent = np.repeat(np.arange(lengths.size, dtype=np.int32), lengths)
        self.n_tokens = int(flat.size)
        # One-time host->device uploads; construction can overlap a
        # sibling rank's step in multi-zoo mode, so guard like any
        # dispatch (no-op in the one-zoo deployment).
        with device_lock.guard():
            self.flat = device_lock.settle(jnp.asarray(flat))
            self.sent = device_lock.settle(jnp.asarray(sent))
            self.keep = device_lock.settle(jnp.asarray(
                model.dictionary.subsample_keep_prob(config.sample)))

    def prep_epoch(self, key):
        # Multi-zoo mode (device_lock.py): the prep program is a
        # multi-device dispatch like any step — serialize and settle.
        with device_lock.guard():
            return device_lock.settle(
                _prep(self.flat, self.sent, self.keep, key))


class DeviceCorpusTrainer:
    """Drives a ``Word2Vec`` model's embeddings straight from a
    device-resident ``TokenizedCorpus``. Covers the FULL mode matrix:
    {skip-gram, CBOW} x {negative sampling, hierarchical softmax}
    (ref: wordembedding.h:95-125 trains every combination through its
    one hot loop), plus the -per_pair skip-gram quality mode."""

    def __init__(self, model, tokenized: TokenizedCorpus,
                 centers_per_step: int = 32768,
                 steps_per_dispatch: int = 8):
        config = model.config
        self.model = model
        self.config = config
        self._C = int(centers_per_step)
        self._G = int(steps_per_dispatch)
        self._corpus = _CorpusOnDevice(model, tokenized)
        self._n_tokens = self._corpus.n_tokens
        if config.hs:
            # Banded HS activations are [C+2W, L, D] (L = max Huffman
            # path, ~log2 vocab; the round-3 row-matrix form was
            # [C, 2W, L, D] — 2W-fold bigger). Cap C so the gathered
            # path rows + their grad stay within ~1.5 GB; callers can
            # pass a smaller centers_per_step, larger is refused by the
            # cap rather than by an HBM OOM mid-epoch.
            path_len = max(int(model._points_host.shape[1]), 1)
            self._C = min(self._C, _hs_center_cap(
                path_len, int(config.embedding_size)))
            self._group = _group_fn_hs(self._C, config.window,
                                       bool(config.cbow))
            # aux slots: the Huffman path/code tables.
            self._aux = (model._points_dev, model._codes_dev)
        else:
            B = max(int(getattr(config, "neg_block", 1)), 1)
            if self._C % B:
                raise ValueError("neg_block must divide centers_per_step")
            per_pair = bool(getattr(config, "per_pair", False))
            if per_pair and config.cbow:
                raise ValueError("per_pair is a skip-gram quality mode")
            self._group = _group_fn(self._C, config.window,
                                    config.negative, bool(config.cbow),
                                    B, per_pair)
            self._aux = (model._neg_prob_dev, model._neg_alias_dev)
        # Post-subsampling tokens actually trained (centers), across
        # epochs — the exact basis for utilization accounting.
        self.kept_words_trained = 0

    def train_epoch(self, seed: int, group_hook=None,
                    max_steps: int = 0) -> Tuple[float, float]:
        """One full epoch on device. ``group_hook(words)`` is called
        after each dispatched group with the raw-word count it covered
        (bench timing); ``max_steps`` truncates the epoch (warmup).
        Returns (loss_sum, examples) as floats — fetched ONCE at epoch
        end. ``examples`` counts (center, context) pairs in skip-gram
        mode and trained centers in CBOW mode (one prediction per
        center)."""
        model, C, G = self.model, self._C, self._G
        key = jax.random.PRNGKey(seed)
        key, prep_key = jax.random.split(key)
        kept, ksent, n_kept_dev = self._corpus.prep_epoch(prep_key)
        n_kept = int(n_kept_dev)  # the one host fetch per epoch
        steps = max(math.ceil(n_kept / C), 1)
        if max_steps:
            steps = min(steps, max_steps)
        self.kept_words_trained += min(steps * C, n_kept)
        # lr schedule decays in RAW corpus words (subsample-dropped words
        # count, ref: distributed_wordembedding.cpp:92-134): spread the
        # epoch's raw words uniformly over its steps.
        raw_per_step = self._n_tokens / max(math.ceil(n_kept / C), 1)
        loss_acc = None
        pair_acc = None
        for g0 in range(0, steps, G):
            bases = np.full(G, n_kept, np.int32)  # padded steps: no-ops
            real = min(G, steps - g0)
            bases[:real] = (np.arange(g0, g0 + real) * C).astype(np.int32)
            lrs = np.zeros(G, np.float32)
            for i in range(real):
                lrs[i] = model.learning_rate()
                model.trained_words += raw_per_step
            with device_lock.guard():
                (model._emb_in, model._emb_out, loss, pairs,
                 key) = device_lock.settle(self._group(
                    model._emb_in, model._emb_out, kept, ksent,
                    self._aux[0], self._aux[1], key,
                    jnp.asarray(bases), jnp.asarray(lrs), n_kept_dev))
            loss_acc = loss if loss_acc is None else loss_acc + loss
            pair_acc = pairs if pair_acc is None else pair_acc + pairs
            if group_hook is not None:
                group_hook(raw_per_step * real)
        return (0.0 if loss_acc is None else float(loss_acc),
                0.0 if pair_acc is None else float(pair_acc))


def _sum_parts(x):
    """Sum a tuple of per-server reply shards (or pass a single array
    through) — used INSIDE the PS step jits."""
    if isinstance(x, (tuple, list)):
        return functools.reduce(jnp.add, x)
    return x


@functools.lru_cache(maxsize=None)
def _block_ids_fn_hs(C: int, W: int, cbow: bool = False):
    """HS block preparation for the PS pipeline: the OUTPUT ids are the
    Huffman-path inner-node rows (banded for skip-gram — one path per
    band position; the center's path for CBOW). The third slot carries
    (pmask, path, code) so the step can mask and label without
    re-deriving them."""

    def ids(kept_pad, ksent_pad, points, codes, key, base, n_kept):
        k_shrink, _ = jax.random.split(key)
        centers, band, pmask = _band_former(C, W, n_kept, kept_pad,
                                            ksent_pad, k_shrink, base)
        if cbow:
            path = points[centers]                 # [C, L]
            code = codes[centers]
            return band, jnp.maximum(path, 0).reshape(-1), \
                (pmask, path, code)
        path = points[band]                        # [C+2W, L]
        code = codes[band]
        return centers, jnp.maximum(path, 0).reshape(-1), \
            (pmask, path, code)

    return jax.jit(ids)


@functools.lru_cache(maxsize=None)
def _block_step_fn_hs(C: int, W: int, L: int, cbow: bool = False):
    """HS PS block step over PULLED rows: mirrors ``_block_step_fn``'s
    contract (aux = the (pmask, path, code) tuple from
    ``_block_ids_fn_hs``)."""

    def step(v, u, aux, lr, inv_workers):
        v = _sum_parts(v)
        u = _sum_parts(u)
        pmask, path, code = aux
        lr_scaled = lr * inv_workers
        if cbow:
            u_path = u.reshape(C, L, -1)
            loss, g_band, g_path, examples = _hs_cbow_loss_and_grads(
                v, u_path, path, code, pmask)
            return (-lr_scaled * g_band,
                    -lr_scaled * g_path.reshape(C * L, -1), loss,
                    examples)
        u_bp = u.reshape(C + 2 * W, L, -1)
        loss, g_v, g_bp = _hs_sg_loss_and_grads(v, u_bp, path, code,
                                                pmask)
        return (-lr_scaled * g_v,
                -lr_scaled * g_bp.reshape((C + 2 * W) * L, -1), loss,
                pmask.sum())

    return jax.jit(step)


@functools.lru_cache(maxsize=None)
def _block_ids_fn(C: int, W: int, K: int, cbow: bool = False,
                  neg_block: int = 1, per_pair: bool = False):
    """Jitted block preparation for the PS pipeline: the INPUT-table id
    block, the OUTPUT-table id block (flat), and the pair validity mask
    — all device-resident, ready to hand to the tables as DEVICE keys.
    Takes the PADDED stream (pad once per epoch, not per step).
    Banded form: skip-gram in=centers [C],
    out=[band (C+2W) | negs (C//B*K)]; CBOW in=band [C+2W],
    out=[centers (C) | negs (C//B*K)]. The band replaces the [C, 2W]
    context id matrix — 2W-fold fewer pulled/pushed rows."""

    def ids(kept_pad, ksent_pad, neg_prob, neg_alias, key, base,
            n_kept):
        k_shrink, k_idx, k_keep = jax.random.split(key, 3)
        centers, band, pmask = _band_former(C, W, n_kept, kept_pad,
                                            ksent_pad, k_shrink, base)
        if per_pair:
            # Quality mode: K negatives per (center, offset) pair, drawn
            # with the SAME key-split order as _seq_pair_step.
            draw = jax.random.randint(k_idx, (2 * W, C, K), 0,
                                      neg_prob.shape[0])
            keep_draw = jax.random.uniform(k_keep, (2 * W, C, K)) \
                < neg_prob[draw]
            negs = jnp.where(keep_draw, draw, neg_alias[draw])
            return centers, jnp.concatenate([band, negs.reshape(-1)]), \
                pmask
        negs = _draw_negs(C, K, neg_block, neg_prob, neg_alias,
                          k_idx, k_keep)
        if cbow:
            return band, jnp.concatenate([centers, negs.reshape(-1)]), \
                pmask
        return centers, jnp.concatenate([band, negs.reshape(-1)]), pmask

    return jax.jit(ids)


@functools.lru_cache(maxsize=None)
def _block_step_fn(C: int, W: int, K: int, cbow: bool = False,
                   neg_block: int = 1, per_pair: bool = False):
    """Jitted PS block step over PULLED rows (banded layout from
    ``_block_ids_fn``): returns the PUSH deltas
    ``-lr*grad/num_workers`` (the reference's (new-old)/num_workers with
    one local step, ref: communicator.cpp:157-249) plus loss/examples.
    ``per_pair``: the quality mode's 2W sequential sub-steps run against
    the PULLED copies (the reference's PS trainer also trains local row
    copies and pushes new-old, communicator.cpp:157-249); the pushed
    delta is the net local change over all sub-steps, / num_workers."""
    nb = C // neg_block

    def step(v, u, pmask, lr, inv_workers):
        # Multi-server pulls arrive as per-server shard tuples (foreign
        # rows zero-filled); summing them HERE folds the reassembly into
        # this program instead of a separate eager dispatch per pull.
        v = _sum_parts(v)
        u = _sum_parts(u)
        if per_pair:
            u_band0 = u[:C + 2 * W]
            u_negs0 = u[C + 2 * W:].reshape(2 * W, C, K, -1)
            offs = [o for o in range(-W, W + 1) if o != 0]
            v_cur, u_band, u_negs = v, u_band0, u_negs0
            loss_acc = 0.0
            for w, off in enumerate(offs):
                u_pos = jax.lax.dynamic_slice_in_dim(u_band, W + off, C)
                loss, g_v, g_pos, g_neg = _pair_offset_loss_and_grads(
                    v_cur, u_pos, u_negs[w], pmask[:, w])
                # Sub-steps apply the RAW lr to the local copies; the
                # pushed net delta carries the 1/num_workers scale.
                v_cur = v_cur - lr * g_v
                u_band = u_band.at[W + off:W + off + C].add(-lr * g_pos)
                u_negs = u_negs.at[w].add(-lr * g_neg)
                loss_acc = loss_acc + loss
            d_v = (v_cur - v) * inv_workers
            d_u = jnp.concatenate(
                [u_band - u_band0,
                 (u_negs - u_negs0).reshape(2 * W * C * K, -1)]) \
                * inv_workers
            return d_v, d_u, loss_acc, pmask.sum()
        lr_scaled = lr * inv_workers
        if cbow:
            # v = pulled INPUT band rows [C+2W, D]; u = pulled OUTPUT
            # [centers | negs] rows [C + nb*K, D].
            u_center = u[:C]
            u_neg = u[C:].reshape(nb, K, -1)
            loss, g_band, g_center, g_neg, examples = \
                _banded_cbow_loss_and_grads(v, u_center, u_neg, pmask)
            g_out = jnp.concatenate(
                [g_center, g_neg.reshape(nb * K, -1)])
            return -lr_scaled * g_band, -lr_scaled * g_out, loss, examples
        # v = pulled center rows [C, D]; u = [band | negs] rows.
        u_band = u[:C + 2 * W]
        u_neg = u[C + 2 * W:].reshape(nb, K, -1)
        loss, g_v, g_band, g_neg = _banded_sgns_loss_and_grads(
            v, u_band, u_neg, pmask)
        g_u = jnp.concatenate([g_band, g_neg.reshape(nb * K, -1)])
        return -lr_scaled * g_v, -lr_scaled * g_u, loss, pmask.sum()

    return jax.jit(step)


@functools.lru_cache(maxsize=None)
def _grouped_ids_fn(ids_fn, G: int):
    """vmap an ids program over G blocks: one program launch prepares
    G blocks' id sets (stacked on a leading axis) from one folded key
    and a [G] base vector."""
    mapped = jax.vmap(ids_fn, in_axes=(None, None, None, None, 0, 0,
                                       None))

    @jax.jit
    def ids(kept_pad, ksent_pad, aux1, aux2, key, bases, n_kept):
        keys = jax.random.split(key, G)
        return mapped(kept_pad, ksent_pad, aux1, aux2, keys, bases,
                      n_kept)

    return ids


@functools.lru_cache(maxsize=None)
def _grouped_step_fn(step_fn, G: int):
    """vmap a PS block step over G stacked blocks (per-block lr vector),
    summing losses/examples — one program launch trains G blocks
    against the group's shared pulled state."""
    mapped = jax.vmap(step_fn, in_axes=(0, 0, 0, 0, None))

    @jax.jit
    def step(v, u, pmask, lrs, inv_workers):
        d_v, d_u, loss, examples = mapped(v, u, pmask, lrs, inv_workers)
        return d_v, d_u, loss.sum(), examples.sum()

    return step


@functools.lru_cache(maxsize=None)
def _segmented_ids_fn(ids_fn, offsets: tuple, caps_in: tuple,
                      caps_out: tuple, oor: int):
    """Wrap a (possibly grouped) block-ids program with PER-SERVER
    SEGMENTATION: sort each id set, searchsorted the server row offsets
    for segment bounds, and emit one fixed-capacity dynamic slice per
    server — so each server receives (and gathers/scatters) only ~its
    share of the ids instead of the full broadcast set (ref per-server
    key bucketing: src/table/matrix_table.cpp:234-315). Capacities are
    static (calibrated by the trainer); an id set whose true segment
    exceeds its capacity raises the OVERFLOW flag, which the trainer
    accumulates on device and checks at epoch end — entries beyond a
    segment's capacity would silently miss their owner otherwise.

    Slice slack needs no masking: an entry past its server's bound
    belongs to the NEXT server, whose own slice also carries it — the
    owner applies it, everyone else range-masks it out."""
    offs = np.asarray(offsets[1:-1], np.int32)

    def prep(ids_nd, caps):
        flat = ids_nd.reshape(-1)
        n = flat.shape[0]
        order = jnp.argsort(flat)
        sorted_ids = flat[order]
        # Inverse permutation by scatter (one pass) — a second argsort
        # would pay a full sort for what is just order[j] -> j.
        inv = jnp.zeros_like(order).at[order].set(
            jnp.arange(n, dtype=order.dtype))
        bounds = jnp.concatenate([
            jnp.zeros(1, jnp.int32),
            jnp.searchsorted(sorted_ids, jnp.asarray(offs)).astype(
                jnp.int32),
            jnp.full(1, n, jnp.int32)])
        padded = jnp.concatenate(
            [sorted_ids, jnp.full((max(caps),), oor, jnp.int32)])
        segs = []
        overflow = jnp.int32(0)
        for s, cap in enumerate(caps):
            segs.append(jax.lax.dynamic_slice(padded, (bounds[s],),
                                              (cap,)))
            overflow = overflow | (
                bounds[s + 1] - bounds[s] > cap).astype(jnp.int32)
        return tuple(segs), (order, inv, bounds), overflow

    def ids(*args):
        in_ids, out_ids, aux = ids_fn(*args)
        segs_in, meta_in, ovf_i = prep(in_ids, caps_in)
        segs_out, meta_out, ovf_o = prep(out_ids, caps_out)
        return (segs_in, segs_out, aux, meta_in, meta_out,
                ovf_i | ovf_o)

    return jax.jit(ids)


@functools.lru_cache(maxsize=None)
def _segmented_step_fn(step_fn, caps_in: tuple, caps_out: tuple,
                       in_shape: tuple, out_shape: tuple):
    """Wrap a PS block step for segmented pulls/pushes: reassemble the
    per-server reply slices into sorted order (increasing-server
    dynamic_update_slice — for any row the LAST writer covering it is
    its owner, so slack rows never survive), un-permute back to the
    step's positional layout, run the step, then re-permute the deltas
    and slice per-server push segments — all in ONE program, so the
    reorder passes ride the step's launch."""
    n_in = int(np.prod(in_shape))
    n_out = int(np.prod(out_shape))

    def reassemble(parts, bounds, n, caps):
        buf = jnp.zeros((n + max(caps), parts[0].shape[-1]),
                        parts[0].dtype)
        for s, part in enumerate(parts):
            buf = jax.lax.dynamic_update_slice(buf, part,
                                               (bounds[s], 0))
        return buf[:n]

    def resort(delta, order, bounds, n, caps):
        d = delta.reshape(n, delta.shape[-1])[order]
        d = jnp.pad(d, ((0, max(caps)), (0, 0)))
        return tuple(
            jax.lax.dynamic_slice(d, (bounds[s], 0),
                                  (cap, d.shape[-1]))
            for s, cap in enumerate(caps))

    def step(parts_v, parts_u, meta_in, meta_out, aux, lr,
             inv_workers):
        order_i, inv_i, bounds_i = meta_in
        order_o, inv_o, bounds_o = meta_out
        dim = parts_v[0].shape[-1]
        v = reassemble(parts_v, bounds_i, n_in, caps_in)[inv_i] \
            .reshape(in_shape + (dim,))
        u = reassemble(parts_u, bounds_o, n_out, caps_out)[inv_o] \
            .reshape(out_shape + (dim,))
        d_v, d_u, loss, examples = step_fn(v, u, aux, lr, inv_workers)
        return (resort(d_v, order_i, bounds_i, n_in, caps_in),
                resort(d_u, order_o, bounds_o, n_out, caps_out),
                loss, examples)

    return jax.jit(step)


def _segment_caps(counts, total: int) -> tuple:
    """Static per-server segment capacities from one calibration
    sample: 2x slack + headroom, power-of-two bucketed, clamped to the
    full id count (a capacity beyond that cannot help)."""
    from ...updater.engine import bucket_size
    cap_total = bucket_size(total)
    return tuple(min(bucket_size(int(c) * 2 + 64), cap_total)
                 for c in counts)


class PSDeviceCorpusTrainer:
    """The PS twin of ``DeviceCorpusTrainer``: same HBM-resident corpus
    pipeline, but the embeddings live in PARAMETER-SERVER matrix tables
    — every block pulls its rows through the full worker/server actor
    stack (device-key Gets), trains, and pushes ``-lr*grad/num_workers``
    deltas back (device-key Adds). Nothing but learning-rate scalars
    crosses the host boundary, which is what lets the PS path approach
    local-mode throughput in-process (the reference's block protocol,
    ref: Applications/WordEmbedding/src/communicator.cpp:117-249, with
    the row list living in HBM).

    Requires the in-process device path. Multi-server tables work —
    device keys broadcast to every server, which masks foreign rows on
    device (ref partition contract: src/table/matrix_table.cpp:234-315)
    — at the cost of one extra [k, D] pass per additional server; the
    host-batch ``PSWord2Vec.train_batches`` remains the general path
    for cross-process runs."""

    def __init__(self, model, tokenized: TokenizedCorpus,
                 centers_per_step: int = 32768,
                 blocks_per_dispatch: int = 1,
                 segment_keys: bool = False):
        """``blocks_per_dispatch`` (G) batches G blocks' ids into ONE
        pull/step/push round trip — G-fold fewer program launches (the
        per-block cost that bounds the PS path on a tunneled chip), at
        the price of G blocks reading the same table state before their
        deltas land: the same bounded-staleness trade the reference
        makes with -is_pipeline prefetch and sync_frequency > 1
        (ref: distributed_wordembedding.cpp:203-224,
        LogisticRegression configure.h sync_frequency). G=1 keeps exact
        per-block semantics.

        ``segment_keys`` sends each server a calibrated-capacity SLICE
        of the sorted ids instead of broadcasting the full set —
        per-server gather/scatter work follows the segment size (ref
        per-server key bucketing: src/table/matrix_table.cpp:234-315).
        Default OFF: on one chip with Zipf-skewed ids the reorder
        passes (sort + two [k, D] permutes + reassembly) cost more
        than the per-server savings — measured 0.59x vs broadcast's
        0.83x same-window on the bench corpus (scratch/seg_ratio.py);
        it pays off when ids spread evenly across servers (balanced /
        hashed tables), so it stays available as an opt-in."""
        config = model.config
        if not getattr(model, "_device_path", False):
            raise ValueError("PS device pipeline needs in-process "
                             "servers (device path)")
        self.model = model
        self.config = config
        self._C = int(centers_per_step)
        self._G = max(int(blocks_per_dispatch), 1)
        self._corpus = _CorpusOnDevice(model, tokenized)
        self._n_tokens = self._corpus.n_tokens
        if config.hs:
            if not hasattr(model, "_points_dev"):
                # PSWord2Vec keeps the Huffman tables host-side (its
                # batch path preps row sets on the host); this pipeline
                # derives paths in-jit, so upload them once (guarded:
                # construction can overlap a sibling rank's step).
                with device_lock.guard():
                    model._points_dev = device_lock.settle(
                        jnp.asarray(model._points_host))
                    model._codes_dev = device_lock.settle(
                        jnp.asarray(model._codes_host))
            path_len = max(int(model._points_host.shape[1]), 1)
            self._C = min(self._C, _hs_center_cap(
                path_len, int(config.embedding_size)))
            self._ids = _block_ids_fn_hs(self._C, config.window,
                                         bool(config.cbow))
            self._step = _block_step_fn_hs(self._C, config.window,
                                           path_len, bool(config.cbow))
            self._aux_tables = (model._points_dev, model._codes_dev)
        else:
            if not hasattr(model, "_neg_prob_dev"):
                # PSWord2Vec keeps the alias tables host-side (its batch
                # path draws negatives on the host); this pipeline
                # samples in-jit, so upload them once (guarded:
                # construction can overlap a sibling rank's step).
                with device_lock.guard():
                    model._neg_prob_dev = device_lock.settle(
                        jnp.asarray(model._neg_prob_host))
                    model._neg_alias_dev = device_lock.settle(
                        jnp.asarray(model._neg_alias_host))
            B = max(int(getattr(config, "neg_block", 1)), 1)
            if self._C % B:
                raise ValueError("neg_block must divide centers_per_step")
            per_pair = bool(getattr(config, "per_pair", False))
            if per_pair and config.cbow:
                raise ValueError("per_pair is a skip-gram quality mode")
            self._ids = _block_ids_fn(self._C, config.window,
                                      config.negative,
                                      bool(config.cbow), B, per_pair)
            self._step = _block_step_fn(self._C, config.window,
                                        config.negative,
                                        bool(config.cbow), B, per_pair)
            self._aux_tables = (model._neg_prob_dev,
                                model._neg_alias_dev)
        self._pad = jax.jit(functools.partial(_pad_stream, self._C,
                                              config.window))
        if self._G > 1:
            self._ids = _grouped_ids_fn(self._ids, self._G)
            self._step = _grouped_step_fn(self._step, self._G)
        num_server = model._in_table._num_server
        self._segment_keys = bool(segment_keys) and num_server > 1
        self._seg_ids = None
        self._seg_step = None
        self._overflow = None
        self.kept_words_trained = 0

    def _build_segment_programs(self, kept_pad, ksent_pad, key,
                                n_kept_dev, n_kept: int) -> None:
        """One-time calibration for segment mode: run the raw ids
        program on a representative group, read the per-server id
        counts back ONCE (setup cost, ~a readback), and fix static
        per-server capacities with 2x slack. Shapes from the same
        sample parameterize the reassembling step wrapper."""
        in_table, out_table = self.model._in_table, self.model._out_table
        offsets = tuple(in_table._offsets)
        if tuple(out_table._offsets) != offsets \
                or in_table.num_row != out_table.num_row:
            raise ValueError("segment mode expects same-shape in/out "
                             "tables")
        base_host = np.minimum(np.arange(self._G) * self._C,
                               max(n_kept, 1)).astype(np.int32)
        with device_lock.guard():
            # The base-vector upload is a dispatch too — keep it inside
            # the same critical section as the ids program it feeds.
            base = np.int32(0) if self._G == 1 else \
                device_lock.settle(jnp.asarray(base_host))
            in_ids, out_ids, _aux = device_lock.settle(self._ids(
                kept_pad, ksent_pad, self._aux_tables[0],
                self._aux_tables[1], key, base, n_kept_dev))

        def caps(ids_nd):
            flat = np.sort(np.asarray(ids_nd).ravel())
            counts = np.diff(np.searchsorted(flat, np.asarray(offsets)))
            return _segment_caps(counts, flat.size)

        caps_in, caps_out = caps(in_ids), caps(out_ids)
        self._seg_ids = _segmented_ids_fn(
            self._ids, offsets, caps_in, caps_out, in_table.num_row)
        self._seg_step = _segmented_step_fn(
            self._step, caps_in, caps_out,
            tuple(in_ids.shape), tuple(out_ids.shape))

    def train_epoch(self, seed: int, block_hook=None,
                    max_steps: int = 0) -> Tuple[float, float]:
        """One epoch: per dispatch group (G blocks; G=1 default),
        compute ids on device -> device-key pulls -> jitted step ->
        device-key delta pushes, all dispatched asynchronously (losses
        accumulate as device scalars; pushes are fire-and-forget until
        the trailing drain)."""
        model, C, G = self.model, self._C, self._G
        in_table, out_table = model._in_table, model._out_table
        key = jax.random.PRNGKey(seed)
        key, prep_key = jax.random.split(key)
        kept, ksent, n_kept_dev = self._corpus.prep_epoch(prep_key)
        # Pad ONCE per epoch; the per-step ids program then slices the
        # padded stream directly (padding per step would re-copy the
        # whole ~24 MB stream every block).
        with device_lock.guard():
            kept_pad, ksent_pad = device_lock.settle(
                self._pad(kept, ksent))
        n_kept = int(n_kept_dev)
        steps = max(math.ceil(n_kept / C), 1)
        if max_steps:
            steps = min(steps, max_steps)
        self.kept_words_trained += min(steps * C, n_kept)
        raw_per_step = self._n_tokens / max(math.ceil(n_kept / C), 1)
        loss_acc = None
        pair_acc = None
        for g0 in range(0, steps, G):
            real = min(G, steps - g0)
            step_key = jax.random.fold_in(key, g0)
            if G == 1:
                base = np.int32(g0 * C)
                lr_host = np.float32(model.learning_rate())
                model._account_words(raw_per_step)
            else:
                # Padded tail blocks get base = n_kept (fully masked)
                # and lr 0 — exact no-ops, so the program set stays one
                # fixed shape.
                bases = np.full(G, n_kept, np.int32)
                bases[:real] = (np.arange(g0, g0 + real)
                                * C).astype(np.int32)
                lr_host = np.zeros(G, np.float32)
                for i in range(real):
                    lr_host[i] = model.learning_rate()
                    model._account_words(raw_per_step)
            with device_lock.guard():
                # The per-group scalar/vector uploads are dispatches
                # too — one guarded region keeps them from interleaving
                # a sibling rank's program in multi-zoo mode.
                if G != 1:
                    base = device_lock.settle(jnp.asarray(bases))
                lr = device_lock.settle(jnp.asarray(lr_host))
                inv_w = device_lock.settle(
                    jnp.float32(1.0 / model._num_workers))
            if self._segment_keys:
                if self._seg_ids is None:
                    self._build_segment_programs(kept_pad, ksent_pad,
                                                 step_key, n_kept_dev,
                                                 n_kept)
                # Segmented form: each server pulls/pushes only its
                # calibrated slice of the sorted ids; the step wrapper
                # reassembles replies and re-slices the push deltas in
                # the same program.
                with device_lock.guard():
                    segs_i, segs_o, pmask, meta_i, meta_o, ovf = \
                        device_lock.settle(self._seg_ids(
                            kept_pad, ksent_pad,
                            self._aux_tables[0],
                            self._aux_tables[1], step_key, base,
                            n_kept_dev))
                mid_in = in_table.get_rows_device_segments_async(segs_i)
                mid_out = out_table.get_rows_device_segments_async(
                    segs_o)
                in_table.wait(mid_in)
                out_table.wait(mid_out)
                v = tuple(in_table.take_device_row_parts())
                u = tuple(out_table.take_device_row_parts())
                with device_lock.guard():
                    d_v_segs, d_u_segs, loss, pairs = device_lock.settle(
                        self._seg_step(
                            v, u, meta_i, meta_o, pmask, lr, inv_w))
                model._pending_pushes.append(
                    (in_table, in_table.add_rows_device_segments_async(
                        segs_i, d_v_segs)))
                model._pending_pushes.append(
                    (out_table,
                     out_table.add_rows_device_segments_async(
                         segs_o, d_u_segs)))
                self._overflow = ovf if self._overflow is None \
                    else self._overflow + ovf
            else:
                # in_ids: centers (skip-gram) or the band (CBOW);
                # out_ids: [band | negs] / [centers | negs] / Huffman
                # path rows — see _block_ids_fn / _block_ids_fn_hs;
                # leading G axis when grouped.
                with device_lock.guard():
                    in_ids, out_ids, pmask = device_lock.settle(self._ids(
                        kept_pad, ksent_pad, self._aux_tables[0],
                        self._aux_tables[1], step_key, base, n_kept_dev))
                # Device-key pulls ride the worker->server actor round
                # trip; the replies are lazy device arrays (no host
                # sync).
                mid_in = in_table.get_rows_device_async(in_ids)
                mid_out = out_table.get_rows_device_async(out_ids)
                in_table.wait(mid_in)
                out_table.wait(mid_out)
                # Per-server shard tuples; the step jit sums them
                # (fused — no separate reassembly dispatch on
                # multi-server tables).
                v = tuple(in_table.take_device_row_parts())
                u = tuple(out_table.take_device_row_parts())
                with device_lock.guard():
                    d_v, d_u, loss, pairs = device_lock.settle(
                        self._step(v, u, pmask, lr, inv_w))
                # Fire-and-forget pushes: waiters self-reap on ack; the
                # trailing drain below bounds the epoch.
                model._pending_pushes.append(
                    (in_table, in_table.add_rows_async(in_ids, d_v)))
                model._pending_pushes.append(
                    (out_table, out_table.add_rows_async(out_ids, d_u)))
            loss_acc = loss if loss_acc is None else loss_acc + loss
            pair_acc = pairs if pair_acc is None else pair_acc + pairs
            self.last_loss = loss  # device scalar; bench sync point
            if block_hook is not None:
                block_hook(raw_per_step * real)
        model._drain_pushes()
        model._flush_word_count()
        if self._overflow is not None:
            # One readback per epoch (the drain already synced): a
            # segment that outgrew its calibrated capacity means some
            # ids never reached their owner — fail loud, never train
            # silently wrong.
            if int(self._overflow):
                raise RuntimeError(
                    "segmented device keys overflowed a calibrated "
                    "per-server capacity (id distribution shifted "
                    ">2x from the calibration sample). Overflowed "
                    "blocks pulled zero rows and pushed corrupted "
                    "deltas THIS epoch — the tables are polluted: "
                    "restore from a checkpoint (or reinit), then "
                    "rebuild the trainer to recalibrate or pass "
                    "segment_keys=False")
            self._overflow = None
        model._in_table.zoo.barrier()
        return (0.0 if loss_acc is None else float(loss_acc),
                0.0 if pair_acc is None else float(pair_acc))
