"""Device-resident corpus training: the word2vec data pipeline in HBM.

The round-2 hot loop shipped every batch's (center, context) ids from the
host; on a tunneled device that transfer (plus one dispatch per batch)
bounds words/sec long before the chip works. This module is the
TPU-native fix: the TOKENIZED CORPUS is uploaded once (~4 bytes/token)
and everything the reference's reader/trainer pipeline does per pass —
subsampling, sentence-bounded dynamic windows, negative sampling, the
SGNS update — happens inside jitted device programs
(ref: Applications/WordEmbedding/src/reader.cpp — subsample-as-you-read;
wordembedding.cpp — per-center shrunk window + SGNS FeedForward/
BPOutputLayer). The host's only per-epoch work is the learning-rate
schedule (a handful of scalars per dispatch group) and one scalar fetch
of the post-subsampling length.

Per epoch, one jitted ``_prep`` pass draws the subsample mask and
stably compacts kept tokens to the front (word2vec subsamples BEFORE
windowing, so windows must span the kept sequence); training then scans
``steps_per_dispatch`` windowed steps per dispatch.

The SGNS/CBOW steps use a BANDED formulation that exploits window
overlap: the contexts of C consecutive centers all lie in the
contiguous band ``kept[base-W : base+C+W]``, so the step gathers those
C+2W rows ONCE and forms the 2W context logits as shifted slices of the
band — 2W-fold less gather AND scatter row traffic than materializing
the [C, 2W] context row matrix, which round-3 profiling showed was the
step's dominant cost (scatter of C*(2W+K) ≈ 0.5M random rows per step).
The per-center shrunk window and sentence bounds survive as masks on
the shifted slices; the update math is bit-identical to the row-matrix
form (duplicates in the band sum, exactly as duplicate scatter ids
did). Negatives come from the unigram^0.75 alias tables, drawn per
center by default; ``neg_block`` > 1 shares one draw of K negatives
across each block of that many consecutive centers (expected gradient
unchanged — every center still sees K ^0.75-unigram negatives — but the
random-row traffic for negatives drops by the block factor; measured
~1.8x words/s at block 32 on v5e).

Measured v5e cost model (see PROGRESS notes, round 4): full-table
sweeps run near peak (~680 GB/s), row gathers ~50-100 GB/s, random-row
scatter-adds are the slowest path (~13 GB/s at 32K rows) — so the
design minimizes SCATTERED ROWS first, gathered rows second, and
never sweeps.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .data import TokenizedCorpus
from .model import _MAX_EXP, _sigmoid_xent


# -- per-epoch subsample + stable compaction (shape-polymorphic jit) --
@jax.jit
def _prep(flat, sent, keep, key):
    mask = jax.random.uniform(key, flat.shape) < keep[flat]
    # Stable: kept tokens keep corpus order, so positional distance in
    # the compacted array IS the word2vec window distance over the
    # subsampled sentence.
    order = jnp.argsort(jnp.where(mask, 0, 1).astype(jnp.int8),
                        stable=True)
    kept = flat[order]
    # Dropped tail gets sentence -1: it can never match a real sentence
    # id, so windows cannot cross into it.
    ksent = jnp.where(mask[order], sent[order], -1)
    return kept, ksent, mask.sum(dtype=jnp.int32)


def _window(C, W, n, kept, ksent, k_shrink, base, n_kept):
    """The in-jit window former shared by every device pipeline:
    C consecutive kept positions as centers, the per-center shrunk
    window masked against sentence bounds (the word2vec trick,
    ref: wordembedding.cpp Train window sampling). Returns
    (centers[C], ctx[C,2W], pmask[C,2W])."""
    offs = np.concatenate([np.arange(-W, 0),
                           np.arange(1, W + 1)]).astype(np.int32)
    offs_dev = jnp.asarray(offs)
    abs_offs = jnp.asarray(np.abs(offs))
    idx = base + jnp.arange(C, dtype=jnp.int32)
    safe = jnp.minimum(idx, n - 1)
    centers = kept[safe]
    csent = ksent[safe]
    center_ok = (idx < n_kept) & (csent >= 0)
    shrink = jax.random.randint(k_shrink, (C,), 1, W + 1)
    cpos = idx[:, None] + offs_dev[None, :]  # [C, 2W]
    inb = (cpos >= 0) & (cpos < n_kept)
    cposc = jnp.clip(cpos, 0, n - 1)
    ctx = kept[cposc]
    valid = (inb & (ksent[cposc] == csent[:, None])
             & (abs_offs[None, :] <= shrink[:, None])
             & center_ok[:, None])
    return centers, ctx, valid.astype(jnp.float32)


def _pad_stream(C, W, kept, ksent):
    """Pad the compacted stream so banded slices never clamp: W on the
    left, C+W on the right (a clamped ``dynamic_slice`` would shift the
    whole band and misalign valid centers on the epoch's tail step).
    Padding carries sentence -2, which never matches a real sentence,
    so every padded position is masked out."""
    return (jnp.pad(kept, (W, C + W)),
            jnp.pad(ksent, (W, C + W), constant_values=-2))


def _band_former(C, W, n_kept, kept_pad, ksent_pad, k_shrink, base):
    """The banded window former: C consecutive kept positions as
    centers; their contexts all lie in the contiguous band
    ``kept[base-W : base+C+W]`` (C+2W tokens), and the per-(center,
    offset) validity — in-stream, same sentence, within the per-center
    shrunk window (the word2vec trick, ref: wordembedding.cpp Train
    window sampling) — is a mask over shifted slices of the band.
    Returns (centers[C], band[C+2W], pmask[C,2W])."""
    offs = [o for o in range(-W, W + 1) if o != 0]
    idx = base + jnp.arange(C, dtype=jnp.int32)
    centers = jax.lax.dynamic_slice_in_dim(kept_pad, base + W, C)
    csent = jax.lax.dynamic_slice_in_dim(ksent_pad, base + W, C)
    center_ok = (idx < n_kept) & (csent >= 0)
    shrink = jax.random.randint(k_shrink, (C,), 1, W + 1)
    band = jax.lax.dynamic_slice_in_dim(kept_pad, base, C + 2 * W)
    band_sent = jax.lax.dynamic_slice_in_dim(ksent_pad, base, C + 2 * W)
    masks = []
    for off in offs:
        p = idx + off
        inb = (p >= 0) & (p < n_kept)
        s = jax.lax.dynamic_slice_in_dim(band_sent, W + off, C)
        masks.append(inb & (s == csent) & (abs(off) <= shrink)
                     & center_ok)
    pmask = jnp.stack(masks, axis=1).astype(jnp.float32)
    return centers, band, pmask


def _draw_negs(C, K, B, neg_prob, neg_alias, k_idx, k_keep):
    """K negatives per block of B consecutive centers via the alias
    tables — B=1 is the per-center draw (and reproduces the round-3
    draws bit-exactly). Returns negs[C//B, K]."""
    nb = C // B
    draw = jax.random.randint(k_idx, (nb, K), 0, neg_prob.shape[0])
    keep_draw = jax.random.uniform(k_keep, (nb, K)) < neg_prob[draw]
    return jnp.where(keep_draw, draw, neg_alias[draw])


def _banded_sgns_loss_and_grads(v, u_band, u_neg, pmask):
    """SGNS objective in banded form: context logits are dot products
    of each center row against 2W shifted slices of the band's OUTPUT
    rows; sigmoid xent at label 1 (masked) plus label 0 for the
    block-shared negatives (weighted by the center's valid-pair count).
    Returns (loss, g_v, g_band, g_neg)."""
    C, W = pmask.shape[0], pmask.shape[1] // 2
    nb, B = u_neg.shape[0], C // u_neg.shape[0]
    offs = [o for o in range(-W, W + 1) if o != 0]
    nvalid = pmask.sum(axis=1)

    def loss_fn(v, u_band, u_neg):
        pos = jnp.stack(
            [jnp.sum(v * jax.lax.dynamic_slice_in_dim(u_band, W + off, C),
                     axis=-1) for off in offs], axis=1)
        pos = jnp.clip(pos, -_MAX_EXP, _MAX_EXP)
        vb = v.reshape(nb, B, v.shape[-1])
        neg = jnp.clip(jnp.einsum("nbd,nkd->nbk", vb, u_neg),
                       -_MAX_EXP, _MAX_EXP)
        xp = _sigmoid_xent(pos, 1.0) * pmask
        xn = _sigmoid_xent(neg, 0.0) * nvalid.reshape(nb, B)[:, :, None]
        return xp.sum() + xn.sum()

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
        v, u_band, u_neg)
    return (loss,) + grads


def _banded_cbow_loss_and_grads(u_band, u_center, u_neg, pmask):
    """CBOW objective in banded form: the masked mean of the window's
    INPUT rows (shifted band slices) predicts the center and the
    block-shared negatives from the OUTPUT table — one example per
    center (ref: wordembedding.cpp CBOW branch; gradient through the
    mean is the 1/|window| form, as on the host-batch path).
    ``u_band`` [C+2W, D] INPUT rows, ``u_center`` [C, D] and ``u_neg``
    [C//B, K, D] OUTPUT rows. Returns
    (loss, g_band, g_center, g_neg, examples)."""
    C, W = pmask.shape[0], pmask.shape[1] // 2
    nb, B = u_neg.shape[0], C // u_neg.shape[0]
    offs = [o for o in range(-W, W + 1) if o != 0]
    nvalid = pmask.sum(axis=1)
    has_ctx = (nvalid > 0).astype(jnp.float32)

    def loss_fn(u_band, u_center, u_neg):
        denom = jnp.maximum(nvalid, 1.0)
        acc = 0.0
        for w, off in enumerate(offs):
            acc = acc + pmask[:, w:w + 1] * \
                jax.lax.dynamic_slice_in_dim(u_band, W + off, C)
        vmean = acc / denom[:, None]
        pos = jnp.clip(jnp.sum(vmean * u_center, axis=-1),
                       -_MAX_EXP, _MAX_EXP)
        vb = vmean.reshape(nb, B, vmean.shape[-1])
        neg = jnp.clip(jnp.einsum("nbd,nkd->nbk", vb, u_neg),
                       -_MAX_EXP, _MAX_EXP)
        xp = _sigmoid_xent(pos, 1.0) * has_ctx
        xn = _sigmoid_xent(neg, 0.0) \
            * has_ctx.reshape(nb, B)[:, :, None]
        return xp.sum() + xn.sum()

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
        u_band, u_center, u_neg)
    return (loss,) + grads + (has_ctx.sum(),)


def _apply_step(C, W, K, cbow, emb_in, emb_out, kept_pad, ksent_pad,
                neg_prob, neg_alias, key, base, lr, n_kept,
                neg_block: int = 1):
    """One full in-jit banded training step against local table arrays
    — band former + objective + scatter-add updates of C+2W band rows,
    C center rows and C//B negative rows (vs the C*(2W+K) scattered
    rows of the row-matrix form). Shared by the single-device group
    scan and the MA mesh path so the update math cannot diverge.
    ``kept_pad``/``ksent_pad`` must come from ``_pad_stream``. Returns
    (emb_in, emb_out, loss, examples)."""
    k_shrink, k_idx, k_keep = jax.random.split(key, 3)
    centers, band, pmask = _band_former(C, W, n_kept, kept_pad,
                                        ksent_pad, k_shrink, base)
    negs = _draw_negs(C, K, neg_block, neg_prob, neg_alias,
                      k_idx, k_keep)
    if cbow:
        # window (input table) -> [center | negs] (output table)
        u_band = emb_in[band]                 # [C+2W, D]
        u_center = emb_out[centers]           # [C, D]
        u_neg = emb_out[negs]                 # [C//B, K, D]
        loss, g_band, g_center, g_neg, examples = \
            _banded_cbow_loss_and_grads(u_band, u_center, u_neg, pmask)
        emb_in = emb_in.at[band].add(-lr * g_band)
        emb_out = emb_out.at[centers].add(-lr * g_center)
        emb_out = emb_out.at[negs].add(-lr * g_neg)
        return emb_in, emb_out, loss, examples
    v = emb_in[centers]              # [C, D]
    u_band = emb_out[band]           # [C+2W, D]
    u_neg = emb_out[negs]            # [C//B, K, D]
    loss, g_v, g_band, g_neg = _banded_sgns_loss_and_grads(
        v, u_band, u_neg, pmask)
    emb_in = emb_in.at[centers].add(-lr * g_v)
    emb_out = emb_out.at[band].add(-lr * g_band)
    emb_out = emb_out.at[negs].add(-lr * g_neg)
    return emb_in, emb_out, loss, pmask.sum()


def _make_group(step, pad=None):
    """The scan driver shared by every device group program: carry the
    tables + PRNG key through G steps, sum losses/examples, return the
    advanced key, donate the table buffers. ``pad=(C, W)`` pads the
    kept stream for the banded steps at group entry (one ~24 MB fused
    copy per dispatch — the per-step slices then never clamp); the HS
    path passes None and consumes the stream unpadded."""

    def group(emb_in, emb_out, kept, ksent, aux1, aux2,
              key, bases, lrs, n_kept):
        if pad is not None:
            kept, ksent = _pad_stream(pad[0], pad[1], kept, ksent)

        def body(carry, xs):
            emb_in, emb_out, key = carry
            base, lr = xs
            key, sub = jax.random.split(key)
            emb_in, emb_out, loss, pairs = step(
                emb_in, emb_out, kept, ksent, aux1, aux2, sub, base,
                lr, n_kept)
            return (emb_in, emb_out, key), (loss, pairs)

        (emb_in, emb_out, key), (losses, pairs) = jax.lax.scan(
            body, (emb_in, emb_out, key), (bases, lrs))
        return emb_in, emb_out, losses.sum(), pairs.sum(), key

    return jax.jit(group, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=None)
def _group_fn_hs(C: int, W: int, n: int):
    """Hierarchical-softmax group: skip-gram over the context word's
    Huffman path — input = center row, outputs = the inner-node rows on
    ``points[ctx]``, labels ``1 - code`` (code 0 = positive, the
    word2vec convention; ref: wordembedding.cpp HS branch). The aux
    argument slots carry (points, codes) [V, L] (-1 padded) instead of
    the SGNS alias tables — same arity as ``_group_fn``, so the trainer
    drives either interchangeably."""

    def step(emb_in, emb_out, kept, ksent, points, codes,
             key, base, lr, n_kept):
        k_shrink, _ = jax.random.split(key)
        centers, ctx, pmask = _window(C, W, n, kept, ksent, k_shrink,
                                      base, n_kept)
        ctx_safe = jnp.clip(ctx, 0, points.shape[0] - 1)
        path = points[ctx_safe]          # [C, 2W, L]
        code = codes[ctx_safe]           # [C, 2W, L], -1 padded
        out_ids = jnp.maximum(path, 0)
        mask = ((path >= 0) & (code >= 0)).astype(jnp.float32) \
            * pmask[..., None]
        labels = (1.0 - code.astype(jnp.float32)) * mask
        v = emb_in[centers]              # [C, D]
        u = emb_out[out_ids]             # [C, 2W, L, D]

        def loss_fn(v, u):
            logits = jnp.clip(jnp.einsum("cd,cwld->cwl", v, u),
                              -_MAX_EXP, _MAX_EXP)
            return jnp.sum(_sigmoid_xent(logits, labels) * mask)

        loss, (g_v, g_u) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(v, u)
        emb_in = emb_in.at[centers].add(-lr * g_v)
        emb_out = emb_out.at[out_ids].add(-lr * g_u)
        return emb_in, emb_out, loss, pmask.sum()

    return _make_group(step)


# Module-level cache so every trainer instance with the same static
# shape (C, window, negative, corpus length, mode) shares one compiled
# group program — a warmup trainer's compile pays for the timed one.
@functools.lru_cache(maxsize=None)
def _group_fn(C: int, W: int, K: int, cbow: bool = False,
              neg_block: int = 1):
    def step(emb_in, emb_out, kept_pad, ksent_pad, neg_prob, neg_alias,
             key, base, lr, n_kept):
        return _apply_step(C, W, K, cbow, emb_in, emb_out, kept_pad,
                           ksent_pad, neg_prob, neg_alias, key, base,
                           lr, n_kept, neg_block=neg_block)

    return _make_group(step, pad=(C, W))


@functools.lru_cache(maxsize=None)
def _ma_group_fn(mesh, C: int, W: int, K: int, neg_block: int = 1):
    """Model-average (``-ma``) word2vec over a device mesh: each device
    scans G local SGNS steps against its own REPLICA of the embedding
    tables on its own CORPUS SHARD, then the replicas average with
    ``lax.pmean`` over ICI — the reference's MA mode (train locally,
    MV_Aggregate; ref: src/zoo.cpp:24,49, src/multiverso.cpp:53-56)
    with the aggregate riding XLA collectives inside one jitted step.

    Arguments of the returned jit (all as ONE global call):
    ``emb_in/emb_out`` replicated [V, D]; ``kept/ksent`` sharded
    [n_devices * n_local]; ``keys`` one PRNG key per device
    [n_devices, 2]; ``bases/lrs`` [G]; ``n_kept_local`` per-device kept
    counts [n_devices]. Returns (averaged tables, summed loss, summed
    pairs, advanced per-device keys) — feed the keys back when chaining
    dispatches or every group replays the same draws."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]

    def device_group(emb_in, emb_out, kept, ksent, neg_prob, neg_alias,
                     keys, bases, lrs, n_kept_local):
        key = keys[0]
        n_kept = n_kept_local[0]
        # The replicated tables DIVERGE per device once local training
        # starts — annotate them device-varying so the scan carry types
        # line up (pmean at the end collapses them back).
        try:
            pcast = functools.partial(jax.lax.pcast, to="varying")
        except AttributeError:  # older jax spells it pvary
            pcast = jax.lax.pvary
        emb_in = pcast(emb_in, axis)
        emb_out = pcast(emb_out, axis)
        # Pad each device's LOCAL stream for the banded slices (inside
        # shard_map, so this is a per-shard local op).
        kept_pad, ksent_pad = _pad_stream(C, W, kept, ksent)

        def body(carry, xs):
            emb_in, emb_out, key = carry
            base, lr = xs
            key, sub = jax.random.split(key)
            emb_in, emb_out, loss, pairs = _apply_step(
                C, W, K, False, emb_in, emb_out, kept_pad,
                ksent_pad, neg_prob, neg_alias, sub, base, lr, n_kept,
                neg_block=neg_block)
            return (emb_in, emb_out, key), (loss, pairs)

        (emb_in, emb_out, key), (losses, pairs) = jax.lax.scan(
            body, (emb_in, emb_out, key), (bases, lrs))
        # MV_Aggregate: average the trained replicas over the mesh.
        emb_in = jax.lax.pmean(emb_in, axis)
        emb_out = jax.lax.pmean(emb_out, axis)
        return (emb_in, emb_out, jax.lax.psum(losses.sum(), axis),
                jax.lax.psum(pairs.sum(), axis), key[None])

    mapped = shard_map(
        device_group, mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis), P(), P(),
                  P(axis), P(), P(), P(axis)),
        out_specs=(P(), P(), P(), P(), P(axis)))
    return jax.jit(mapped, donate_argnums=(0, 1))


class _CorpusOnDevice:
    """Shared upload of a ``TokenizedCorpus``: the flat id stream, its
    per-token sentence ids, and the subsample keep probabilities — one
    transfer, reused every epoch by both the local and the PS device
    trainers."""

    def __init__(self, model, tokenized: TokenizedCorpus):
        config = model.config
        flat = np.asarray(tokenized.flat, np.int32)
        lengths = np.diff(tokenized.offsets).astype(np.int64)
        sent = np.repeat(np.arange(lengths.size, dtype=np.int32), lengths)
        self.n_tokens = int(flat.size)
        self.flat = jnp.asarray(flat)
        self.sent = jnp.asarray(sent)
        self.keep = jnp.asarray(
            model.dictionary.subsample_keep_prob(config.sample))

    def prep_epoch(self, key):
        return _prep(self.flat, self.sent, self.keep, key)


class DeviceCorpusTrainer:
    """Drives a ``Word2Vec`` model's embeddings straight from a
    device-resident ``TokenizedCorpus``. Covers skip-gram negative
    sampling (the reference's default and the bench headline), CBOW
    negative sampling, and skip-gram hierarchical softmax; the CBOW+HS
    combination stays on the general host-batch path."""

    def __init__(self, model, tokenized: TokenizedCorpus,
                 centers_per_step: int = 32768,
                 steps_per_dispatch: int = 8):
        config = model.config
        if config.hs and config.cbow:
            raise ValueError("device corpus training covers skip-gram "
                             "HS; CBOW+HS stays on the batch path")
        self.model = model
        self.config = config
        self._C = int(centers_per_step)
        self._G = int(steps_per_dispatch)
        self._corpus = _CorpusOnDevice(model, tokenized)
        self._n_tokens = self._corpus.n_tokens
        if config.hs:
            # HS activations are [C, 2W, L, D] (L = max Huffman path,
            # ~log2 vocab) — orders of magnitude bigger per center than
            # SGNS. Cap C so u + its grad stay within ~1 GB; callers
            # can pass a smaller centers_per_step, larger is refused by
            # the cap rather than by an HBM OOM mid-epoch.

            path_len = max(int(model._points_host.shape[1]), 1)
            dim = int(config.embedding_size)
            budget = 1 << 30  # bytes for the gathered path rows
            cap = max(budget // (2 * config.window * path_len * dim * 4),
                      64)
            self._C = min(self._C, cap)
            self._group = _group_fn_hs(self._C, config.window,
                                       self._n_tokens)
            # aux slots: the Huffman path/code tables.
            self._aux = (model._points_dev, model._codes_dev)
        else:
            B = max(int(getattr(config, "neg_block", 1)), 1)
            if self._C % B:
                raise ValueError("neg_block must divide centers_per_step")
            self._group = _group_fn(self._C, config.window,
                                    config.negative, bool(config.cbow),
                                    B)
            self._aux = (model._neg_prob_dev, model._neg_alias_dev)
        # Post-subsampling tokens actually trained (centers), across
        # epochs — the exact basis for utilization accounting.
        self.kept_words_trained = 0

    def train_epoch(self, seed: int, group_hook=None,
                    max_steps: int = 0) -> Tuple[float, float]:
        """One full epoch on device. ``group_hook(words)`` is called
        after each dispatched group with the raw-word count it covered
        (bench timing); ``max_steps`` truncates the epoch (warmup).
        Returns (loss_sum, examples) as floats — fetched ONCE at epoch
        end. ``examples`` counts (center, context) pairs in skip-gram
        mode and trained centers in CBOW mode (one prediction per
        center)."""
        model, C, G = self.model, self._C, self._G
        key = jax.random.PRNGKey(seed)
        key, prep_key = jax.random.split(key)
        kept, ksent, n_kept_dev = self._corpus.prep_epoch(prep_key)
        n_kept = int(n_kept_dev)  # the one host fetch per epoch
        steps = max(math.ceil(n_kept / C), 1)
        if max_steps:
            steps = min(steps, max_steps)
        self.kept_words_trained += min(steps * C, n_kept)
        # lr schedule decays in RAW corpus words (subsample-dropped words
        # count, ref: distributed_wordembedding.cpp:92-134): spread the
        # epoch's raw words uniformly over its steps.
        raw_per_step = self._n_tokens / max(math.ceil(n_kept / C), 1)
        loss_acc = None
        pair_acc = None
        for g0 in range(0, steps, G):
            bases = np.full(G, n_kept, np.int32)  # padded steps: no-ops
            real = min(G, steps - g0)
            bases[:real] = (np.arange(g0, g0 + real) * C).astype(np.int32)
            lrs = np.zeros(G, np.float32)
            for i in range(real):
                lrs[i] = model.learning_rate()
                model.trained_words += raw_per_step
            (model._emb_in, model._emb_out, loss, pairs,
             key) = self._group(
                model._emb_in, model._emb_out, kept, ksent,
                self._aux[0], self._aux[1], key,
                jnp.asarray(bases), jnp.asarray(lrs), n_kept_dev)
            loss_acc = loss if loss_acc is None else loss_acc + loss
            pair_acc = pairs if pair_acc is None else pair_acc + pairs
            if group_hook is not None:
                group_hook(raw_per_step * real)
        return (0.0 if loss_acc is None else float(loss_acc),
                0.0 if pair_acc is None else float(pair_acc))


@functools.lru_cache(maxsize=None)
def _block_ids_fn(C: int, W: int, K: int, cbow: bool = False,
                  neg_block: int = 1):
    """Jitted block preparation for the PS pipeline: the INPUT-table id
    block, the OUTPUT-table id block (flat), and the pair validity mask
    — all device-resident, ready to hand to the tables as DEVICE keys.
    Takes the PADDED stream (pad once per epoch, not per step).
    Banded form: skip-gram in=centers [C],
    out=[band (C+2W) | negs (C//B*K)]; CBOW in=band [C+2W],
    out=[centers (C) | negs (C//B*K)]. The band replaces the [C, 2W]
    context id matrix — 2W-fold fewer pulled/pushed rows."""

    def ids(kept_pad, ksent_pad, neg_prob, neg_alias, key, base,
            n_kept):
        k_shrink, k_idx, k_keep = jax.random.split(key, 3)
        centers, band, pmask = _band_former(C, W, n_kept, kept_pad,
                                            ksent_pad, k_shrink, base)
        negs = _draw_negs(C, K, neg_block, neg_prob, neg_alias,
                          k_idx, k_keep)
        if cbow:
            return band, jnp.concatenate([centers, negs.reshape(-1)]), \
                pmask
        return centers, jnp.concatenate([band, negs.reshape(-1)]), pmask

    return jax.jit(ids)


@functools.lru_cache(maxsize=None)
def _block_step_fn(C: int, W: int, K: int, cbow: bool = False,
                   neg_block: int = 1):
    """Jitted PS block step over PULLED rows (banded layout from
    ``_block_ids_fn``): returns the PUSH deltas
    ``-lr*grad/num_workers`` (the reference's (new-old)/num_workers with
    one local step, ref: communicator.cpp:157-249) plus loss/examples."""
    nb = C // neg_block

    def step(v, u, pmask, lr_scaled):
        if cbow:
            # v = pulled INPUT band rows [C+2W, D]; u = pulled OUTPUT
            # [centers | negs] rows [C + nb*K, D].
            u_center = u[:C]
            u_neg = u[C:].reshape(nb, K, -1)
            loss, g_band, g_center, g_neg, examples = \
                _banded_cbow_loss_and_grads(v, u_center, u_neg, pmask)
            g_out = jnp.concatenate(
                [g_center, g_neg.reshape(nb * K, -1)])
            return -lr_scaled * g_band, -lr_scaled * g_out, loss, examples
        # v = pulled center rows [C, D]; u = [band | negs] rows.
        u_band = u[:C + 2 * W]
        u_neg = u[C + 2 * W:].reshape(nb, K, -1)
        loss, g_v, g_band, g_neg = _banded_sgns_loss_and_grads(
            v, u_band, u_neg, pmask)
        g_u = jnp.concatenate([g_band, g_neg.reshape(nb * K, -1)])
        return -lr_scaled * g_v, -lr_scaled * g_u, loss, pmask.sum()

    return jax.jit(step)


class PSDeviceCorpusTrainer:
    """The PS twin of ``DeviceCorpusTrainer``: same HBM-resident corpus
    pipeline, but the embeddings live in PARAMETER-SERVER matrix tables
    — every block pulls its rows through the full worker/server actor
    stack (device-key Gets), trains, and pushes ``-lr*grad/num_workers``
    deltas back (device-key Adds). Nothing but learning-rate scalars
    crosses the host boundary, which is what lets the PS path approach
    local-mode throughput in-process (the reference's block protocol,
    ref: Applications/WordEmbedding/src/communicator.cpp:117-249, with
    the row list living in HBM).

    Requires the in-process device path and a single server (device-key
    partition); the host-batch ``PSWord2Vec.train_batches`` remains the
    general path for cross-process / multi-server runs."""

    def __init__(self, model, tokenized: TokenizedCorpus,
                 centers_per_step: int = 32768):
        config = model.config
        if config.hs:
            raise ValueError("the PS device pipeline covers negative "
                             "sampling; hierarchical softmax uses the "
                             "host-batch PS path")
        if not getattr(model, "_device_path", False):
            raise ValueError("PS device pipeline needs in-process "
                             "servers (device path)")
        if model._in_table._num_server != 1:
            raise ValueError("PS device pipeline needs a single server "
                             "(device keys cannot partition)")
        self.model = model
        self.config = config
        self._C = int(centers_per_step)
        self._corpus = _CorpusOnDevice(model, tokenized)
        self._n_tokens = self._corpus.n_tokens
        if not hasattr(model, "_neg_prob_dev"):
            # PSWord2Vec keeps the alias tables host-side (its batch
            # path draws negatives on the host); this pipeline samples
            # in-jit, so upload them once.
            model._neg_prob_dev = jnp.asarray(model._neg_prob_host)
            model._neg_alias_dev = jnp.asarray(model._neg_alias_host)
        B = max(int(getattr(config, "neg_block", 1)), 1)
        if self._C % B:
            raise ValueError("neg_block must divide centers_per_step")
        self._ids = _block_ids_fn(self._C, config.window,
                                  config.negative, bool(config.cbow), B)
        self._pad = jax.jit(functools.partial(_pad_stream, self._C,
                                              config.window))
        self._step = _block_step_fn(self._C, config.window,
                                    config.negative, bool(config.cbow),
                                    B)
        self.kept_words_trained = 0

    def train_epoch(self, seed: int, block_hook=None,
                    max_steps: int = 0) -> Tuple[float, float]:
        """One epoch: per block, compute ids on device -> device-key
        pulls -> jitted step -> device-key delta pushes, all dispatched
        asynchronously (losses accumulate as device scalars; pushes are
        fire-and-forget until the trailing drain)."""
        model, C = self.model, self._C
        in_table, out_table = model._in_table, model._out_table
        key = jax.random.PRNGKey(seed)
        key, prep_key = jax.random.split(key)
        kept, ksent, n_kept_dev = self._corpus.prep_epoch(prep_key)
        # Pad ONCE per epoch; the per-step ids program then slices the
        # padded stream directly (padding per step would re-copy the
        # whole ~24 MB stream every block).
        kept_pad, ksent_pad = self._pad(kept, ksent)
        n_kept = int(n_kept_dev)
        steps = max(math.ceil(n_kept / C), 1)
        if max_steps:
            steps = min(steps, max_steps)
        self.kept_words_trained += min(steps * C, n_kept)
        raw_per_step = self._n_tokens / max(math.ceil(n_kept / C), 1)
        loss_acc = None
        pair_acc = None
        for s in range(steps):
            step_key = jax.random.fold_in(key, s)
            # in_ids: centers [C] (skip-gram) or the context window
            # block [C, 2W] (CBOW); out_ids: [ctx | negs] or
            # [center | negs] — see _block_ids_fn.
            in_ids, out_ids, pmask = self._ids(
                kept_pad, ksent_pad, model._neg_prob_dev,
                model._neg_alias_dev, step_key, np.int32(s * C),
                n_kept_dev)
            # Device-key pulls ride the worker->server actor round trip;
            # the replies are lazy device arrays (no host sync).
            mid_in = in_table.get_rows_device_async(in_ids)
            mid_out = out_table.get_rows_device_async(out_ids)
            in_table.wait(mid_in)
            out_table.wait(mid_out)
            v = in_table.take_device_rows()
            u = out_table.take_device_rows()
            lr_scaled = jnp.float32(
                model.learning_rate() / model._num_workers)
            d_v, d_u, loss, pairs = self._step(v, u, pmask, lr_scaled)
            # Fire-and-forget pushes: waiters self-reap on ack; the
            # trailing drain below bounds the epoch.
            model._pending_pushes.append(
                (in_table, in_table.add_rows_async(in_ids, d_v)))
            model._pending_pushes.append(
                (out_table, out_table.add_rows_async(out_ids, d_u)))
            model._account_words(raw_per_step)
            loss_acc = loss if loss_acc is None else loss_acc + loss
            pair_acc = pairs if pair_acc is None else pair_acc + pairs
            self.last_loss = loss  # device scalar; bench sync point
            if block_hook is not None:
                block_hook(raw_per_step)
        model._drain_pushes()
        model._flush_word_count()
        model._in_table.zoo.barrier()
        return (0.0 if loss_acc is None else float(loss_acc),
                0.0 if pair_acc is None else float(pair_acc))
