"""WordEmbedding application (ref: Applications/WordEmbedding)."""

from .data import (BlockLoader, CbowBatch, PairBatch, TokenizedCorpus,  # noqa: F401
                   iter_pair_batches, iter_sentences, sentence_pairs)
from .device_train import (DeviceCorpusTrainer,  # noqa: F401
                           PSDeviceCorpusTrainer)
from .dictionary import Dictionary  # noqa: F401
from .huffman import HuffmanTree, build_huffman  # noqa: F401
from .ma_train import MACorpusTrainer  # noqa: F401
from .model import PSWord2Vec, Word2Vec, Word2VecConfig  # noqa: F401
