"""WordEmbedding CLI: distributed word2vec trainer.

ref: Applications/WordEmbedding/src/main.cpp:16-28 and
distributed_wordembedding.cpp (epoch loop over blocks with a loader
thread; rank 0 saves embeddings after the last epoch). Flags use the
framework's -key=value convention, mirroring the reference's argv names.

Usage::

    python -m multiverso_tpu.models.wordembedding.main \
        -train_file=corpus.txt -output_file=vectors.txt -size=100 \
        -window=5 -negative=5 -epoch=1 [-cbow=true] [-hs=true] \
        [-use_ps=true] [-min_count=5] [-sample=1e-3] [-batch_size=4096]
"""

from __future__ import annotations

import sys
import time

from ... import init as mv_init, shutdown as mv_shutdown
from ...util import log
from ...util.configure import (define_bool, define_double, define_int,
                               define_string, get_flag, parse_cmd_flags)
from .data import BlockLoader, TokenizedCorpus, iter_pair_batches
from .device_train import DeviceCorpusTrainer, PSDeviceCorpusTrainer
from .dictionary import Dictionary
from .model import PSWord2Vec, Word2Vec, Word2VecConfig

define_string("train_file", "", "training corpus (';'-separated)")
define_string("output_file", "vectors.txt", "embedding output path")
define_string("vocab_file", "", "optional prebuilt vocab to load")
define_int("size", 100, "embedding dimension")
define_int("window", 5, "max context window")
define_int("negative", 5, "negative samples (0 with -hs)")
define_int("epoch", 1, "training epochs")
define_int("min_count", 5, "discard words rarer than this")
define_double("sample", 1e-3, "subsampling threshold")
define_double("init_learning_rate", 0.025, "initial learning rate")
define_bool("cbow", False, "CBOW instead of skip-gram")
define_bool("hs", False, "hierarchical softmax instead of negative "
                         "sampling")
define_bool("use_ps", False, "train through the parameter server")
define_int("batch_size", 4096, "pairs per jitted step")
define_int("neg_block", 1, "device pipelines: share one draw of K "
           "negatives across this many consecutive centers (1 = "
           "per-center draws; larger divides negative row traffic)")
define_bool("per_pair", False, "device pipelines, skip-gram: per-pair "
            "negatives + sequential window sub-steps (the reference's "
            "update structure; slower, reaches sequential-SGD quality)")
define_bool("is_pipeline", True, "overlap loading with training")
define_bool("device_pipeline", True, "train through the HBM-resident "
            "device pipeline (the fast path; -batch_size/-is_pipeline "
            "apply only to the host-batch loop); false = host-batch "
            "loop (the cross-process-capable form)")
define_string("stopwords", "", "optional stopwords file (one word per "
              "line) filtered out of the vocabulary — the reference "
              "reader's stopwords table (ref: Applications/WordEmbedding"
              "/src/reader.cpp, flag -stopwords)")


def run(argv=None) -> Word2Vec:
    parse_cmd_flags(list(argv) if argv is not None else sys.argv[1:])
    config = Word2VecConfig(
        embedding_size=get_flag("size"), window=get_flag("window"),
        negative=get_flag("negative"), epochs=get_flag("epoch"),
        min_count=get_flag("min_count"), sample=get_flag("sample"),
        init_learning_rate=get_flag("init_learning_rate"),
        cbow=get_flag("cbow"), hs=get_flag("hs"),
        batch_size=get_flag("batch_size"), use_ps=get_flag("use_ps"),
        neg_block=get_flag("neg_block"), per_pair=get_flag("per_pair"))
    train_file = get_flag("train_file")
    if not train_file:
        raise SystemExit("need -train_file=<corpus>")

    stopwords = None
    if get_flag("stopwords"):
        from ...io import TextReader
        stopwords = set()
        reader = TextReader(get_flag("stopwords"))
        while True:
            line = reader.get_line()
            if line is None:
                break
            word = line.strip()
            if word:
                stopwords.add(word)
        reader.close()
        log.info("loaded %d stopwords", len(stopwords))

    if get_flag("vocab_file"):
        dictionary = Dictionary.load(get_flag("vocab_file"))
    else:
        dictionary = Dictionary.build(train_file,
                                      min_count=config.min_count,
                                      stopwords=stopwords)
    log.info("vocab: %d words, %d tokens", dictionary.size,
             dictionary.total_count)

    if config.use_ps:
        mv_init([])
        model: Word2Vec = PSWord2Vec(config, dictionary)
    else:
        model = Word2Vec(config, dictionary)

    corpus = TokenizedCorpus.build(dictionary, train_file)
    # The DEVICE pipelines (corpus + windowing + sampling in HBM —
    # models/wordembedding/device_train.py) are the fast path for every
    # mode combination; -device_pipeline=false falls back to the
    # host-batch loop (the form that also runs cross-process, and the
    # only path for worker-only PS ranks whose servers live elsewhere).
    device_ok = not config.use_ps or getattr(model, "_device_path", False)
    use_device = get_flag("device_pipeline") and device_ok
    if use_device:
        log.info("training via the device pipeline "
                 "(-batch_size/-is_pipeline apply to the host-batch "
                 "loop only; -device_pipeline=false selects it)")
        trainer = (PSDeviceCorpusTrainer(model, corpus)
                   if config.use_ps else
                   DeviceCorpusTrainer(model, corpus))

        def train_one(epoch):
            return trainer.train_epoch(seed=config.seed + epoch)
    else:
        def train_one(epoch):
            batches = iter_pair_batches(
                dictionary, corpus, batch_size=config.batch_size,
                window=config.window, subsample=config.sample,
                cbow=config.cbow, seed=config.seed + epoch)
            # Row preparation runs in the loader thread (prepared()) so
            # it overlaps with device steps; the hot loop lives in the
            # model — local mode accumulates device losses without host
            # syncs, PS mode pipelines pull/train/push.
            iterator = BlockLoader(model.prepared(batches)) \
                if get_flag("is_pipeline") else batches
            return model.train_batches(iterator)

    start = time.perf_counter()
    for epoch in range(config.epochs):
        loss_sum, pair_count = train_one(epoch)
        elapsed = time.perf_counter() - start
        log.info("epoch %d: avg pair loss %.4f, %.0f words/s", epoch,
                 loss_sum / max(pair_count, 1),
                 model.trained_words / max(elapsed, 1e-9))

    should_save = not config.use_ps or model._in_table.zoo.rank == 0
    if should_save and get_flag("output_file"):
        model.save_embeddings(get_flag("output_file"))
    if config.use_ps:
        mv_shutdown()
    return model


if __name__ == "__main__":
    run()
