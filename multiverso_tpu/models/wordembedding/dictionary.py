"""Vocabulary: word counts, ids, subsampling.

TPU-native equivalent of the reference's ``Dictionary`` + preprocess
word-count pass (ref: Applications/WordEmbedding/src/dictionary.cpp,
preprocess/word_count.cpp): build from a corpus (or load a saved vocab),
filter by ``min_count``, and precompute word2vec subsample-keep
probabilities ``p(w) = (sqrt(f/t) + 1) * t/f`` and the unigram^0.75
negative-sampling distribution used by SGNS.
"""

from __future__ import annotations

import collections
from typing import Dict, Iterable, List, Optional

import numpy as np

from ...io import StreamFactory, TextReader


class Dictionary:
    def __init__(self) -> None:
        self.words: List[str] = []
        self.counts: np.ndarray = np.zeros(0, np.int64)
        self.word2id: Dict[str, int] = {}

    @property
    def size(self) -> int:
        return len(self.words)

    @property
    def total_count(self) -> int:
        return int(self.counts.sum())

    @classmethod
    def build(cls, corpus_path: str, min_count: int = 5,
              stopwords: Optional[set] = None) -> "Dictionary":
        counter: collections.Counter = collections.Counter()
        reader = TextReader(corpus_path)
        while True:
            line = reader.get_line()
            if line is None:
                break
            counter.update(line.split())
        reader.close()
        dictionary = cls()
        stopwords = stopwords or set()
        # Deterministic order: by count desc, then lexicographic — frequent
        # words get small ids (helps HBM locality of hot rows).
        items = sorted(((w, c) for w, c in counter.items()
                        if c >= min_count and w not in stopwords),
                       key=lambda kv: (-kv[1], kv[0]))
        dictionary.words = [w for w, _ in items]
        dictionary.counts = np.array([c for _, c in items], np.int64)
        dictionary.word2id = {w: i for i, w in enumerate(dictionary.words)}
        return dictionary

    def ids(self, tokens: Iterable[str]) -> List[int]:
        w2i = self.word2id
        return [w2i[t] for t in tokens if t in w2i]

    # -- word2vec sampling tables --
    def subsample_keep_prob(self, sample: float = 1e-3) -> np.ndarray:
        """Keep probability per word id (word2vec subsampling)."""
        if sample <= 0:
            return np.ones(self.size, np.float32)
        freq = self.counts / max(self.total_count, 1)
        ratio = sample / np.maximum(freq, 1e-12)
        return np.minimum((np.sqrt(ratio) + ratio), 1.0).astype(np.float32)

    def negative_table(self, power: float = 0.75) -> np.ndarray:
        """Unigram^power sampling distribution (probabilities per id)."""
        weighted = self.counts.astype(np.float64) ** power
        return (weighted / weighted.sum()).astype(np.float32)

    # -- persistence (reference saves vocab as "word count" lines) --
    def store(self, path: str) -> None:
        with StreamFactory.get_stream(path, "w") as stream:
            for word, count in zip(self.words, self.counts):
                stream.write(f"{word} {int(count)}\n".encode())

    @classmethod
    def load(cls, path: str) -> "Dictionary":
        dictionary = cls()
        reader = TextReader(path)
        words, counts = [], []
        while True:
            line = reader.get_line()
            if line is None:
                break
            if not line.strip():
                continue
            word, _, count = line.rpartition(" ")
            words.append(word)
            counts.append(int(count))
        reader.close()
        dictionary.words = words
        dictionary.counts = np.array(counts, np.int64)
        dictionary.word2id = {w: i for i, w in enumerate(words)}
        return dictionary
