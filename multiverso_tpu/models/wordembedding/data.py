"""Training-pair pipeline: sentences -> fixed-shape pair batches.

TPU-native re-design of the reference's Reader/DataBlock/BlockQueue
(ref: Applications/WordEmbedding/src/reader.cpp, data_block.cpp,
block_queue.cpp): a loader thread turns the corpus into fixed-shape
batches of (center, context) training pairs — subsampled, with the
word2vec shrinking-window trick — which is what a TPU step wants instead
of the reference's per-sentence scalar walk. Negative sampling happens
*inside* the jitted step (inverse-CDF over the unigram^0.75 distribution),
so batches carry only the pairs.

CBOW batches carry the padded context window per center instead of
exploded pairs (ref trains both modes, wordembedding.cpp).
"""

from __future__ import annotations

import queue as queue_mod
from typing import Iterator, List, Optional

import numpy as np

from ...io import TextReader
from ...runtime import thread_roles
from .dictionary import Dictionary

MAX_SENTENCE_LEN = 1000  # ref: constant MAX_SENTENCE_LENGTH


class PairBatch:
    """Skip-gram: (centers[B], contexts[B]); ``count`` = real pairs (rows
    beyond it are padding the train step masks out); ``words`` = corpus
    words (pre-subsampling) this batch consumed — the unit the lr schedule
    and words/sec decay in (pairs ≈ window x words, a different unit)."""

    __slots__ = ("centers", "contexts", "count", "words")

    def __init__(self, centers, contexts, count, words):
        self.centers = centers
        self.contexts = contexts
        self.count = count
        self.words = words


class CbowBatch:
    """CBOW: (window[B, 2W] padded with -1, centers[B]); see PairBatch for
    count/words semantics."""

    __slots__ = ("window", "centers", "count", "words")

    def __init__(self, window, centers, count, words):
        self.window = window
        self.centers = centers
        self.count = count
        self.words = words


class TokenizedCorpus:
    """One-pass tokenization cache: the corpus as a flat id array plus
    sentence offsets. Multi-epoch training re-reads ids (cheap numpy)
    instead of re-tokenizing text (Python dict lookups per token — the
    loader bottleneck). Subsampling stays per-epoch randomized."""

    def __init__(self, flat: np.ndarray, offsets: np.ndarray):
        self.flat = flat
        self.offsets = offsets  # [n_sentences + 1]

    @classmethod
    def build(cls, dictionary: Dictionary,
              corpus_path: str) -> "TokenizedCorpus":
        chunks: List[np.ndarray] = []
        lengths: List[int] = [0]
        for path in corpus_path.split(";"):
            reader = TextReader(path)
            while True:
                line = reader.get_line()
                if line is None:
                    break
                ids = dictionary.ids(line.split())
                if len(ids) >= 2:
                    chunks.append(np.asarray(ids[:MAX_SENTENCE_LEN],
                                             np.int32))
                    lengths.append(chunks[-1].size)
            reader.close()
        flat = np.concatenate(chunks) if chunks \
            else np.zeros(0, np.int32)
        return cls(flat, np.cumsum(lengths).astype(np.int64))

    def sentences(self) -> Iterator[np.ndarray]:
        for i in range(len(self.offsets) - 1):
            yield self.flat[self.offsets[i]:self.offsets[i + 1]]


def iter_sentences(dictionary: Dictionary, corpus,
                   subsample: float = 1e-3,
                   seed: int = 1) -> Iterator[Tuple[np.ndarray, int]]:
    """Yields (subsampled ids, raw word count). ``corpus`` is a path
    (tokenized on the fly) or TokenizedCorpus. The raw count is what the
    word2vec lr schedule decays in (it counts every word read, including
    subsample-discarded ones)."""
    keep = dictionary.subsample_keep_prob(subsample)
    rng = np.random.default_rng(seed)
    no_subsample = subsample <= 0

    def emit(ids: np.ndarray) -> Optional[np.ndarray]:
        if not no_subsample:
            ids = ids[rng.random(ids.size) < keep[ids]]
        return ids if ids.size >= 2 else None

    if isinstance(corpus, TokenizedCorpus):
        for ids in corpus.sentences():
            out = emit(ids)
            if out is not None:
                yield out, ids.size
        return
    for path in corpus.split(";"):
        reader = TextReader(path)
        while True:
            line = reader.get_line()
            if line is None:
                break
            ids = np.array(dictionary.ids(line.split()), np.int32)
            if ids.size:
                out = emit(ids[:MAX_SENTENCE_LEN])
                if out is not None:
                    yield out, min(ids.size, MAX_SENTENCE_LEN)
        reader.close()


def iter_pair_batches(dictionary: Dictionary, corpus_path,
                      batch_size: int = 4096, window: int = 5,
                      subsample: float = 1e-3, cbow: bool = False,
                      seed: int = 1,
                      chunk_words: int = 16384) -> Iterator:
    """Walk sentences emitting fixed-shape batches; the per-center window
    size shrinks uniformly in [1, window] (the word2vec trick,
    ref: wordembedding.cpp Train window sampling).

    Sentences are expanded to pairs in multi-sentence CHUNKS
    (``chunk_sentence_pairs``): per-sentence numpy calls are the loader
    bottleneck at scale — one vectorized call per ~16K words instead of
    one per ~40-word sentence keeps the loader ahead of the device."""
    rng = np.random.default_rng(seed + 7)
    if cbow:
        yield from _iter_cbow(dictionary, corpus_path, batch_size, window,
                              subsample, rng, seed)
        return
    # Pending pairs carry a per-pair fractional word weight so each batch
    # reports exactly the corpus words it consumed (a sentence's raw words
    # spread over its pairs; sums are exact across batch boundaries).
    pending: List[np.ndarray] = []  # [3, k]: center, context, word-frac
    pending_count = 0
    chunk: List[np.ndarray] = []
    chunk_raw: List[int] = []
    chunk_n = 0

    def flush_chunk():
        nonlocal pending, pending_count, chunk, chunk_raw, chunk_n
        if not chunk:
            return
        pairs, sent_of_pair = chunk_sentence_pairs(chunk, window, rng)
        if pairs.shape[1]:
            # Per-sentence raw words spread over that sentence's pairs.
            per_sent = np.bincount(sent_of_pair, minlength=len(chunk))
            raw = np.asarray(chunk_raw, np.float64)
            frac = (raw / np.maximum(per_sent, 1))[sent_of_pair]
            pending.append(np.concatenate([pairs.astype(np.float64),
                                           frac[None, :]]))
            pending_count += pairs.shape[1]
        chunk, chunk_raw, chunk_n = [], [], 0

    def drain_full_batches():
        nonlocal pending, pending_count
        while pending_count >= batch_size:
            flat = np.concatenate(pending, axis=1)
            yield PairBatch(flat[0, :batch_size].astype(np.int32),
                            flat[1, :batch_size].astype(np.int32),
                            batch_size,
                            float(flat[2, :batch_size].sum()))
            rest = flat[:, batch_size:]
            pending = [rest] if rest.shape[1] else []
            pending_count = rest.shape[1]

    for ids, raw_words in iter_sentences(dictionary, corpus_path,
                                         subsample, seed):
        chunk.append(ids)
        chunk_raw.append(raw_words)
        chunk_n += ids.size
        if chunk_n < chunk_words:
            continue
        flush_chunk()
        yield from drain_full_batches()
    flush_chunk()
    yield from drain_full_batches()
    if pending_count:
        flat = np.concatenate(pending, axis=1)
        centers = np.zeros(batch_size, np.int32)
        contexts = np.zeros(batch_size, np.int32)
        centers[:pending_count] = flat[0].astype(np.int32)
        contexts[:pending_count] = flat[1].astype(np.int32)
        yield PairBatch(centers, contexts, pending_count,
                        float(flat[2].sum()))


def chunk_sentence_pairs(ids_list: List[np.ndarray], window: int,
                         rng: np.random.Generator):
    """Vectorized (center, context) expansion for MANY sentences at once:
    the sentences concatenate into one flat array with a per-position
    sentence id; a context position is valid when it stays inside the
    flat array, inside the SAME sentence, and within the center's shrunk
    window. Returns (int32 pairs [2, k], sentence index per pair [k])."""
    flat = np.concatenate(ids_list)
    n = flat.size
    if n == 0:
        return np.zeros((2, 0), np.int32), np.zeros(0, np.int64)
    lengths = np.fromiter((a.size for a in ids_list), np.int64,
                          count=len(ids_list))
    sent_id = np.repeat(np.arange(len(ids_list)), lengths)
    shrink = rng.integers(1, window + 1, size=n)
    offsets = np.concatenate([np.arange(-window, 0),
                              np.arange(1, window + 1)])
    pos = np.arange(n)[:, None] + offsets[None, :]  # [n, 2w]
    inside = (pos >= 0) & (pos < n)
    pos_c = np.clip(pos, 0, n - 1)
    valid = inside & (np.abs(offsets)[None, :] <= shrink[:, None]) \
        & (sent_id[pos_c] == sent_id[:, None])
    center_idx, off_idx = np.nonzero(valid)
    pairs = np.stack([flat[center_idx],
                      flat[pos_c[center_idx, off_idx]]]).astype(np.int32)
    return pairs, sent_id[center_idx]


def sentence_pairs(ids: np.ndarray, window: int,
                   rng: np.random.Generator) -> np.ndarray:
    """Vectorized (center, context) expansion for one sentence: offsets
    -window..window per position, masked by the per-center shrunk window
    and sentence bounds. Returns int32 [2, k]."""
    n = ids.size
    shrink = rng.integers(1, window + 1, size=n)
    offsets = np.concatenate([np.arange(-window, 0),
                              np.arange(1, window + 1)])
    pos = np.arange(n)[:, None] + offsets[None, :]  # [n, 2w]
    valid = (np.abs(offsets)[None, :] <= shrink[:, None]) \
        & (pos >= 0) & (pos < n)
    center_idx, off_idx = np.nonzero(valid)
    return np.stack([ids[center_idx],
                     ids[pos[center_idx, off_idx]]]).astype(np.int32)


def _iter_cbow(dictionary, corpus_path, batch_size, window, subsample,
               rng, seed) -> Iterator[CbowBatch]:
    width = 2 * window
    win = np.full((batch_size, width), -1, np.int32)
    centers = np.empty(batch_size, np.int32)
    word_fracs = np.zeros(batch_size)
    fill = 0
    for ids, raw_words in iter_sentences(dictionary, corpus_path,
                                         subsample, seed):
        n = ids.size
        shrink = rng.integers(1, window + 1, size=n)
        frac = raw_words / n
        for i in range(n):
            b = shrink[i]
            ctx = np.concatenate([ids[max(0, i - b):i],
                                  ids[i + 1:min(n, i + b + 1)]])
            if ctx.size == 0:
                continue
            win[fill, :] = -1
            win[fill, :ctx.size] = ctx[:width]
            centers[fill] = ids[i]
            word_fracs[fill] = frac
            fill += 1
            if fill == batch_size:
                yield CbowBatch(win.copy(), centers.copy(), batch_size,
                                float(word_fracs.sum()))
                fill = 0
                word_fracs[:] = 0
    if fill:
        win[fill:] = -1
        centers[fill:] = 0
        yield CbowBatch(win.copy(), centers.copy(), fill,
                        float(word_fracs[:fill].sum()))


class BlockLoader:
    """Background loader thread + bounded queue (the reference's
    BlockQueue + loader thread, ref: distributed_wordembedding.cpp:33-56)."""

    def __init__(self, batch_iter: Iterator, depth: int = 8):
        self._queue: "queue_mod.Queue" = queue_mod.Queue(maxsize=depth)
        self._thread = thread_roles.spawn(
            thread_roles.BACKGROUND, target=self._fill,
            args=(batch_iter,), name="mv-we-blockloader")

    def _fill(self, batch_iter) -> None:
        try:
            for batch in batch_iter:
                self._queue.put(batch)
        finally:
            self._queue.put(None)

    def __iter__(self):
        while True:
            batch = self._queue.get()
            if batch is None:
                return
            yield batch
