"""Huffman tree for hierarchical softmax.

TPU-native equivalent of the reference's ``HuffmanEncoder``
(ref: Applications/WordEmbedding/src/huffman_encoder.cpp): builds the
frequency-ordered binary tree and emits, per word, its code (left/right
bits) and point list (inner-node ids). Re-designed for batched TPU
consumption: codes/points are returned as dense ``[vocab, max_code_len]``
arrays padded with -1, ready for fixed-shape gather + mask inside one
jitted HS step instead of the reference's per-node scalar loop.
"""

from __future__ import annotations

import heapq
from typing import Tuple

import numpy as np


class HuffmanTree:
    def __init__(self, codes: np.ndarray, points: np.ndarray,
                 code_lengths: np.ndarray):
        self.codes = codes  # [vocab, L] 0/1, -1 pad
        self.points = points  # [vocab, L] inner node ids, -1 pad
        self.code_lengths = code_lengths  # [vocab]

    @property
    def max_code_length(self) -> int:
        return self.codes.shape[1]

    @property
    def num_inner_nodes(self) -> int:
        return int(self.points.max()) + 1 if self.points.size else 0


def build_huffman(counts: np.ndarray) -> HuffmanTree:
    """Standard Huffman construction over word frequencies."""
    vocab = len(counts)
    if vocab == 0:
        return HuffmanTree(np.zeros((0, 0), np.int32),
                           np.zeros((0, 0), np.int32),
                           np.zeros(0, np.int32))
    # Heap of (count, tiebreak, node). Leaves are 0..vocab-1; inner nodes
    # get ids vocab..2*vocab-2, renumbered to 0-based inner ids at the end.
    heap = [(int(c), i, i) for i, c in enumerate(counts)]
    heapq.heapify(heap)
    next_id = vocab
    parent = {}
    side = {}
    while len(heap) > 1:
        c1, _, n1 = heapq.heappop(heap)
        c2, _, n2 = heapq.heappop(heap)
        parent[n1], side[n1] = next_id, 0
        parent[n2], side[n2] = next_id, 1
        heapq.heappush(heap, (c1 + c2, next_id, next_id))
        next_id += 1
    root = heap[0][2]

    codes_list, points_list = [], []
    for leaf in range(vocab):
        code, points = [], []
        node = leaf
        while node != root:
            code.append(side[node])
            points.append(parent[node] - vocab)  # 0-based inner node id
            node = parent[node]
        codes_list.append(code[::-1])
        points_list.append(points[::-1])

    max_len = max((len(c) for c in codes_list), default=0)
    codes = np.full((vocab, max_len), -1, np.int32)
    points = np.full((vocab, max_len), -1, np.int32)
    lengths = np.zeros(vocab, np.int32)
    for i, (code, point) in enumerate(zip(codes_list, points_list)):
        lengths[i] = len(code)
        codes[i, :len(code)] = code
        points[i, :len(point)] = point
    return HuffmanTree(codes, points, lengths)


def expected_code_length(tree: HuffmanTree,
                         counts: np.ndarray) -> float:
    freq = counts / max(counts.sum(), 1)
    return float((tree.code_lengths * freq).sum())
