"""Cross-rank model-average word2vec: the ``-ma`` training path.

The reference's ``-ma`` mode trains each rank's full table replica
locally and periodically calls ``MV_Aggregate`` on the parameter buffer
(ref: src/zoo.cpp:49, Test/test_allreduce.cpp:10-19). ``MACorpusTrainer``
is the flagship wiring of that loop on top of the device corpus
pipeline:

- each rank runs its own ``DeviceCorpusTrainer`` over its corpus shard
  (device compute, banded steps);
- every ``avg_every`` dispatched groups the host-fetched embedding
  tables are model-averaged across ranks over the control transport
  (chunked ring allreduce, runtime/allreduce_engine.py);
- with ``overlap=True`` the averager double-buffers: the allreduce of
  snapshot i streams chunk-by-chunk on the transport writer threads
  while groups i+1 compute on device, and the collected average is
  corrected by the local progress made meanwhile (``MAAverager``
  semantics). Sync and overlapped runs apply the SAME update at the
  SAME point — bit-identical trajectories when ``-allreduce_lossy`` is
  off; only the ``MA_COMM_STALL`` wall time differs, which is exactly
  what the bench compares.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from ...parallel.ma import MAAverager, MAShardedAverager
from .device_train import DeviceCorpusTrainer, TokenizedCorpus


class MACorpusTrainer:
    """Model-average wrapper around :class:`DeviceCorpusTrainer`.

    All ranks must construct their model with the same config seed (MA
    assumes replicas start identical) and call ``train_epoch`` the same
    number of times with the same group counts — the averages are
    matched positionally across ranks, like every collective.

    ``sharded=True`` switches to delta-vs-last-average MA over the
    sharded sparse collective (:class:`MAShardedAverager`): each round
    ships only the parameters' change since the last average — sparse
    once training localizes — through a reduce-scatter of codec sparse
    frames, a shard-local divide, and an allgather. The submit/collect
    call points are identical, so sync and overlapped sharded runs stay
    bit-identical to each other exactly like the dense mode's."""

    def __init__(self, model, tokenized: TokenizedCorpus,
                 avg_every: int = 4, overlap: bool = True, zoo=None,
                 sharded: bool = False, **trainer_kw):
        self.model = model
        self.avg_every = max(1, int(avg_every))
        self.overlap = bool(overlap)
        self.sharded = bool(sharded)
        self._inner = DeviceCorpusTrainer(model, tokenized, **trainer_kw)
        self._averager = MAShardedAverager(zoo) if self.sharded \
            else MAAverager(zoo)
        self.comm_rounds = 0

    # -- host <-> device parameter shuttling --
    def _params_host(self) -> np.ndarray:
        """One flat float32 buffer [emb_in | emb_out] — the shape the
        allreduce engine chunks. The fetch blocks on outstanding device
        work, which is the natural overlap boundary: everything
        dispatched since ``submit`` ran while the previous average was
        streaming."""
        m = self.model
        return np.concatenate([np.asarray(m._emb_in).ravel(),
                               np.asarray(m._emb_out).ravel()])

    def _apply(self, flat: np.ndarray) -> None:
        m = self.model
        n_in = m._emb_in.size
        m._emb_in = jnp.asarray(
            flat[:n_in].reshape(m._emb_in.shape), jnp.float32)
        m._emb_out = jnp.asarray(
            flat[n_in:].reshape(m._emb_out.shape), jnp.float32)

    def _average_point(self) -> None:
        now = self._params_host()
        if self._averager.busy:
            # avg_i + (now - snapshot_i): cross-rank average of block i
            # plus the local progress made while it streamed.
            now = self._averager.collect(current=now)
            self._apply(now)
        future = self._averager.submit(now)
        if not self.overlap:
            # Sync mode: pay the whole collective as a stall right here.
            # The RESULT is applied at the same later point as in
            # overlap mode, so the trajectories stay bit-identical.
            future.wait()
        self.comm_rounds += 1

    def train_epoch(self, seed: int, group_hook=None, max_steps: int = 0,
                    group_quota: int = 0) -> Tuple[float, float]:
        """One local epoch with cross-rank averaging every ``avg_every``
        groups. Collectives are matched positionally, so EVERY rank must
        reach the same averaging points: with equal corpus shards the
        group counts line up naturally; with UNEVEN shards pass
        ``group_quota`` = the LARGEST rank's groups-per-epoch — ranks
        whose local epoch ends early keep joining the remaining averages
        with their (finished) parameters instead of leaving the longer
        ranks' collectives hanging until the allreduce timeout."""
        groups = 0

        def hook(words: float) -> None:
            nonlocal groups
            groups += 1
            if groups % self.avg_every == 0:
                self._average_point()
            if group_hook is not None:
                group_hook(words)

        out = self._inner.train_epoch(seed, group_hook=hook,
                                      max_steps=max_steps)
        while groups < group_quota:
            groups += 1
            if groups % self.avg_every == 0:
                self._average_point()
        return out

    def finish(self) -> None:
        """Fold the in-flight average in (call once after the last
        epoch; otherwise the final local block never merges)."""
        if self._averager.busy:
            self._apply(self._averager.collect(
                current=self._params_host()))

    @property
    def kept_words_trained(self) -> int:
        return self._inner.kept_words_trained
