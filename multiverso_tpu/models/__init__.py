"""Applications (the reference's Applications/ directory)."""
