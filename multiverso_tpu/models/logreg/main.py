"""LogisticRegression CLI: train + test from a config file.

ref: Applications/LogisticRegression/src/main.cpp:7-13 (config-file driven)
and src/logreg.cpp:41-173 (epoch loop with periodic loss display; test
writes predictions through the Stream layer).

Usage: ``python -m multiverso_tpu.models.logreg.main <config-file>``
"""

from __future__ import annotations

import sys
import time

import numpy as np

from ... import init as mv_init, shutdown as mv_shutdown
from ...io import StreamFactory
from ...util import log
from .config import Configure
from .model import create_model
from .reader import PrefetchReader, make_batches, iter_samples


class LogReg:
    """ref: src/logreg.{h,cpp}."""

    def __init__(self, config_path: str):
        self.config = Configure.from_file(config_path)
        if self.config.use_ps:
            mv_init([])
        self.model = create_model(self.config)
        if self.config.init_model_file:
            with StreamFactory.get_stream(self.config.init_model_file,
                                          "r") as stream:
                self.model.load(stream)

    # ref: logreg.cpp:41-87
    def train(self) -> float:
        config = self.config
        last_loss = 0.0
        for epoch in range(config.train_epoch):
            sample_count, loss_sum = 0, 0.0
            shown = 0
            start = time.perf_counter()
            for batch in PrefetchReader(config, config.train_file):
                loss_sum += self.model.update(batch)
                sample_count += batch.count
                if sample_count - shown >= config.show_time_per_sample:
                    log.info("epoch %d: %d samples, avg loss %.6f, "
                             "%.0f samples/s", epoch, sample_count,
                             loss_sum / sample_count,
                             sample_count / (time.perf_counter() - start))
                    shown = sample_count
            last_loss = loss_sum / max(sample_count, 1)
            log.info("epoch %d done: %d samples, avg train loss %.6f",
                     epoch, sample_count, last_loss)
        if config.output_model_file:
            with StreamFactory.get_stream(config.output_model_file,
                                          "w") as stream:
                self.model.store(stream)
        return last_loss

    # ref: logreg.cpp:121-173
    def test(self) -> float:
        config = self.config
        if not config.test_file:
            return 0.0
        correct, total = 0, 0
        out_stream = StreamFactory.get_stream(config.output_file, "w") \
            if config.output_file else None
        for batch in make_batches(config,
                                  iter_samples(config, config.test_file)):
            pred = self.model.predict(batch)[:batch.count]
            labels = batch.labels[:batch.count]
            if pred.shape[1] == 1:
                hits = (pred[:, 0] >= 0.5).astype(np.int32) == labels
            else:
                hits = pred.argmax(axis=1).astype(np.int32) == labels
            correct += int(hits.sum())
            total += batch.count
            if out_stream is not None:
                lines = "\n".join(
                    " ".join(f"{v:.6f}" for v in row) for row in pred)
                out_stream.write((lines + "\n").encode())
        if out_stream is not None:
            out_stream.close()
        accuracy = correct / max(total, 1)
        log.info("test: %d/%d correct (%.4f)", correct, total, accuracy)
        return accuracy

    def close(self) -> None:
        if self.config.use_ps:
            mv_shutdown()


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print("usage: python -m multiverso_tpu.models.logreg.main "
              "<config-file>", file=sys.stderr)
        return 2
    app = LogReg(argv[0])
    app.train()
    app.test()
    app.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
